"""``tmpi report``: one unified post-mortem report over an obs dir.

The obs dir accumulates a dozen record kinds across per-rank streams
(metrics, numerics, supervisor, fleet, serve, spans, stall files);
after an incident every one of them holds a piece of the story and no
single ``grep`` shows causality. This tool tails ALL of them (through
the same byte-offset reader the exporter uses), merges every record
into one monotonic event timeline with file:line provenance, groups
events causally — a ``kind=retry`` record *adopts* the anomaly /
reshard / rollback / corrupt-scrub / stall / drift-breach records that
preceded it (they are its cause chain), leftovers stand alone — and
renders:

- a run summary (ranks, steps, events, retries, fleet health),
- the incident list, each incident citing its evidence records,
- the merged event timeline (notable kinds; routine cadence records
  are counted, not listed),
- a per-phase wall breakdown rolled up from ``kind=span_summary``,
- the model-drift trajectory (``kind=drift`` EWMA errors + breaches),
- straggler/frozen verdicts from the fleet stream, annotated with the
  step ranges they covered,
- a final verdict — ``completed`` / ``degraded`` / ``halted`` — with
  the evidence lines that forced it.

Usage::

    tmpi report OBS_DIR                  # markdown to stdout
    tmpi report OBS_DIR --out report.md  # or report.html by extension
    tmpi report OBS_DIR --json           # one kind=report object
                                         # (schema: check_obs_schema)

Read-only by construction, like ``tmpi top``: the tailer runs with
``write_records=False`` and nothing here opens a file for writing
except ``--out``. Deliberately byte-deterministic for a finished dir —
no wall-clock stamp rides the body, so two invocations diff clean
(tests/test_lint_all.py budgets and diffs exactly that).
"""

from __future__ import annotations

import argparse
import glob
import html as _html
import json
import os
import sys
from typing import Optional

from theanompi_tpu.obs.fleet import FleetTailer, fleet_topology

# record kinds rendered individually in the timeline; everything else
# (per-step cadence records) is summarized as counts to keep a long
# run's report readable
NOTABLE_KINDS = (
    "anomaly", "retry", "reshard", "rollback", "scrub", "stall",
    "drift", "topology", "preflight", "reload", "shard", "router",
)
# kinds a subsequent retry adopts as its cause chain (scrub only when
# it actually found corruption; drift only when it breached tolerance)
_ADOPTABLE = ("anomaly", "reshard", "rollback", "scrub", "stall", "drift")


def _iter_jsonl(path: str):
    """Yield ``(line_no, record)`` for every well-formed object line.

    Torn tail lines (a rank killed mid-write) parse as garbage and are
    skipped, same stance as the fleet tailer."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("kind"):
                    yield i, rec
    except OSError:
        return


def _scan_events(obs_dir: str) -> list:
    """Every record in the dir as ``{"t","rank","kind","step","src",
    "rec"}``, sorted into ONE monotonic timeline. ``src`` is
    ``file:line`` — the citation format every downstream section uses.
    Sort key includes src so equal timestamps stay deterministic."""
    events = []
    names = sorted(
        n for n in os.listdir(obs_dir)
        if n.endswith(".jsonl") and
        os.path.isfile(os.path.join(obs_dir, n))
    ) if os.path.isdir(obs_dir) else []
    for name in names:
        for line_no, rec in _iter_jsonl(os.path.join(obs_dir, name)):
            events.append({
                # span records carry t0, not t — fall through so span
                # summaries land where they happened on the timeline
                "t": float(rec.get("t") or rec.get("t0") or 0.0),
                "rank": int(rec.get("rank") or 0),
                "kind": str(rec.get("kind")),
                "step": rec.get("step"),
                "src": f"{name}:{line_no}",
                "rec": rec,
            })
    # stall verdict files are single JSON objects, not JSONL streams
    for path in sorted(glob.glob(os.path.join(obs_dir, "stall_rank*.json"))):
        try:
            with open(path, "r", encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict):
            events.append({
                "t": float(rec.get("t") or 0.0),
                "rank": int(rec.get("rank") or 0),
                "kind": "stall",
                "step": rec.get("step"),
                "src": f"{os.path.basename(path)}:1",
                "rec": rec,
            })
    events.sort(key=lambda e: (e["t"], e["rank"], e["kind"], e["src"]))
    return events


def _describe(ev: dict) -> str:
    """One human line per record — what a teammate would say about it."""
    r, kind = ev["rec"], ev["kind"]
    if kind == "retry":
        return (f"rank {ev['rank']} retry attempt {r.get('attempt')} "
                f"from step {r.get('step')}: {r.get('error')!r}"
                + (f" (cause: {r['cause']})" if r.get("cause") else ""))
    if kind == "anomaly":
        return (f"rank {ev['rank']} anomaly {r.get('metric')} "
                f"({r.get('reason')}) policy={r.get('policy', 'record')}")
    if kind == "reshard":
        return (f"reshard {r.get('from_world')}→{r.get('to_world')} ranks "
                f"at step {r.get('step')} in {r.get('seconds'):.2f}s"
                if isinstance(r.get("seconds"), (int, float)) else
                f"reshard {r.get('from_world')}→{r.get('to_world')} ranks")
    if kind == "rollback":
        return (f"rank {ev['rank']} rollback step {r.get('step')}→"
                f"{r.get('restore_step')} (budget left "
                f"{r.get('budget_left')})")
    if kind == "scrub":
        return (f"scrub: {r.get('corrupt')}/{r.get('checked')} corrupt, "
                f"quarantined [{r.get('quarantined')}]")
    if kind == "stall":
        return (f"rank {ev['rank']} STALL at step {r.get('step')}: "
                f"{r.get('stall_s')}s past {r.get('timeout_s')}s timeout")
    if kind == "drift":
        errs = ", ".join(
            f"{s}={r[f'model_err_{s}']:.3f}"
            for s in ("cost", "traffic", "memory")
            if isinstance(r.get(f"model_err_{s}"), (int, float)))
        breached = r.get("breached") or ""
        return (f"model drift [{errs}]"
                + (f" BREACHED: {breached}" if breached else ""))
    if kind == "topology":
        return f"topology: {r.get('world', r.get('ranks', '?'))} ranks"
    if kind == "preflight":
        return f"preflight peak {r.get('peak_bytes')} bytes"
    if kind == "reload":
        return f"serve hot-reload step {r.get('from_step')}→{r.get('to_step')}"
    if kind == "router":
        name, rid = r.get("event"), r.get("replica_id")
        if name == "health":
            msg = (f"replica {rid} {r.get('from_state')}→"
                   f"{r.get('to_state')}")
            return msg + (f": {r['error']}" if r.get("error") else "")
        if name == "failover":
            return (f"failover: in-flight request re-admitted from "
                    f"replica {rid} to replica {r.get('to_replica')}")
        if name == "restart":
            return (f"replica {rid} restarted after "
                    f"{r.get('backoff_s')}s backoff (replica lost, "
                    "traffic absorbed by survivors)")
        if name == "restart_failed":
            return f"replica {rid} restart FAILED: {r.get('error')}"
        if name == "drop":
            return (f"request DROPPED on replica {rid}: "
                    f"{r.get('error')}")
        return f"router {name}"
    if kind == "shard":
        return f"sharding lint: {r.get('verdict', r.get('status', 'ran'))}"
    return kind


def _is_adoptable(ev: dict) -> bool:
    k = ev["kind"]
    if k not in _ADOPTABLE:
        return False
    if k == "scrub":
        return bool(ev["rec"].get("corrupt"))
    if k == "drift":
        return bool(ev["rec"].get("breached"))
    return True


def _is_router_adoptable(ev: dict) -> bool:
    """Serving events a later replica RESTART adopts as its cause
    chain: the health transition that took the member down, the
    failovers that re-homed its in-flight requests, any dropped
    request, and failed restart attempts along the way."""
    if ev["kind"] != "router":
        return False
    name = ev["rec"].get("event")
    if name == "health":
        return ev["rec"].get("to_state") == "down"
    return name in ("failover", "drop", "restart_failed")


def _group_incidents(events: list) -> list:
    """Causal grouping: walking the merged timeline in order, adoptable
    events accumulate as pending evidence; the next ``retry`` record
    adopts ALL of them as its cause chain (the crash/anomaly/reshard
    that preceded a restart explains it). Serving events group the same
    way on their own track: a router ``restart`` adopts the crash /
    failover / drop records that preceded it (training evidence never
    crosses into a serving incident or vice versa). Pending events that
    no adopter ever claims become standalone incidents — real, just
    not fatal."""
    incidents, pending, pending_serve = [], [], []
    for ev in events:
        if ev["kind"] == "retry":
            incidents.append({
                "kind": "retry",
                "t": ev["t"],
                "rank": ev["rank"],
                "step": ev["rec"].get("step"),
                "what": _describe(ev),
                "src": ev["src"],
                "evidence": [
                    {"src": p["src"], "kind": p["kind"],
                     "what": _describe(p)} for p in pending
                ],
            })
            pending = []
        elif (ev["kind"] == "router"
              and ev["rec"].get("event") == "restart"):
            incidents.append({
                "kind": "replica_restart",
                "t": ev["t"],
                "rank": ev["rank"],
                "step": ev["rec"].get("step"),
                "what": _describe(ev),
                "src": ev["src"],
                "evidence": [
                    {"src": p["src"], "kind": p["kind"],
                     "what": _describe(p)} for p in pending_serve
                ],
            })
            pending_serve = []
        elif _is_adoptable(ev):
            pending.append(ev)
        elif _is_router_adoptable(ev):
            pending_serve.append(ev)
    leftovers = sorted(pending + pending_serve,
                       key=lambda e: (e["t"], e["rank"], e["src"]))
    for ev in leftovers:
        incidents.append({
            "kind": ev["kind"],
            "t": ev["t"],
            "rank": ev["rank"],
            "step": ev["rec"].get("step"),
            "what": _describe(ev),
            "src": ev["src"],
            "evidence": [],
        })
    return incidents


def _phase_breakdown(events: list) -> dict:
    """Roll every rank's ``kind=span_summary`` records into one
    per-phase wall table: total exclusive seconds per span kind across
    the run, plus the share of summed wall they represent."""
    totals, wall = {}, 0.0
    for ev in events:
        if ev["kind"] != "span_summary":
            continue
        r = ev["rec"]
        wall += float(r.get("wall_s") or 0.0)
        for k, v in (r.get("totals_s") or {}).items():
            if isinstance(v, (int, float)):
                totals[str(k)] = totals.get(str(k), 0.0) + float(v)
    if not totals or wall <= 0:
        return {}
    phases = {k: {"seconds": round(v, 6), "frac": round(v / wall, 6)}
              for k, v in sorted(totals.items())}
    phases["_wall_s"] = round(wall, 6)
    return phases


def _drift_trajectory(events: list) -> dict:
    """The ``kind=drift`` stream condensed: last + worst EWMA error per
    model, and the steps where the watchdog declared a breach."""
    rows, breaches = [], []
    last, worst = {}, {}
    for ev in events:
        if ev["kind"] != "drift":
            continue
        r = ev["rec"]
        row = {"step": r.get("step")}
        for s in ("cost", "traffic", "memory"):
            v = r.get(f"model_err_{s}")
            if isinstance(v, (int, float)):
                row[s] = v
                last[f"model_err_{s}"] = v
                # max with a self-default so an all-zero error series
                # still lands in worst (last/worst carry the same keys)
                worst[f"model_err_{s}"] = max(
                    worst.get(f"model_err_{s}", v), v)
        rows.append(row)
        if r.get("breached"):
            breaches.append({"step": r.get("step"), "src": ev["src"],
                             "breached": r["breached"]})
    if not rows:
        return {}
    return {"last": last, "worst": worst, "breaches": breaches,
            "n_records": len(rows)}


def _straggler_annotations(events: list) -> list:
    """Fleet-stream straggler/frozen verdicts as step-range
    annotations: "rank R flagged straggler over steps A–B", citing the
    first fleet record that raised the flag."""
    spans = {}  # (flag, rank) -> {first_src, lo, hi}
    for ev in events:
        if ev["kind"] != "fleet":
            continue
        r = ev["rec"]
        step = r.get("step")
        for flag in ("stragglers", "frozen"):
            field = r.get(flag)
            if not field:
                continue
            for tok in str(field).split(","):
                tok = tok.strip()
                if not tok:
                    continue
                key = (flag, tok)
                if key not in spans:
                    spans[key] = {"src": ev["src"], "lo": step, "hi": step}
                else:
                    spans[key]["hi"] = step
    out = []
    for (flag, rank), s in sorted(spans.items()):
        out.append({
            "flag": flag[:-1] if flag.endswith("s") else flag,
            "rank": rank,
            "step_lo": s["lo"], "step_hi": s["hi"], "src": s["src"],
            "what": (f"rank {rank} flagged {flag[:-1]} over steps "
                     f"{s['lo']}–{s['hi']} ({s['src']})"),
        })
    return out


def _verdict(events: list, incidents: list, drift: dict,
             stragglers: list) -> tuple:
    """``(verdict, evidence_lines)``. Halted beats degraded beats
    completed; every verdict cites the record lines that forced it. A
    halt-policy anomaly adopted by a later retry does NOT halt the run
    — the retry proves the supervisor recovered past it. On the
    serving side the line runs between "degraded (replica lost,
    traffic absorbed)" — crash/failover/restart records with zero
    drops — and "halted": ANY dropped request is a broken serving
    contract, even though the fleet kept running."""
    evidence = []
    adopted = {e["src"] for inc in incidents for e in inc["evidence"]}
    for ev in events:
        if ev["kind"] == "stall":
            evidence.append(f"{ev['src']} — {_describe(ev)}")
        elif (ev["kind"] == "anomaly"
              and ev["rec"].get("policy") == "halt"
              and ev["src"] not in adopted):
            evidence.append(f"{ev['src']} — {_describe(ev)}")
        elif (ev["kind"] == "router"
              and ev["rec"].get("event") == "drop"):
            # a dropped request is a halt-class violation whether or
            # not a restart later adopted it as evidence: the request
            # is gone either way
            evidence.append(f"{ev['src']} — {_describe(ev)}")
    if evidence:
        return "halted", evidence
    for inc in incidents:
        evidence.append(f"{inc['src']} — {inc['what']}")
    for ann in stragglers:
        evidence.append(ann["what"])
    for b in drift.get("breaches", []):
        evidence.append(f"{b['src']} — drift breach ({b['breached']}) "
                        f"at step {b['step']}")
    if evidence:
        # dedupe while keeping order (a drift breach may already be a
        # standalone incident)
        seen, uniq = set(), []
        for line in evidence:
            if line not in seen:
                seen.add(line)
                uniq.append(line)
        return "degraded", uniq
    return "completed", []


def build_report(obs_dir: str, *, ckpt_dir: Optional[str] = None) -> dict:
    """The full report as one JSON-safe dict (the ``--json`` body)."""
    events = _scan_events(obs_dir)
    incidents = _group_incidents(events)
    phases = _phase_breakdown(events)
    drift = _drift_trajectory(events)
    stragglers = _straggler_annotations(events)
    verdict, evidence = _verdict(events, incidents, drift, stragglers)

    ranks = sorted({e["rank"] for e in events})
    steps = [e["step"] for e in events if isinstance(e["step"], int)]
    kind_counts = {}
    for e in events:
        kind_counts[e["kind"]] = kind_counts.get(e["kind"], 0) + 1
    timeline = [
        {"t": e["t"], "rank": e["rank"], "kind": e["kind"],
         "step": e["step"], "src": e["src"], "what": _describe(e)}
        for e in events if e["kind"] in NOTABLE_KINDS
    ]

    # one read-only post-mortem fleet pass for the live health verdict
    # (straggler/frozen flags the per-record scan above may have missed
    # on runs that never wrote a fleet stream)
    fleet = {"kind_counts": kind_counts, "stragglers": stragglers}
    try:
        tailer = FleetTailer(
            obs_dir, topology=fleet_topology(ckpt_dir),
            live=False, write_records=False,
        )
        view = tailer.refresh()
        if view is not None and view.rows:
            fleet["healthy"] = bool(view.healthy)
            fleet["unhealthy_reasons"] = view.unhealthy_reasons()
            fleet["retries"] = int(view.retries)
    except Exception:
        pass  # a report over a partial dir still renders

    return {
        "kind": "report",
        "verdict": verdict,
        "ranks": len(ranks),
        "n_events": len(events),
        "n_incidents": len(incidents),
        "steps": (max(steps) if steps else 0),
        "evidence": evidence,
        "timeline": timeline,
        "incidents": incidents,
        "phases": phases,
        "drift": drift,
        "fleet": fleet,
    }


def render_markdown(rep: dict, obs_dir: str) -> str:
    lines = [f"# tmpi run report — {os.path.basename(os.path.abspath(obs_dir))}",
             ""]
    verdict = rep["verdict"].upper()
    lines.append(f"**Verdict: {verdict}**")
    for ev in rep["evidence"]:
        lines.append(f"- {ev}")
    lines += ["",
              "## Run summary", "",
              f"- ranks: {rep['ranks']}",
              f"- max step: {rep['steps']}",
              f"- events: {rep['n_events']} "
              f"({', '.join(f'{k}×{v}' for k, v in sorted(rep['fleet']['kind_counts'].items()))})",
              f"- incidents: {rep['n_incidents']}"]
    if "retries" in rep["fleet"]:
        lines.append(f"- supervisor retries: {rep['fleet']['retries']}")
    if "healthy" in rep["fleet"]:
        lines.append(
            "- fleet health: "
            + ("healthy" if rep["fleet"]["healthy"]
               else "UNHEALTHY (" +
               "; ".join(rep["fleet"]["unhealthy_reasons"]) + ")"))
    lines.append("")

    if rep["incidents"]:
        lines += ["## Incidents", ""]
        for i, inc in enumerate(rep["incidents"], 1):
            lines.append(f"{i}. [{inc['kind']}] {inc['what']}  "
                         f"`{inc['src']}`")
            for e in inc["evidence"]:
                lines.append(f"   - caused by [{e['kind']}] {e['what']}  "
                             f"`{e['src']}`")
        lines.append("")

    if rep["fleet"]["stragglers"]:
        lines += ["## Straggler / frozen verdicts", ""]
        for ann in rep["fleet"]["stragglers"]:
            lines.append(f"- {ann['what']}")
        lines.append("")

    if rep["timeline"]:
        lines += ["## Event timeline", ""]
        for ev in rep["timeline"]:
            step = f" step {ev['step']}" if ev["step"] is not None else ""
            lines.append(f"- t={ev['t']:.3f}{step} [{ev['kind']}] "
                         f"{ev['what']}  `{ev['src']}`")
        lines.append("")

    if rep["phases"]:
        lines += ["## Per-phase wall breakdown", "",
                  "| phase | seconds | share |",
                  "|---|---:|---:|"]
        for k, v in rep["phases"].items():
            if k.startswith("_"):
                continue
            lines.append(f"| {k} | {v['seconds']:.3f} | "
                         f"{100.0 * v['frac']:.1f}% |")
        lines.append(f"| *total wall* | {rep['phases']['_wall_s']:.3f} | |")
        lines.append("")

    if rep["drift"]:
        d = rep["drift"]
        lines += ["## Model drift", ""]
        for s in ("cost", "traffic", "memory"):
            key = f"model_err_{s}"
            if key in d.get("last", {}):
                lines.append(
                    f"- {key}: last {d['last'][key]:.3f}, "
                    f"worst {d['worst'][key]:.3f}")
        if d.get("breaches"):
            for b in d["breaches"]:
                lines.append(f"- **breach** at step {b['step']}: "
                             f"{b['breached']}  `{b['src']}`")
        else:
            lines.append("- no tolerance breaches")
        lines.append("")

    return "\n".join(lines).rstrip() + "\n"


def render_html(rep: dict, obs_dir: str) -> str:
    """Minimal self-contained HTML: the markdown body escaped inside a
    ``<pre>`` — survives any mail client / artifact browser."""
    body = _html.escape(render_markdown(rep, obs_dir))
    verdict = _html.escape(rep["verdict"])
    return ("<!doctype html><html><head><meta charset=\"utf-8\">"
            f"<title>tmpi report: {verdict}</title></head>"
            f"<body><pre>{body}</pre></body></html>\n")


def report_main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tmpi report", description=__doc__.splitlines()[0]
    )
    ap.add_argument("obs_dir", help="obs directory (finished run or "
                                    "committed profile dir)")
    ap.add_argument("--out", default=None,
                    help="write the report to this path; .html gets the "
                         "HTML rendering, anything else markdown")
    ap.add_argument("--json", action="store_true",
                    help="emit the kind=report JSON object to stdout "
                         "instead of markdown")
    ap.add_argument("--ckpt-dir", default=None,
                    help="checkpoint dir whose __topology__ manifest "
                         "labels slices in the fleet verdict")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.obs_dir):
        print(f"tmpi report: not a directory: {args.obs_dir}",
              file=sys.stderr)
        return 2

    rep = build_report(args.obs_dir, ckpt_dir=args.ckpt_dir)

    if args.json:
        sys.stdout.write(json.dumps(rep, sort_keys=True) + "\n")
    if args.out:
        if args.out.endswith(".html"):
            text = render_html(rep, args.obs_dir)
        else:
            text = render_markdown(rep, args.obs_dir)
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
    if not args.json and not args.out:
        sys.stdout.write(render_markdown(rep, args.obs_dir))
    return 0


if __name__ == "__main__":
    sys.exit(report_main())
