"""Export per-rank span JSONL to Chrome/Perfetto ``trace_event`` JSON.

The span log (obs/spans.py, ``spans_rank{r}.jsonl``) is machine-
readable but nothing renders it; this exporter turns any set of span
files into ONE trace viewable in ``chrome://tracing`` / Perfetto /
``ui.perfetto.dev``:

- one trace **process** per rank (``pid = rank``), named ``rank {r}``;
- bracketed spans on thread 0 (``spans``) as complete ``"ph": "X"``
  events — nesting renders from the timestamps, ``depth`` rides in
  ``args``;
- ``amortized`` spans (the dispatch pipeline's attributed step windows,
  utils/dispatch.py) on their OWN lane (thread 1, ``amortized``),
  flagged in ``args`` — attributed time is not a measured bracket and
  must not fake-nest under real ones;
- ``span_summary`` lines become per-process metadata (``args`` on a
  zero-duration instant event) so the per-kind fractions travel with
  the trace.

Usage::

    python -m theanompi_tpu.tools.spans_to_trace RUN_OBS_DIR -o trace.json
    python -m theanompi_tpu.tools.spans_to_trace spans_rank0.jsonl ...

Directories are searched for ``spans_rank*.jsonl``. Timestamps are the
span log's wall-clock ``t0`` (seconds) converted to microseconds, so
multi-rank traces align on real time.

Multi-rank merges additionally get **clock alignment** (on by default,
``--no-align`` to keep raw wall clocks): per-host clocks skew, so raw
``t0`` values from different ranks can offset the whole timeline by
more than a step. Each rank's FIRST ``name == "step"`` span is a
matching step boundary across ranks (synchronous data-parallel steps
start together at the first collective); the lowest anchored rank is
the reference and every other rank's events shift by the difference of
first-step anchors. Only the *initial* offset is corrected — later
divergence is preserved, which is the point: a straggler's growing gap
stays visible on the shared timeline instead of hiding inside clock
skew.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Optional


def _rank_of(path: str, fallback: int = 0) -> int:
    m = re.search(r"spans_rank(\d+)\.jsonl$", os.path.basename(path))
    return int(m.group(1)) if m else fallback


def discover(paths: list[str]) -> list[str]:
    files = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(
                glob.glob(os.path.join(p, "**", "spans_rank*.jsonl"),
                          recursive=True)
            )
            if not found:
                raise FileNotFoundError(f"no spans_rank*.jsonl under {p!r}")
            files += found
        else:
            files.append(p)
    return files


def _first_step_anchor(path: str) -> Optional[float]:
    """``t0`` of the file's first measured ``name == "step"`` span (the
    cross-rank alignment anchor), or None when the file has none."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if (row.get("kind") == "span" and row.get("name") == "step"
                    and not row.get("amortized")):
                try:
                    return float(row["t0"])
                except (KeyError, TypeError, ValueError):
                    return None
    return None


def clock_offsets(paths: list[str]) -> dict[int, float]:
    """Per-rank additive clock corrections (seconds), anchored on each
    rank's first step-boundary span: ranks started a synchronous step
    together, so differing anchors are clock skew. The lowest anchored
    rank is the reference (offset 0); ranks without a step span get no
    correction. Empty when fewer than two ranks anchor (nothing to
    align against)."""
    anchors: dict[int, float] = {}
    for i, path in enumerate(paths):
        rank = _rank_of(path, fallback=i)
        a = _first_step_anchor(path)
        if a is not None and (rank not in anchors or a < anchors[rank]):
            anchors[rank] = a
    if len(anchors) < 2:
        return {}
    ref = anchors[min(anchors)]
    return {rank: ref - a for rank, a in anchors.items()}


def convert(paths: list[str], align: bool = True) -> dict:
    """``{"traceEvents": [...], "displayTimeUnit": "ms"}`` from span
    files. Unparseable / non-span lines are skipped (partial telemetry
    still converts). ``align`` applies :func:`clock_offsets` so a
    multi-rank merge shares one timeline (straggler gaps are real
    divergence, not clock skew)."""
    offsets = clock_offsets(paths) if align else {}
    events = []
    seen_ranks = set()
    for i, path in enumerate(paths):
        rank = _rank_of(path, fallback=i)
        shift = offsets.get(rank, 0.0)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                kind = row.get("kind")
                if kind == "span":
                    amortized = bool(row.get("amortized", False))
                    events.append({
                        "name": row["name"],
                        "ph": "X",
                        "ts": (row["t0"] + shift) * 1e6,
                        "dur": max(0.0, row["dur"] * 1e6),
                        "pid": rank,
                        "tid": 1 if amortized else 0,
                        "args": {"depth": row.get("depth", 0),
                                 "amortized": amortized},
                    })
                    seen_ranks.add(rank)
                elif kind == "span_summary":
                    events.append({
                        "name": "span_summary",
                        "ph": "i",  # instant: fractions ride in args
                        "ts": (row.get("t0", 0.0) + shift
                               + row.get("wall_s", 0.0)) * 1e6,
                        "pid": rank,
                        "tid": 0,
                        "s": "p",  # process-scoped instant
                        "args": {"fractions": row.get("fractions", {}),
                                 "totals_s": row.get("totals_s", {}),
                                 "wall_s": row.get("wall_s")},
                    })
                    seen_ranks.add(rank)
    for rank in sorted(seen_ranks):
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        events.append({"name": "thread_name", "ph": "M", "pid": rank,
                       "tid": 0, "args": {"name": "spans"}})
        events.append({"name": "thread_name", "ph": "M", "pid": rank,
                       "tid": 1, "args": {"name": "amortized (attributed)"}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="span .jsonl files, or directories to search "
                         "for spans_rank*.jsonl (obs dirs)")
    ap.add_argument("-o", "--out", default="trace.json",
                    help="output trace_event JSON (chrome://tracing, "
                         "Perfetto)")
    ap.add_argument("--no-align", action="store_true",
                    help="keep raw per-rank wall clocks (skip the "
                         "first-step-span clock alignment)")
    args = ap.parse_args(argv)
    files = discover(args.paths)
    trace = convert(files, align=not args.no_align)
    with open(args.out, "w") as f:
        json.dump(trace, f)
    n_spans = sum(1 for e in trace["traceEvents"] if e["ph"] == "X")
    print(f"wrote {args.out}: {n_spans} spans from {len(files)} "
          f"file{'s' if len(files) != 1 else ''}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
