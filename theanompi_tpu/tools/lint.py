"""``tmpi lint`` — every repo lint plus the SPMD safety analyzer,
behind one command with stable rule IDs.

The three long-standing lints (hot-loop, codec coverage, telemetry
schemas) and the jaxpr/AST analyzer (tools/analyze/) run as one pass::

    tmpi lint                       # whole tree, human output
    tmpi lint --json                # machine-readable CI report
    tmpi lint --update-golden       # regenerate collective signatures
    tmpi lint --no-analyze runs/    # fast path: classic lints only
    python -m theanompi_tpu.tools.lint_all   # thin alias (legacy CI)

Exit codes: 0 clean, 1 findings, 2 internal lint failure.

Rule catalog (:data:`RULES`):

======== ================================================================
HOT001   host-materializing call inside a worker train loop
HOT002   host-materializing call inside the serve micro-batch loop's
         per-request paths
CODEC001 engine module bypasses the wire-codec layer without exemption
SCHEMA001 telemetry record violates its documented schema
SPMD001 collective names an axis the engine mesh does not bind
SPMD002 collective under potentially rank-divergent control flow
SPMD003 collective signature drifted from the reviewed golden
SPMD101 traced wire bytes disagree with the declared traffic_model()
SPMD102 codec-on trace does not realize the claimed compression
SPMD201 donates_state declared but the lowered step does not donate
SPMD202 host np.asarray aliases state donated to an engine step
SPMD301 rank-divergent value gates cross-rank work (host taint)
SPMD302 unsorted directory listing (shared-storage order divergence)
HOT003  host sync in `tmpi profile`'s warm-step measurement loops
        beyond the sanctioned blocked reads
MEM001  predicted peak HBM exceeds the budget (tmpi preflight)
MEM002  donation declared but bytes not realized (double buffer)
MEM003  XLA temp pool >> engine state (rematerialization smell)
MEM101  per-leaf HBM residency drifted from golden
PREC001 fp32 island inside a low-precision model's hot path
PREC002 long reduction accumulating in bf16
PREC003 fused-update epilogue math below fp32
PREC101 dtype-flow signature drifted from golden
RACE001 shared attribute written from >=2 thread contexts, no lock
RACE002 inconsistent guarding (locked at some writes, bare at others)
RACE003 lock-order inversion (potential deadlock)
RACE004 filesystem exists/stat-then-use TOCTOU across threads
RACE005 non-atomic multi-field publish vs a locked reader
RACE101 discovered thread model drifted from the reviewed golden
SHARD001 declared spec vs compiled leaf sharding mismatch
SHARD002 implicit resharding: hidden (or elided) collective wire
SHARD003 replication bloat: declared-sharded leaf compiled replicated
SHARD004 train->serve handoff spec drift
SHARD101 declared per-leaf spec table drifted from golden
======== ================================================================

The SHARD family is the sharding & layout analyzer
(tools/analyze/sharding.py, ISSUE 15): every engine x codec x
``--fused-update`` configuration is LOWERED through the shared
cache-bypassing compile (tools/analyze/lowering.py — the same
executable the memory family reads, compiled once per config) and the
COMPILED truth — per-leaf ``input_shardings`` and the optimized-HLO
collective set — is checked against the engine's ShardingRecipe
declaration (parallel/recipe.py), the traced jaxpr signature, and
``traffic_model()``. Hidden wire is a finding, not a footnote.

The RACE family is the host-concurrency analyzer
(tools/analyze/concurrency.py): it discovers the thread model
(``threading.Thread``/``Timer``/pool submits/HTTP handler threads plus
callback registrations), computes the shared-mutable-state set and the
lock discipline actually used, and checks them against each other.
Its dynamic twin is the deterministic thread-stress harness
(tools/analyze/stress.py).

The MEM/PREC families are the memory & precision pre-flight (ISSUE
12): every engine x codec x --fused-update configuration is LOWERED
over abstract operands (compiled, never executed) and its XLA memory
analysis / dtype dataflow checked against the engine's declared
``memory_model()`` and the committed ``golden/preflight_*.json``
snapshots. The same analysis runs one-config-at-a-time with a real
HBM budget behind ``tmpi preflight`` (tools/preflight.py). The
``--json`` report carries per-rule-family wall seconds (``timings_s``)
so budget regressions are attributable.

**Suppressions**: any SPMD/MEM/PREC finding that carries a source
location (SPMD*, PREC001/002/003) can be waived per line with an
end-of-line (or immediately preceding) comment carrying a written
reason. Config-level findings have no source line to suppress at:
MEM001 is answered with a budget, MEM002/MEM003 by fixing the engine
(or, for MEM003, the documented ``TEMP_STATE_RATIO``), and
MEM101/PREC101 by ``--update-golden`` after review::

    files = os.listdir(d)  # spmd_exempt: order-insensitive dict fill

A bare ``spmd_exempt:`` with no reason does not count. Suppressed
findings still appear in the ``--json`` report under ``suppressed``.
The HOT/CODEC/SCHEMA rules keep their own exemption mechanics
(``codec_exempt:`` markers, loop scoping) and do not honor
``spmd_exempt``.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Optional

RULES = {
    "HOT001": "host sync inside a worker train loop "
              "(tools/check_hot_loop.py)",
    "HOT002": "host sync inside the serve micro-batch loop's per-request "
              "paths (tools/check_hot_loop.py)",
    "HOT003": "host sync inside `tmpi profile`'s warm-step measurement "
              "loops beyond the sanctioned blocked reads "
              "(tools/check_hot_loop.py)",
    "CODEC001": "engine exchange bypasses the wire-codec layer "
                "(tools/check_codec_coverage.py)",
    "SCHEMA001": "telemetry record violates its schema "
                 "(tools/check_obs_schema.py)",
    "SPMD001": "collective names an axis not bound on the engine mesh",
    "SPMD002": "collective under potentially rank-divergent control flow",
    "SPMD003": "collective signature drifted from golden "
               "(tmpi lint --update-golden to accept)",
    "SPMD101": "traced wire bytes disagree with declared traffic_model()",
    "SPMD102": "codec-on trace does not realize the claimed compression",
    "SPMD201": "donates_state declared but lowered step does not donate",
    "SPMD202": "host asarray aliases donated engine state",
    "SPMD301": "rank-divergent value gates cross-rank work",
    "SPMD302": "unsorted directory listing on possibly-shared storage",
    "MEM001": "predicted peak HBM exceeds the budget "
              "(tools/analyze/memory.py; tmpi preflight)",
    "MEM002": "donates_state declared but the donation bytes are not "
              "realized — state double-buffers per in-flight dispatch",
    "MEM003": "XLA temp pool >> engine state (rematerialization smell)",
    "MEM101": "per-leaf HBM residency drifted from golden, or the "
              "config could not be lowered "
              "(tmpi lint --update-golden to accept a reviewed drift)",
    "PREC001": "fp32 island inside a low-precision model's hot path",
    "PREC002": "long reduction accumulating in bf16",
    "PREC003": "fused-update epilogue math below fp32",
    "PREC101": "dtype-flow signature drifted from golden, or the "
               "config could not be traced "
               "(tmpi lint --update-golden to accept a reviewed drift)",
    "RACE001": "shared attribute written from >=2 thread contexts with "
               "no lock anywhere (tools/analyze/concurrency.py)",
    "RACE002": "inconsistent guarding: attribute locked at some write "
               "sites, bare (or differently locked) at others",
    "RACE003": "lock-order inversion across two locks (potential "
               "deadlock)",
    "RACE004": "filesystem exists/stat-then-use TOCTOU racing the "
               "prune/scrubber/reload threads, no OSError guard",
    "RACE005": "non-atomic multi-field publish read as a pair under a "
               "lock in another thread context",
    "RACE101": "discovered thread model drifted from the reviewed "
               "golden (tools/analyze/golden/thread_model.json; "
               "tmpi lint --update-golden to accept)",
    "SHARD001": "declared ShardingRecipe spec disagrees with the "
                "compiled executable's leaf sharding (or a hand-rolled "
                "PartitionSpec outside parallel/recipe.py)",
    "SHARD002": "GSPMD-inserted (or elided) collective wire absent "
                "from the traced program, or compiled wire bytes "
                "drifting from traffic_model() beyond the SPMD101 "
                "tolerance",
    "SHARD003": "leaf declared sharded but compiled fully replicated "
                "— memory_model()'s 1/n division is a lie",
    "SHARD004": "train->serve handoff drift: serve template specs vs "
                "the training recipe's stamped __topology__ specs",
    "SHARD101": "declared per-leaf spec table drifted from golden, or "
                "the config could not be lowered "
                "(tmpi lint --update-golden to accept a reviewed "
                "drift)",
}

_EXEMPT_RE = re.compile(r"spmd_exempt:[ \t]*(\S[^\n]*)")


@dataclass
class LintFinding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    exempt_reason: str = ""

    def as_json(self) -> dict:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "message": self.message}
        if self.suppressed:
            d["suppressed"] = True
            d["exempt_reason"] = self.exempt_reason
        return d


@dataclass
class LintReport:
    findings: list = field(default_factory=list)
    suppressed: list = field(default_factory=list)
    notes: list = field(default_factory=list)
    # per-rule-family wall seconds (hot_loop, codec, schema, spmd,
    # memory, precision) — budget regressions are attributable to the
    # family that grew (tests/test_lint_all.py enforces the total)
    timings_s: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def as_json(self) -> dict:
        return {
            "ok": self.ok,
            "counts": {
                "findings": len(self.findings),
                "suppressed": len(self.suppressed),
            },
            "findings": [f.as_json() for f in self.findings],
            "suppressed": [f.as_json() for f in self.suppressed],
            "notes": list(self.notes),
            "timings_s": {k: round(v, 3)
                          for k, v in self.timings_s.items()},
            "rules": RULES,
        }


def _exemption_reason(path: str, line: int) -> Optional[str]:
    """The written ``spmd_exempt`` reason covering ``path:line`` — on
    the line itself or the line immediately above (comment-only line)."""
    if not path or line <= 0 or not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return None
    if 1 <= line <= len(lines):
        m = _EXEMPT_RE.search(lines[line - 1])
        if m:
            return m.group(1).strip()
    # a standalone comment line immediately above also covers the line
    if 2 <= line <= len(lines) + 1:
        prev = lines[line - 2].strip()
        if prev.startswith("#"):
            m = _EXEMPT_RE.search(prev)
            if m:
                return m.group(1).strip()
    return None


def _add(report: LintReport, rule: str, path: str, line: int,
         message: str, suppressible: bool = True) -> None:
    f = LintFinding(rule=rule, path=path, line=line, message=message)
    # the analyzer families (SPMD + the MEM/PREC pre-flight) share the
    # per-line written-reason suppression; HOT/CODEC/SCHEMA keep their
    # own exemption mechanics
    reason = _exemption_reason(path, line) if (
        suppressible and rule.startswith(("SPMD", "MEM", "PREC", "RACE",
                                          "SHARD"))
    ) else None
    if reason:
        f.suppressed = True
        f.exempt_reason = reason
        report.suppressed.append(f)
    else:
        report.findings.append(f)


_LINE_RE = re.compile(r"line (\d+):")


def _run_hot_loop(report: LintReport) -> None:
    from theanompi_tpu.tools import check_hot_loop as H

    with open(H.WORKER_PATH) as f:
        for err in H.check_source(f.read()):
            m = _LINE_RE.search(err)
            _add(report, "HOT001", H.WORKER_PATH,
                 int(m.group(1)) if m else 0, err)
    with open(H.SERVE_PATH) as f:
        for err in H.check_serve_source(f.read()):
            m = _LINE_RE.search(err)
            _add(report, "HOT002", H.SERVE_PATH,
                 int(m.group(1)) if m else 0, err)
    with open(H.PROFILE_PATH) as f:
        for err in H.check_profile_source(f.read()):
            m = _LINE_RE.search(err)
            _add(report, "HOT003", H.PROFILE_PATH,
                 int(m.group(1)) if m else 0, err)


def _run_codec_coverage(report: LintReport) -> None:
    from theanompi_tpu.tools import check_codec_coverage as C

    for err in C.check_dir():
        path = err.split(":", 1)[0]
        _add(report, "CODEC001", path, 0, err)


def _run_schema(report: LintReport, paths: Optional[list]) -> None:
    from theanompi_tpu.tools import check_obs_schema as S
    from theanompi_tpu.tools.lint_all import telemetry_files

    files = telemetry_files(paths)
    if not files:
        report.notes.append("schema lint: no telemetry files found (OK)")
        return
    loc = re.compile(r"^(.*?):(\d+): ")
    for f in files:
        for err in S.check_file(f):
            m = loc.match(err)
            _add(report, "SCHEMA001", m.group(1) if m else f,
                 int(m.group(2)) if m else 0, err)


def _ensure_virtual_devices() -> None:
    """Give the analyzer a multi-device CPU platform to trace over,
    regardless of entry point (``tmpi lint``, ``python -m ...lint``,
    the ``lint_all`` alias). XLA_FLAGS is read at BACKEND init —
    setting it here works as long as nothing touched devices yet, and
    is a harmless no-op under pytest's conftest (backend already up
    with 8 virtual devices and the same flag)."""
    os.environ.setdefault(
        "JAX_PLATFORMS", os.environ.get("TMPI_FORCE_PLATFORM") or "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def _run_analyzer(report: LintReport, update_golden: bool) -> None:
    _ensure_virtual_devices()
    from theanompi_tpu.tools.analyze.astlint import run_ast_lints
    from theanompi_tpu.tools.analyze.rules import analyze_engines

    for f in analyze_engines(update_golden=update_golden):
        _add(report, f.rule, f.path, f.line, f.message)
    for f in run_ast_lints():
        _add(report, f.rule, f.path, f.line, f.message)


def _run_memory(report: LintReport, update_golden: bool) -> None:
    _ensure_virtual_devices()
    from theanompi_tpu.tools.analyze.memory import analyze_memory

    for f in analyze_memory(update_golden=update_golden):
        _add(report, f.rule, f.path, f.line, f.message)


def _run_precision(report: LintReport, update_golden: bool) -> None:
    _ensure_virtual_devices()
    from theanompi_tpu.tools.analyze.precision import analyze_precision

    for f in analyze_precision(update_golden=update_golden):
        _add(report, f.rule, f.path, f.line, f.message)


def _run_sharding(report: LintReport, update_golden: bool,
                  obs_dir: Optional[str] = None) -> None:
    _ensure_virtual_devices()
    from theanompi_tpu.tools.analyze.sharding import analyze_sharding

    for f in analyze_sharding(update_golden=update_golden,
                              obs_dir=obs_dir):
        _add(report, f.rule, f.path, f.line, f.message)


def _run_concurrency(report: LintReport, update_golden: bool) -> None:
    # pure AST over the threaded host files — needs no devices, so it
    # also runs under --no-analyze-free fast paths cheaply
    from theanompi_tpu.tools.analyze.concurrency import run_concurrency_lints

    for f in run_concurrency_lints(update_golden=update_golden):
        _add(report, f.rule, f.path, f.line, f.message)


def _timed(report: LintReport, family: str, fn, *args) -> None:
    import time

    t0 = time.monotonic()
    fn(report, *args)
    report.timings_s[family] = (report.timings_s.get(family, 0.0)
                                + time.monotonic() - t0)


def run_lint(paths: Optional[list] = None, update_golden: bool = False,
             analyze: bool = True,
             obs_dir: Optional[str] = None) -> LintReport:
    report = LintReport()
    _timed(report, "hot_loop", _run_hot_loop)
    _timed(report, "codec_coverage", _run_codec_coverage)
    _timed(report, "schema", _run_schema, paths)
    # the RACE family (host-concurrency analyzer) is AST-only and
    # cheap — it runs even on the classic fast path, like the other
    # source lints
    _timed(report, "concurrency", _run_concurrency, update_golden)
    if analyze:
        _timed(report, "spmd", _run_analyzer, update_golden)
        # the preflight families lower+compile the engine matrix (the
        # only lint step that compiles); their share of the <90 s CPU
        # budget is attributable via timings_s
        _timed(report, "memory", _run_memory, update_golden)
        _timed(report, "precision", _run_precision, update_golden)
        # the sharding family reads the SAME compiled executables the
        # memory family lowered (tools/analyze/lowering.py memoizes
        # them), so its marginal cost is parsing, not compiling
        _timed(report, "sharding", _run_sharding, update_golden, obs_dir)
    return report


def _rel(path: str) -> str:
    try:
        return os.path.relpath(path)
    except ValueError:
        return path


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tmpi lint", description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="telemetry dirs/files for the schema lint "
                         "(default: the repo tree)")
    ap.add_argument("--json", action="store_true", dest="json_out",
                    help="machine-readable report on stdout (CI)")
    ap.add_argument("--update-golden", action="store_true",
                    help="regenerate the per-engine collective-signature "
                         "snapshots instead of diffing against them")
    ap.add_argument("--no-analyze", action="store_true",
                    help="skip the SPMD analyzer (classic lints only)")
    ap.add_argument("--obs-dir", default=None,
                    help="append one kind=shard record per analyzed "
                         "config to <dir>/metrics.jsonl "
                         "(tools/check_obs_schema.py)")
    args = ap.parse_args(argv)
    try:
        report = run_lint(paths=args.paths or None,
                          update_golden=args.update_golden,
                          analyze=not args.no_analyze,
                          obs_dir=args.obs_dir)
    except Exception as e:  # noqa: BLE001 — rc 2 = the lint itself broke
        print(f"tmpi lint: internal failure: {type(e).__name__}: {e}",
              file=sys.stderr)
        if args.json_out:
            print(json.dumps({"ok": False, "internal_error": repr(e)}))
        return 2
    if args.json_out:
        print(json.dumps(report.as_json(), indent=1))
        return 0 if report.ok else 1
    for note in report.notes:
        print(note)
    for f in report.findings:
        loc = f"{_rel(f.path)}:{f.line}: " if f.path else ""
        print(f"{f.rule} {loc}{f.message}")
    for f in report.suppressed:
        print(f"{f.rule} {_rel(f.path)}:{f.line}: suppressed "
              f"(spmd_exempt: {f.exempt_reason})")
    if args.update_golden:
        from theanompi_tpu.tools.analyze.golden import GOLDEN_DIR

        print(f"golden signatures regenerated under {_rel(GOLDEN_DIR)}")
    print("tmpi lint: " + ("OK" if report.ok else
                           f"{len(report.findings)} findings"))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
