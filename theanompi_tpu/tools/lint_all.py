"""Run every repo lint in one pass: hot-loop + codec coverage +
telemetry schemas.

One entry point for CI and the tier-1 suite (tests/test_lint_all.py):

1. **hot-loop lint** (tools/check_hot_loop.py): the worker train loops
   must contain no host-materializing calls — the invariant the async
   dispatch pipeline (and the numerics sentinels that ride it) depend
   on;
2. **codec-coverage lint** (tools/check_codec_coverage.py): every
   engine module under ``parallel/`` routes its exchange through the
   codec layer (``parallel/codec.py``) or carries an explicit
   ``codec_exempt: <reason>`` marker — ``--wire-codec`` must keep
   covering the whole fleet;
3. **schema lint** (tools/check_obs_schema.py): every telemetry
   ``*.jsonl`` (plus heartbeat/stall ``.json``) found under the given
   paths — default: the repo tree — must match the documented record
   schemas, including the ``numerics``/``anomaly`` kinds the flight
   recorder emits and the ``comm`` wire-declaration records.

A tree with no telemetry files passes the schema step vacuously (fresh
checkouts hold none until a run writes some); a single invalid line
fails the whole lint.

Usage::

    python -m theanompi_tpu.tools.lint_all              # repo tree
    python -m theanompi_tpu.tools.lint_all runs/ exp/   # specific dirs
"""

from __future__ import annotations

import fnmatch
import os
import sys
from typing import Optional

from theanompi_tpu.tools import (
    check_codec_coverage,
    check_hot_loop,
    check_obs_schema,
)

# never telemetry; test fixtures under tests/ may hold deliberately
# invalid lines for the schema checker's own tests
_SKIP_DIRS = {".git", "__pycache__", ".jax_cache", "node_modules",
              ".pytest_cache", "tests"}

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def telemetry_files(paths: Optional[list] = None) -> list[str]:
    """Every ``*.jsonl`` + heartbeat/stall ``.json`` under ``paths``
    (default: the repo root), skipping VCS/cache/test dirs."""
    roots = paths or [REPO_ROOT]
    files: list[str] = []
    for root in roots:
        if os.path.isfile(root):
            files.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for name in sorted(filenames):
                if name.endswith(".jsonl") or fnmatch.fnmatch(
                    name, "heartbeat_rank*.json"
                ) or fnmatch.fnmatch(name, "stall_rank*.json"):
                    files.append(os.path.join(dirpath, name))
    return files


def main(argv: Optional[list] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    rc = 0

    # 1. hot-loop lint on the worker train loops
    rc |= check_hot_loop.main([])

    # 2. codec-coverage lint over the parallel/ engine modules
    rc |= check_codec_coverage.main([])

    # 3. schema lint over every telemetry file found
    files = telemetry_files(argv or None)
    if not files:
        print("schema lint: no telemetry files found (OK)")
    else:
        rc |= check_obs_schema.main([*files, "-q"])

    print("lint_all: " + ("OK" if rc == 0 else "FAILED"))
    return 1 if rc else 0


if __name__ == "__main__":
    sys.exit(main())
