"""Legacy alias for ``tmpi lint`` (tools/lint.py).

ISSUE 7 folded the three classic lints (hot-loop, codec coverage,
telemetry schemas) together with the SPMD safety analyzer behind the
``tmpi lint`` subcommand; ISSUE 12 added the memory & precision
pre-flight families (MEM*/PREC*, tools/analyze/memory.py /
precision.py — the one step that lowers+compiles), so the full alias
pass now runs those too, under the <90 s CPU budget
tests/test_lint_all.py enforces (per-family wall time rides the
``--json`` report's ``timings_s``). This module stays a thin alias so
existing CI invocations keep working::

    python -m theanompi_tpu.tools.lint_all              # repo tree
    python -m theanompi_tpu.tools.lint_all runs/ exp/   # telemetry dirs

Positional arguments remain telemetry paths for the schema step. A
tree with no telemetry files passes the schema step vacuously (fresh
checkouts hold none until a run writes some); a single invalid line
fails the whole lint. Rule IDs, ``--json`` output, and ``spmd_exempt``
suppressions are documented in :mod:`theanompi_tpu.tools.lint`.

:func:`telemetry_files` (the discovery walk the schema step uses)
lives here and is shared with tools/lint.py.
"""

from __future__ import annotations

import fnmatch
import os
import sys
from typing import Optional

# never telemetry; test fixtures under tests/ may hold deliberately
# invalid lines for the schema checker's own tests
_SKIP_DIRS = {".git", "__pycache__", ".jax_cache", "node_modules",
              ".pytest_cache", "tests"}

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def telemetry_files(paths: Optional[list] = None) -> list[str]:
    """Every ``*.jsonl`` + heartbeat/stall ``.json`` under ``paths``
    (default: the repo root), skipping VCS/cache/test dirs."""
    roots = paths or [REPO_ROOT]
    files: list[str] = []
    for root in roots:
        if os.path.isfile(root):
            files.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for name in sorted(filenames):
                if name.endswith(".jsonl") or fnmatch.fnmatch(
                    name, "heartbeat_rank*.json"
                ) or fnmatch.fnmatch(name, "stall_rank*.json"):
                    files.append(os.path.join(dirpath, name))
    return files


def main(argv: Optional[list] = None) -> int:
    """Thin alias over ``tmpi lint`` (tools/lint.py): positional args
    remain telemetry paths for the schema step, and the full pass now
    includes the serve hot-path lint and the SPMD safety analyzer
    (tools/analyze/). Kept so existing CI invocations of
    ``python -m theanompi_tpu.tools.lint_all`` keep working."""
    argv = sys.argv[1:] if argv is None else argv
    from theanompi_tpu.tools.lint import main as lint_main

    rc = lint_main(list(argv))
    print("lint_all: " + ("OK" if rc == 0 else "FAILED"))
    return rc


if __name__ == "__main__":
    sys.exit(main())
