"""SPMD safety analyzer: static verification of every engine's
collective schedule (ISSUE 7).

Theano-MPI's canonical failure mode was the mismatched collective —
one worker enters an exchange its peers never post, and the whole gang
deadlocks (reference: every ``Exch_*`` strategy assumed all ranks call
``exchange()`` on the same iteration; SURVEY.md §3). The TPU rebuild
inherits the same class through SPMD: a collective under rank-divergent
control flow, a donated buffer read after the step consumed it, or
host code whose decisions depend on rank-divergent inputs (NFS listing
order, wall clock) feeding a cross-rank agreement. PR 4 shipped exactly
one of these for real — the rollback path needed a checkpoint-step
allgather because different hosts resolved different "newest"
checkpoints.

This package finds that class BEFORE it runs, by abstract
interpretation rather than execution:

- :mod:`~theanompi_tpu.tools.analyze.signature` traces each engine's
  train step with ``jax.make_jaxpr`` (tiny model, 2-device CPU mesh —
  nothing is compiled or executed) and walks the equations into an
  ordered **collective signature**: (primitive, axis names, dtype,
  shape, static trip count) per collective, plus a replicated-vs-
  varying dataflow analysis that flags collectives under control flow
  whose predicate can differ across ranks;
- :mod:`~theanompi_tpu.tools.analyze.harness` owns the tiny engine
  builds (all five rules: BSP, ZeRO-1, EASGD, GoSGD, ND — codec off
  and ``int8:ef``);
- :mod:`~theanompi_tpu.tools.analyze.rules` runs the four rule
  families over the traces (collective safety, traffic-model
  cross-check, donation audit, golden-signature drift);
- :mod:`~theanompi_tpu.tools.analyze.astlint` is the host-side half:
  rank-divergence taint lint and the use-after-donation alias lint
  over the launch/checkpoint sources;
- :mod:`~theanompi_tpu.tools.analyze.golden` stores the per-engine
  signature snapshots (``tmpi lint --update-golden`` regenerates);
- :mod:`~theanompi_tpu.tools.analyze.concurrency` is the HOST-side
  concurrency half (ISSUE 14): thread-model discovery over the
  dispatcher/checkpointer/scrubber/serve/health sources, the shared-
  mutable-state + lock-discipline computation, and the RACE001–005
  rule family plus the RACE101 thread-model golden;
- :mod:`~theanompi_tpu.tools.analyze.stress` is its dynamic twin: the
  deterministic seeded thread-stress harness the tier-1 stress tests
  drive (switch-interval shrinking, barrier-released threads,
  injectable delay hooks, ``kind=stress`` records).

Everything surfaces through ``tmpi lint`` (tools/lint.py) with stable
rule IDs and per-line ``spmd_exempt: <reason>`` suppressions; rule
catalog in :data:`theanompi_tpu.tools.lint.RULES`.
"""

from theanompi_tpu.tools.analyze.rules import Finding, analyze_engines  # noqa: F401
from theanompi_tpu.tools.analyze.astlint import (  # noqa: F401
    donation_findings,
    rank_divergence_findings,
)
