"""Static peak-HBM budgeting — the memory side of ``tmpi preflight``.

The flagship question this module answers WITHOUT executing anything:
*will this engine × model × mesh × codec fit in HBM, and where does
every byte live?* Each engine's numerics-off train step is LOWERED over
abstract ``ShapeDtypeStruct`` operands via the same path the PR-9 cost
authority uses (``jitted.lower(...).compile()`` — compiles, never
executes) and XLA's own ``memory_analysis()`` is read off the compiled
executable: argument / output / temp / generated-code bytes plus the
``alias`` bytes donation actually realized. Per-leaf attribution comes
from the engine's declared :func:`~theanompi_tpu.utils.flops.MemoryModel`
(the ``memory_model()`` hook every engine carries, mirroring
``traffic_model()``): sharded leaves divide by their mesh extent, so
the table is per-DEVICE residency.

Peak model::

    peak = argument + (output - alias) + temp + generated_code
           + donation_shortfall

``donation_shortfall`` is the double-buffer penalty of a DECLARED
donation the lowered program did not realize: under the async dispatch
pipeline every in-flight step then holds a second full state copy, so
an unrealized donation costs (at least) one extra state copy of HBM.
This term is what makes the predicted peak GROW by >= the state bytes
when a ``donate`` flag is dropped — backend-independent, where the raw
XLA numbers are not (this container's CPU backend books aliased
buffers into ``temp`` as well, so donated/undonated XLA peaks nearly
cancel; TPU does not).

Rules (IDs in tools/lint.py RULES):

- **MEM001 over-budget** — predicted peak exceeds the HBM budget
  (``--budget-gb``, or the device table's capacity column,
  utils/flops.py ``hbm_capacity_bytes``). The finding names the top-10
  largest live buffers so the refusal is actionable.
- **MEM002 donation-declared-but-double-buffered** — extends SPMD201
  from "``donated_invars`` set" to "bytes saved REALIZED": the engine
  declares ``donates_state`` but XLA's alias bytes fall short of the
  state's per-device bytes.
- **MEM003 rematerialization smell** — XLA temp bytes exceed
  ``TEMP_STATE_RATIO`` x the engine state's per-device bytes: the
  compiled step is holding far more scratch than the model it trains,
  the classic signature of a missed remat/fusion opportunity.
- **MEM101 golden drift** — the per-leaf residency table drifted from
  the reviewed snapshot (golden.py ``preflight``; regenerate with
  ``tmpi lint --update-golden``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from theanompi_tpu.tools.analyze.rules import Finding

# MEM003: temp bytes beyond this multiple of the state's per-device
# bytes smell like rematerialization. The harness tiny models sit
# around 1-4x (activations for a 16-row batch vs a few-KB net); real
# training steps keep temps within a small multiple of state unless
# XLA lost a fusion — 16x leaves comfortable clean-tree margin while
# still catching order-of-magnitude scratch blowups.
TEMP_STATE_RATIO = 16.0
# MEM002: alias shortfall below this floor is accounting noise (tiny
# unaliased leaves like empty () fields), not a lost donation
DONATION_SHORTFALL_FLOOR = 4096


@dataclass(frozen=True)
class XlaMemory:
    """One compiled executable's ``memory_analysis()`` numbers (bytes,
    per device — the executable IS the per-device program)."""

    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    alias_bytes: int
    generated_code_bytes: int

    def as_json(self) -> dict:
        return {"argument_bytes": int(self.argument_bytes),
                "output_bytes": int(self.output_bytes),
                "temp_bytes": int(self.temp_bytes),
                "alias_bytes": int(self.alias_bytes),
                "generated_code_bytes": int(self.generated_code_bytes)}


def compiled_memory(compiled) -> XlaMemory:
    """Read ``memory_analysis()`` off an already-compiled executable.
    Raises when the backend provides no memory analysis (the caller
    converts that into a per-config finding rather than crashing the
    lint)."""
    ma = compiled.memory_analysis()
    if ma is None:
        raise RuntimeError("backend returned no memory_analysis()")
    return XlaMemory(
        argument_bytes=int(getattr(ma, "argument_size_in_bytes", 0)),
        output_bytes=int(getattr(ma, "output_size_in_bytes", 0)),
        temp_bytes=int(getattr(ma, "temp_size_in_bytes", 0)),
        alias_bytes=int(getattr(ma, "alias_size_in_bytes", 0)),
        generated_code_bytes=int(
            getattr(ma, "generated_code_size_in_bytes", 0)),
    )


def lowered_memory(jitted, *args, **kwargs) -> XlaMemory:
    """XLA ``memory_analysis()`` of one jitted callable lowered over
    abstract operands — compiles, never executes, with the persistent
    compilation cache BYPASSED: a cache-DESERIALIZED executable reports
    ``alias_size_in_bytes == 0`` (the stats don't survive
    serialization), which would read as every donation silently failing
    — the exact false positive MEM002 must never produce. The bypass
    (and the process-wide cache-latch workaround) lives in the shared
    tools/analyze/lowering.py, because the sharding analyzer needs the
    same discipline: a cache-deserialized executable also drops its
    sharding metadata."""
    from theanompi_tpu.tools.analyze.lowering import lowered_compile

    return compiled_memory(lowered_compile(jitted, *args, **kwargs))


@dataclass
class MemoryReport:
    """The reconciled memory picture of ONE engine configuration:
    XLA's compiled-program accounting + the engine's declared per-leaf
    residency + the donation audit, against an optional budget."""

    engine: str
    codec: str
    fused: bool
    xla: XlaMemory
    model: "object"  # utils/flops.MemoryModel
    declared_donates: bool
    budget_bytes: Optional[float] = None
    budget_source: str = ""  # "--budget-gb" | "device-table" | ""

    @property
    def donated_expected_bytes(self) -> int:
        """Per-device bytes a full state donation should alias."""
        return int(self.model.state_bytes_per_device)

    @property
    def donation_shortfall(self) -> int:
        """Declared-but-unrealized donation bytes (0 when the engine
        does not declare donation at all — an honest no-donate engine
        pays its double buffer in the XLA output term instead)."""
        if not self.declared_donates:
            return 0
        return max(0, self.donated_expected_bytes
                   - int(self.xla.alias_bytes))

    @property
    def peak_bytes(self) -> int:
        x = self.xla
        return int(x.argument_bytes + max(0, x.output_bytes - x.alias_bytes)
                   + x.temp_bytes + x.generated_code_bytes
                   + self.donation_shortfall)

    @property
    def fit(self) -> Optional[bool]:
        # None-vs-0.0 is presence-vs-value (the same distinction the
        # perf-gate zero-baseline fix draws): an explicit 0 budget is a
        # budget, and nothing fits in it
        if self.budget_bytes is None:
            return None
        return self.peak_bytes <= float(self.budget_bytes)

    def buffer_table(self) -> list:
        """Named live buffers, largest first: every state leaf (per
        device) plus synthetic rows for the batch operands and XLA's
        temp pool — the table MEM001 prints on refusal."""
        rows = [
            {"name": l.path, "bytes": int(l.per_device_bytes),
             "dtype": l.dtype, "shape": list(l.shape),
             "kind": "state",
             # the recipe-DECLARED spec the per-device division derives
             # from (None on legacy bare-factor callers) — `tmpi
             # preflight` prints it instead of re-deriving sharding
             "spec": getattr(l, "spec", None),
             "shard_factor": int(l.shard_factor)}
            for l in self.model.leaves
        ]
        batch = max(0, int(self.xla.argument_bytes)
                    - self.donated_expected_bytes)
        rows.append({"name": "<batch operands>", "bytes": batch,
                     "dtype": "", "shape": [], "kind": "argument"})
        rows.append({"name": "<xla temp pool>",
                     "bytes": int(self.xla.temp_bytes),
                     "dtype": "", "shape": [], "kind": "temp"})
        if self.donation_shortfall:
            rows.append({"name": "<double-buffered state "
                                 "(unrealized donation)>",
                         "bytes": int(self.donation_shortfall),
                         "dtype": "", "shape": [], "kind": "penalty"})
        return sorted(rows, key=lambda r: -r["bytes"])

    def top_buffers(self, k: int = 10) -> list:
        return self.buffer_table()[:k]

    def as_json(self) -> dict:
        return {
            "engine": self.engine, "codec": self.codec,
            "fused": bool(self.fused),
            "n_devices": int(self.model.n_devices),
            "xla": self.xla.as_json(),
            "state_bytes_per_device": self.donated_expected_bytes,
            "declared_donates": bool(self.declared_donates),
            "donation_shortfall": int(self.donation_shortfall),
            "peak_bytes": int(self.peak_bytes),
            "budget_bytes": float(self.budget_bytes)
            if self.budget_bytes is not None else None,
            "budget_source": self.budget_source,
            "fit": self.fit,
            "buffers": self.buffer_table(),
        }


def analyze_step_memory(jitted, args, model, declared_donates: bool,
                        engine: str = "", codec: str = "none",
                        fused: bool = False,
                        budget_bytes: Optional[float] = None,
                        budget_source: str = "") -> MemoryReport:
    """Lower+compile ``jitted`` over abstract ``args`` and reconcile
    the XLA memory analysis with the declared per-leaf ``model``
    (utils/flops.MemoryModel). The building block both ``tmpi lint``'s
    matrix sweep and ``tmpi preflight``'s single-config run share —
    also the mutation-test entry point (hand it a scratch no-donate
    step and watch MEM002 + the predicted peak grow)."""
    return MemoryReport(
        engine=engine, codec=codec, fused=bool(fused),
        xla=lowered_memory(jitted, *args),
        model=model,
        declared_donates=bool(declared_donates),
        budget_bytes=budget_bytes,
        budget_source=budget_source,
    )


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.2f} {unit}" if unit != "B" else f"{n:.0f} B"
        n /= 1024.0
    return f"{n:.2f} GiB"


def memory_findings(report: MemoryReport,
                    temp_state_ratio: float = TEMP_STATE_RATIO) -> list:
    """MEM001/MEM002/MEM003 over one reconciled report."""
    out = []
    tag = (f"[{report.engine}/{report.codec}"
           f"{'/fused' if report.fused else ''}]")
    if report.fit is False:
        top = ", ".join(
            f"{r['name']}={_fmt_bytes(r['bytes'])}"
            for r in report.top_buffers(10)
        )
        out.append(Finding(
            rule="MEM001", path="", line=0, engine=report.engine,
            message=(
                f"{tag} predicted peak {_fmt_bytes(report.peak_bytes)} "
                f"exceeds the {_fmt_bytes(report.budget_bytes)} budget "
                f"({report.budget_source or 'device table'}); largest "
                f"live buffers: {top}"
            ),
        ))
    if report.donation_shortfall > DONATION_SHORTFALL_FLOOR:
        out.append(Finding(
            rule="MEM002", path="", line=0, engine=report.engine,
            message=(
                f"{tag} engine declares donates_state but the lowered "
                f"step aliases only "
                f"{_fmt_bytes(report.xla.alias_bytes)} of the "
                f"{_fmt_bytes(report.donated_expected_bytes)} state — "
                "the unrealized donation double-buffers "
                f"{_fmt_bytes(report.donation_shortfall)} per in-flight "
                "dispatch (declared-vs-lowered bytes, the MEM "
                "extension of SPMD201)"
            ),
        ))
    state_b = max(1, report.donated_expected_bytes)
    if report.xla.temp_bytes > temp_state_ratio * state_b:
        out.append(Finding(
            rule="MEM003", path="", line=0, engine=report.engine,
            message=(
                f"{tag} XLA temp pool "
                f"{_fmt_bytes(report.xla.temp_bytes)} is "
                f"{report.xla.temp_bytes / state_b:.1f}x the engine "
                f"state ({_fmt_bytes(state_b)}) — rematerialization "
                f"smell (threshold {temp_state_ratio:.0f}x); check "
                "remat/fusion on the backward pass"
            ),
        ))
    return out


# --------------------------------------------------------------------------
# the lint-side matrix sweep (engine x codec x fused via the preflight
# harness) + golden comparison
# --------------------------------------------------------------------------

_REPORT_CACHE: dict = {}


def config_report(name: str, codec: str, fused: bool,
                  budget_bytes: Optional[float] = None,
                  budget_source: str = ""):
    """``(MemoryReport | None, error | None)`` for one harness config,
    memoized per process (the lint and its tests re-enter)."""
    from theanompi_tpu.tools.analyze import harness

    key = (name, codec, fused)
    if key not in _REPORT_CACHE:
        pre = harness.preflight_trace(name, codec, fused)
        if pre.error is not None:
            _REPORT_CACHE[key] = (None, pre.error)
        else:
            try:
                # compile through the shared per-config executable
                # cache (tools/analyze/lowering.py): the sharding
                # family reads input_shardings/HLO off the SAME
                # executable, so the matrix compiles once per process
                from theanompi_tpu.tools.analyze.lowering import (
                    config_executable,
                )

                report = MemoryReport(
                    engine=name, codec=codec, fused=bool(fused),
                    xla=compiled_memory(config_executable(
                        key, pre.step_fn, pre.step_args)),
                    model=pre.memory,
                    declared_donates=bool(pre.declared_donates),
                )
                _REPORT_CACHE[key] = (report, None)
            except Exception as e:  # noqa: BLE001 — becomes a finding
                _REPORT_CACHE[key] = (None, f"{type(e).__name__}: {e}")
    report, err = _REPORT_CACHE[key]
    if report is not None and budget_bytes is not None:
        # budget applies per call (the CLI passes one; the lint none)
        report = MemoryReport(
            engine=report.engine, codec=report.codec, fused=report.fused,
            xla=report.xla, model=report.model,
            declared_donates=report.declared_donates,
            budget_bytes=budget_bytes, budget_source=budget_source,
        )
    return report, err


def analyze_memory(update_golden: bool = False) -> list:
    """MEM001/002/003 + MEM101 (golden) over the full preflight matrix
    (5 engines x {none, int8:ef} x {unfused, fused}). No budget is
    applied here — the lint machine is a CPU without an HBM spec entry;
    will-it-fit runs through ``tmpi preflight`` where a budget exists."""
    from theanompi_tpu.tools.analyze import harness

    findings: list = []
    for name in harness.PREFLIGHT_ENGINES:
        for codec in harness.CODEC_SPECS:
            for fused in harness.FUSED_FLAGS:
                report, err = config_report(name, codec, fused)
                if err is not None:
                    # a config that cannot even be built/lowered is an
                    # analysis failure, NOT a budget refusal — routed to
                    # the family's golden/infrastructure rule (MEM101)
                    # so rule-keyed CI consumers never misread it as
                    # over-budget (mirrors PREC101's failure routing
                    # and SPMD001's trace-failure convention)
                    findings.append(Finding(
                        rule="MEM101", path="", line=0, engine=name,
                        message=(
                            f"[{name}/{codec}{'/fused' if fused else ''}] "
                            f"memory pre-flight could not lower the "
                            f"step: {err}"
                        ),
                    ))
                    continue
                findings.extend(memory_findings(report))
                findings.extend(golden_memory_findings(
                    report, update=update_golden))
    return findings


def memory_payload(report: MemoryReport) -> dict:
    """The golden-stable slice of a report: the per-leaf residency
    table and the donation declaration — pure functions of the engine's
    state structure and mesh, deliberately excluding the raw XLA
    temp/code numbers (XLA-version-fragile)."""
    return {
        "declared_donates": bool(report.declared_donates),
        "n_devices": int(report.model.n_devices),
        "state_bytes_per_device": int(report.model.state_bytes_per_device),
        "leaves": [l.as_json() for l in report.model.leaves],
    }


def golden_memory_findings(report: MemoryReport,
                           update: bool = False) -> list:
    """MEM101: the per-leaf residency table vs the reviewed snapshot
    (golden.py ``preflight`` block)."""
    from theanompi_tpu.tools.analyze import golden as G

    if update:
        G.update_preflight_golden(report.engine, report.codec,
                                  report.fused,
                                  memory=memory_payload(report))
        return []
    gold = G.load_preflight_golden(report.engine, report.codec,
                                   report.fused)
    path = G.preflight_golden_path(report.engine, report.codec,
                                   report.fused)
    tag = (f"[{report.engine}/{report.codec}"
           f"{'/fused' if report.fused else ''}]")
    if gold is None or "memory" not in gold:
        return [Finding(
            rule="MEM101", path=path, line=0, engine=report.engine,
            message=f"{tag} no memory golden — run `tmpi lint "
                    "--update-golden` and review the residency table",
        )]
    errs = G.diff_payload(gold["memory"], memory_payload(report))
    return [Finding(
        rule="MEM101", path=path, line=0, engine=report.engine,
        message=f"{tag} per-leaf residency drifted from golden: {e} — "
                "if deliberate, regenerate with `tmpi lint "
                "--update-golden` and review the diff",
    ) for e in errs]
