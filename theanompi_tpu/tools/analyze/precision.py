"""Dtype-flow lint — the precision side of ``tmpi preflight``.

A jaxpr dtype-dataflow pass over the same abstract traces the SPMD
analyzer walks (tools/analyze/signature.py — this module reuses its
quantization-evidence convention: track where low-precision values
originate and where they silently widen). Three rule families:

- **PREC001 fp32 island** — inside a bf16 model, a compute-heavy op
  (``dot_general`` / conv) executing with fp32 operands that ORIGINATE
  from bf16 values: an unintended upcast on the hot path. A
  ``dot_general(bf16, bf16) -> f32`` via ``preferred_element_type`` is
  the GOOD pattern (fp32 accumulation on bf16 inputs) and is not
  flagged — the island is ``bf16 -> convert f32 -> matmul(f32)``.
  Pallas kernel BODIES are exempt: a hand-written kernel manages its
  own precision deliberately (the flash-attention softmax statistics
  and the fused-update epilogue are fp32 ON PURPOSE — the latter is
  even enforced the other way by PREC003).
- **PREC002 bf16 accumulation hazard** — an EXPLICIT reduction
  (``reduce_sum``) of >= :data:`ACCUM_MIN_ELEMS` elements accumulating
  IN bf16 (8 mantissa bits swamp). ``dot_general`` is deliberately NOT
  a hazard site regardless of its output dtype: the MXU/XLA accumulate
  a single dot in fp32 internally and round once on output — flagging
  every bf16 transformer matmul would be crying wolf on the sanctioned
  mixed-precision recipe (models/transformer.py). Dots still appear in
  the golden reduction TABLE, so silently narrowing a
  ``preferred_element_type`` fp32 accumulator is caught as PREC101
  drift even though it is not a PREC002 hazard.
- **PREC003 fused-update fp32-math invariant** — the ``--fused-update``
  epilogue (ops/pallas_update.py) must compute in fp32 even for bf16
  params. Pinned STATICALLY here (trace the registry's fused
  optimizers over bf16 params and reject any arithmetic eqn producing
  a sub-fp32 value — the Pallas kernel body included), not just by the
  parity test.
- **PREC101 golden drift** — the per-config dtype-flow signature
  (dtype histogram + reduction table) drifted from the reviewed
  snapshot; widening or narrowing ANY accumulator shows up here even
  when no hazard rule fires.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from theanompi_tpu.tools.analyze.rules import Finding
from theanompi_tpu.tools.analyze.signature import _source_of, _subjaxprs

# reductions at least this long accumulating in bf16 lose mantissa
# bits to swamping; the threshold is deliberately generous (a 4096-term
# bf16 sum is already ~2 decimal digits of error in the worst case)
ACCUM_MIN_ELEMS = 4096

_LOW_PRECISION = ("bfloat16", "float16")
# arithmetic primitives whose sub-fp32 output inside an update
# epilogue violates the fused-update fp32-math invariant
_ARITH_PRIMS = {
    "add", "sub", "mul", "div", "neg", "max", "min", "pow",
    "integer_pow", "sqrt", "rsqrt", "exp", "log", "dot_general",
    "add_any",
}
_MATMUL_PRIMS = {"dot_general", "conv_general_dilated"}


def _dtype_of(var) -> Optional[str]:
    dt = getattr(getattr(var, "aval", None), "dtype", None)
    return None if dt is None else str(dt)


def _is_low(dtype: Optional[str]) -> bool:
    return dtype is not None and dtype.startswith(_LOW_PRECISION)


def iter_eqns(jaxpr):
    """Every eqn reachable from ``jaxpr``, descending into all
    subjaxpr-carrying params (pjit, scan, cond branches, custom_*,
    pallas_call kernels — the precision rules must see kernel bodies,
    unlike the collective walk which treats them as opaque wire)."""
    stack = [jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr]
    seen = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            yield eqn
            for pv in eqn.params.values():
                stack.extend(_subjaxprs(pv))


# --------------------------------------------------------------------------
# dtype-flow signature (the PREC101 golden payload)
# --------------------------------------------------------------------------


def dtype_histogram(jaxpr) -> dict:
    """``{dtype: eqn_output_count}`` over the whole traced program —
    the coarse fingerprint a precision change cannot dodge."""
    hist: dict = {}
    for eqn in iter_eqns(jaxpr):
        for v in eqn.outvars:
            dt = _dtype_of(v)
            if dt is not None:
                hist[dt] = hist.get(dt, 0) + 1
    return hist


def _reduced_elems(eqn) -> int:
    """Elements folded into each output element of a reduction eqn."""
    try:
        in_elems = int(np.prod(eqn.invars[0].aval.shape or (1,)))
        out_elems = int(np.prod(eqn.outvars[0].aval.shape or (1,)))
        return max(1, in_elems // max(1, out_elems))
    except Exception:  # noqa: BLE001 — advisory sizing only
        return 1


def _contraction_elems(eqn) -> int:
    """Contraction length of a dot_general (elements accumulated per
    output element)."""
    try:
        (lhs_c, _), _ = eqn.params["dimension_numbers"]
        shape = eqn.invars[0].aval.shape
        n = 1
        for d in lhs_c:
            n *= int(shape[d])
        return max(1, n)
    except Exception:  # noqa: BLE001
        return 1


def reduction_table(jaxpr) -> list:
    """Ordered accumulation signature: every ``reduce_sum`` and
    ``dot_general`` with its operand dtype, ACCUMULATION dtype (the
    output / preferred_element_type — widening an accumulator changes
    this column, which is exactly the PREC101 golden-drift mutation),
    and folded length."""
    rows = []
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name == "reduce_sum":
            rows.append({
                "prim": name,
                "operand_dtype": _dtype_of(eqn.invars[0]),
                "accum_dtype": _dtype_of(eqn.outvars[0]),
                "elems": _reduced_elems(eqn),
            })
        elif name == "dot_general":
            rows.append({
                "prim": name,
                "operand_dtype": _dtype_of(eqn.invars[0]),
                "accum_dtype": _dtype_of(eqn.outvars[0]),
                "elems": _contraction_elems(eqn),
            })
    return rows


def precision_payload(jaxpr) -> dict:
    return {"dtype_ops": dtype_histogram(jaxpr),
            "reductions": reduction_table(jaxpr)}


# --------------------------------------------------------------------------
# PREC001: fp32 islands in a low-precision model
# --------------------------------------------------------------------------


def fp32_island_findings(jaxpr, engine: str = "",
                         tag: str = "") -> list:
    """Flag compute-heavy ops running in fp32 on values that ORIGINATE
    from bf16/f16 — the silent-upcast hot-path island. Dataflow: a var
    is 'low-origin' when its dtype is low precision, or it was produced
    (transitively) from a low-origin var by a convert/elementwise
    chain. Only matmul-class eqns whose OPERANDS are already fp32 fire
    (bf16-in/fp32-out accumulation is the sanctioned pattern)."""
    out = []
    origin: dict = {}

    def get(v) -> bool:
        if not hasattr(v, "aval") or hasattr(v, "val"):
            return False  # literals
        return origin.get(id(v), False)

    def walk(j):
        for eqn in j.eqns:
            in_low = any(get(v) or _is_low(_dtype_of(v))
                         for v in eqn.invars)
            name = eqn.primitive.name
            if (name in _MATMUL_PRIMS and in_low
                    and all(_dtype_of(v) == "float32"
                            for v in eqn.invars
                            if _dtype_of(v) is not None)):
                f, ln = _source_of(eqn)
                out.append(Finding(
                    rule="PREC001", path=f, line=ln, engine=engine,
                    message=(
                        f"{tag} {name} executes in fp32 on values that "
                        "originated as bf16 — an unintended upcast "
                        "island on the hot path (cast back to bf16 "
                        "before the matmul, or use "
                        "preferred_element_type for fp32 accumulation "
                        "on bf16 operands)"
                    ),
                ))
            if name != "pallas_call":  # kernels manage precision
                for pv in eqn.params.values():
                    for sub in _subjaxprs(pv):
                        if len(sub.invars) == len(eqn.invars):
                            for si, oi in zip(sub.invars, eqn.invars):
                                origin[id(si)] = get(oi) or _is_low(
                                    _dtype_of(oi))
                        else:
                            for si in sub.invars:
                                origin[id(si)] = in_low
                        walk(sub)
            for v in eqn.outvars:
                origin[id(v)] = in_low or _is_low(_dtype_of(v))
    j = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    walk(j)
    return out


# --------------------------------------------------------------------------
# PREC002: long reductions accumulating in bf16
# --------------------------------------------------------------------------


def accumulation_findings(jaxpr, engine: str = "", tag: str = "",
                          min_elems: int = ACCUM_MIN_ELEMS) -> list:
    """Explicit reductions (``reduce_sum``) folding >= ``min_elems``
    elements IN a sub-fp32 dtype. ``dot_general`` is not a hazard site
    (see the module docstring) — a bf16 matmul accumulates fp32 inside
    the MXU and rounds once."""
    out = []
    for row_eqn in iter_eqns(jaxpr):
        name = row_eqn.primitive.name
        if name == "reduce":
            # generic monoid reduce: only the additive monoid
            # accumulates (min/max reductions lose no mantissa)
            if not _reduce_monoid_is_add(row_eqn):
                continue
        elif name != "reduce_sum":
            continue
        acc = _dtype_of(row_eqn.outvars[0])
        elems = _reduced_elems(row_eqn)
        if _is_low(acc) and elems >= min_elems:
            f, ln = _source_of(row_eqn)
            out.append(Finding(
                rule="PREC002", path=f, line=ln, engine=engine,
                message=(
                    f"{tag} {name} folds {elems} elements "
                    f"accumulating in {acc} — widen the "
                    "accumulator to fp32 (8 mantissa bits swamp "
                    f"past ~{min_elems} terms)"
                ),
            ))
    return out


def _reduce_monoid_is_add(eqn) -> bool:
    j = eqn.params.get("jaxpr")
    if j is None:
        return False
    j = j.jaxpr if hasattr(j, "jaxpr") else j
    return any(e.primitive.name in ("add", "add_any") for e in j.eqns)


# --------------------------------------------------------------------------
# PREC003: fused-update epilogue must do fp32 math
# --------------------------------------------------------------------------


def update_math_findings(jaxpr, engine: str = "", tag: str = "",
                         where: str = "fused update") -> list:
    """Reject any arithmetic eqn producing a sub-fp32 value inside an
    optimizer-update program. Converts (the final cast back to the
    param dtype) are exempt — math is not."""
    out = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name not in _ARITH_PRIMS:
            continue
        for v in eqn.outvars:
            if _is_low(_dtype_of(v)):
                f, ln = _source_of(eqn)
                out.append(Finding(
                    rule="PREC003", path=f, line=ln, engine=engine,
                    message=(
                        f"{tag} {eqn.primitive.name} inside the "
                        f"{where} produces {_dtype_of(v)} — the "
                        "epilogue must compute in fp32 even for bf16 "
                        "params (cast in, math fp32, cast out; "
                        "ops/pallas_update.py pins this invariant)"
                    ),
                ))
                break
    return out


def fused_update_invariant_findings() -> list:
    """PREC003 self-check: trace every registered fused optimizer's
    one-pass ``apply`` over bf16 params (fp32 velocity) and verify no
    sub-fp32 arithmetic anywhere — Pallas kernel body included
    (``iter_eqns`` descends into ``pallas_call``)."""
    import jax
    import jax.numpy as jnp

    from theanompi_tpu.ops.pallas_update import _FUSED_BUILDERS

    findings: list = []
    sds = jax.ShapeDtypeStruct
    params = {"w": sds((256,), jnp.bfloat16),
              "b": sds((16,), jnp.bfloat16)}
    grads = params
    for name, builder in sorted(_FUSED_BUILDERS.items()):
        opt = builder()
        state = jax.eval_shape(opt.init, params)
        try:
            jaxpr = jax.make_jaxpr(
                lambda g, s, p: opt.apply(g, s, p, 0.1)
            )(grads, state, params)
        except Exception as e:  # noqa: BLE001 — becomes a finding
            findings.append(Finding(
                rule="PREC003", path="", line=0, engine="",
                message=f"[fused:{name}] fused apply could not be "
                        f"traced over bf16 params: "
                        f"{type(e).__name__}: {e}",
            ))
            continue
        findings.extend(update_math_findings(
            jaxpr, engine="", tag=f"[fused:{name}]",
            where=f"fused '{name}' update"))
    return findings


# --------------------------------------------------------------------------
# the lint-side matrix sweep + golden comparison
# --------------------------------------------------------------------------


def config_findings(name: str, codec: str, fused: bool,
                    update_golden: bool = False) -> list:
    """PREC001/002 + PREC101 for one harness config."""
    from theanompi_tpu.tools.analyze import golden as G, harness

    pre = harness.preflight_trace(name, codec, fused)
    tag = f"[{name}/{codec}{'/fused' if fused else ''}]"
    if pre.error is not None:
        return [Finding(
            rule="PREC101", path="", line=0, engine=name,
            message=f"{tag} precision pre-flight could not trace the "
                    f"step: {pre.error}",
        )]
    findings = []
    findings.extend(fp32_island_findings(pre.jaxpr, engine=name, tag=tag))
    findings.extend(accumulation_findings(pre.jaxpr, engine=name, tag=tag))
    payload = precision_payload(pre.jaxpr)
    if update_golden:
        G.update_preflight_golden(name, codec, fused, precision=payload)
        return findings
    gold = G.load_preflight_golden(name, codec, fused)
    path = G.preflight_golden_path(name, codec, fused)
    if gold is None or "precision" not in gold:
        findings.append(Finding(
            rule="PREC101", path=path, line=0, engine=name,
            message=f"{tag} no precision golden — run `tmpi lint "
                    "--update-golden` and review the dtype-flow "
                    "signature",
        ))
        return findings
    for e in G.diff_payload(gold["precision"], payload):
        findings.append(Finding(
            rule="PREC101", path=path, line=0, engine=name,
            message=f"{tag} dtype-flow signature drifted from golden: "
                    f"{e} — if deliberate, regenerate with `tmpi lint "
                    "--update-golden` and review the diff "
                    "(accumulator widened/narrowed?)",
        ))
    return findings


def analyze_precision(update_golden: bool = False) -> list:
    """The full precision family over the preflight matrix, plus the
    engine-independent fused-update fp32 invariant."""
    from theanompi_tpu.tools.analyze import harness

    findings: list = []
    for name in harness.PREFLIGHT_ENGINES:
        for codec in harness.CODEC_SPECS:
            for fused in harness.FUSED_FLAGS:
                findings.extend(config_findings(
                    name, codec, fused, update_golden=update_golden))
    findings.extend(fused_update_invariant_findings())
    return findings
