"""Engine build + abstract-trace harness for the SPMD analyzer.

Every engine is built against a TINY model on a 2-device mesh and
traced with ``jax.make_jaxpr`` over ``ShapeDtypeStruct`` operands —
nothing is compiled or executed, so the full 5-engine × 2-codec sweep
takes a few seconds on CPU (the ``tmpi lint`` budget is 60 s).

The harness needs >= 2 devices to exist (a 1-device mesh has no
collectives to verify). Under pytest that's the conftest's 8-way
virtual CPU platform; the ``tmpi lint`` CLI sets
``--xla_force_host_platform_device_count`` itself before jax
initializes (see tools/lint.py).

Traces are memoized per process: the analyzed tree cannot change
mid-run, and the lint entrypoints are called repeatedly by the test
suite.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from theanompi_tpu.tools.analyze.signature import (
    Signature,
    extract_signature,
    donated_flags,
)

# the analyzed engine configurations: every driver rule, codec off and
# the error-feedback int8 codec (the convergence-safe compressed
# default) — golden signatures exist for each pair. ``bsp_bucketed``
# is the BSP rule under ``--allreduce-buckets``: its per-bucket psums
# replace the single gradient pmean, so the bucketed collective
# schedule gets its own golden (a bucket whose axis drifts from its
# siblings fails SPMD003; tests/test_analyze.py mutation self-test).
# ``bsp_bucketed_fused`` pins the two PR-11 knobs COMBINED
# (``--allreduce-buckets`` + ``--fused-update``): the per-bucket psum
# schedule must survive the fused epilogue, so the pair gets its own
# golden instead of only the knobs-in-isolation ones. ``bsp_hier`` is
# the hierarchical exchange on a 4-device 2-slice ('dcn','data') mesh:
# in-slice reduce-scatter, cross-slice psum over only the scattered
# shard (the codec'd hop), in-slice all-gather — its golden pins the
# three-collective schedule and SPMD101's per-link split verifies the
# DCN hop's bytes against the declared two-hop model.
ENGINE_NAMES = ("bsp", "bsp_hier", "bsp_bucketed", "bsp_bucketed_fused",
                "zero1", "easgd", "gosgd", "nd")
CODEC_SPECS = ("none", "int8:ef")

# the memory & precision pre-flight matrix (tools/analyze/memory.py /
# precision.py, `tmpi preflight`): the five driver rules, each codec,
# each side of the --fused-update boundary — goldens per triple
# (golden/preflight_*.json). ND runs the momentum recipe here (both
# flags, so the fused/unfused pair differs ONLY by the knob — the LM
# default adam has no fused kernel and is refused loudly).
PREFLIGHT_ENGINES = ("bsp", "zero1", "easgd", "gosgd", "nd")
FUSED_FLAGS = (False, True)
EASGD_AVG_FREQ = 4  # harness exchange cadence (amortization weight)
# bucket size for the bucketed-BSP trace: small enough that the tiny
# model's 4 leaves split into 4 buckets (reverse-order greedy fill)
BUCKET_MB = 0.001


@dataclass
class TracePart:
    """One traced program of an engine (train step; EASGD adds the
    elastic exchange) with its amortization weight — the fraction of
    training steps on which it runs."""

    name: str
    signature: Signature
    axis_sizes: dict
    weight: float = 1.0
    donated: tuple = ()  # donated_invars over the state arg's leaves


@dataclass
class EngineTrace:
    engine: str
    codec: str
    parts: list = field(default_factory=list)
    traffic: Any = None  # obs.comm.TrafficModel (declared wire model)
    declared_donates: bool = False
    module_file: str = ""
    error: Optional[str] = None  # trace failure (e.g. unbound axis)


def _tiny_model():
    """Smallest contract model with a multi-leaf param pytree big
    enough (~6.5k elements) that the int8 codec's 128-block padding is
    noise relative to the traffic tolerances."""
    from theanompi_tpu import nn
    from theanompi_tpu.models.contract import Model, Recipe

    class _AnalyzeTinyMLP(Model):
        name = "analyze-tiny"

        @classmethod
        def default_recipe(cls):
            return Recipe(batch_size=8, input_shape=(8, 8, 3),
                          num_classes=10, optimizer="momentum",
                          dataset="synthetic")

        def build(self):
            return nn.Sequential(
                [nn.Flatten(), nn.Dense(32, name="h"),
                 nn.Activation("relu"),
                 nn.Dense(self.recipe.num_classes, name="out")],
                name="analyze_tiny_mlp",
            )

    return _AnalyzeTinyMLP()


def _tiny_lm():
    from theanompi_tpu.models.lm import TransformerLMModel

    recipe = TransformerLMModel.default_recipe().replace(
        batch_size=8, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        input_shape=(16,), num_classes=32,
    )
    return TransformerLMModel(recipe)


def _mesh2():
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 2:
        raise RuntimeError(
            "SPMD analyzer needs >= 2 devices to trace collectives; "
            "run under the test conftest (8-way virtual CPU) or let "
            "`tmpi lint` set --xla_force_host_platform_device_count"
        )
    return Mesh(np.array(devs[:2]), ("data",))


def _mesh22():
    """2 slices x 2 chips: the smallest mesh where the hierarchical
    exchange exercises both link classes (axis order matches
    parallel/mesh.make_multislice_mesh: DCN outermost)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 4:
        raise RuntimeError(
            "hierarchical-exchange analysis needs >= 4 devices (2 "
            "slices x 2 chips); run under the test conftest (8-way "
            "virtual CPU) or let `tmpi lint` set "
            "--xla_force_host_platform_device_count"
        )
    return Mesh(np.array(devs[:4]).reshape(2, 2), ("dcn", "data"))


def _abstract_state(engine, rng):
    import jax

    return jax.eval_shape(engine.init_state, rng)


def _trace(fn, *args) -> tuple:
    """make_jaxpr over abstract args -> (Signature, axis_sizes,
    donated_flags, jaxpr)."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args)
    sig, axis_sizes = extract_signature(jaxpr)
    return sig, axis_sizes, jaxpr


def _build_one(name: str, codec: str) -> EngineTrace:
    import inspect

    import jax
    import jax.numpy as jnp

    sds = jax.ShapeDtypeStruct
    wire_codec = None if codec == "none" else codec
    out = EngineTrace(engine=name, codec=codec)
    try:
        # inside the try: a device/mesh setup failure must surface as a
        # per-engine finding (SPMD001), not crash the whole lint
        rng = jax.random.PRNGKey(0)
        mesh = _mesh2()
        if name in ("bsp", "bsp_bucketed", "bsp_bucketed_fused"):
            from theanompi_tpu.parallel.bsp import BSPEngine

            model = _tiny_model()
            eng = BSPEngine(
                model, mesh, wire_codec=wire_codec,
                allreduce_buckets=BUCKET_MB if "bucketed" in name
                else 0.0,
                fused_update=name.endswith("_fused"),
            )
            state = _abstract_state(eng, rng)
            x = sds((16, 8, 8, 3), jnp.float32)
            y = sds((16,), jnp.int32)
            step_parts = [("step", eng._steps[False], (state, x, y, rng), 1.0)]
        elif name == "bsp_hier":
            from theanompi_tpu.parallel.bsp import BSPEngine

            model = _tiny_model()
            eng = BSPEngine(model, _mesh22(), strategy="hier",
                            wire_codec=wire_codec)
            state = _abstract_state(eng, rng)
            x = sds((16, 8, 8, 3), jnp.float32)
            y = sds((16,), jnp.int32)
            step_parts = [("step", eng._steps[False], (state, x, y, rng), 1.0)]
        elif name == "zero1":
            from theanompi_tpu.parallel.zero import ZeroEngine

            model = _tiny_model()
            eng = ZeroEngine(model, mesh, wire_codec=wire_codec)
            state = _abstract_state(eng, rng)
            x = sds((16, 8, 8, 3), jnp.float32)
            y = sds((16,), jnp.int32)
            step_parts = [("step", eng._steps[False], (state, x, y, rng), 1.0)]
        elif name == "easgd":
            from theanompi_tpu.parallel.easgd import EASGDEngine

            model = _tiny_model()
            eng = EASGDEngine(model, mesh, avg_freq=EASGD_AVG_FREQ,
                              wire_codec=wire_codec)
            state = _abstract_state(eng, rng)
            x = sds((16, 8, 8, 3), jnp.float32)
            y = sds((16,), jnp.int32)
            step_parts = [
                ("step", eng._steps[False], (state, x, y, rng), 1.0),
                ("exchange", eng._exchange, (state,),
                 1.0 / EASGD_AVG_FREQ),
            ]
        elif name == "gosgd":
            from theanompi_tpu.parallel.gosgd import GOSGDEngine

            model = _tiny_model()
            eng = GOSGDEngine(model, mesh, wire_codec=wire_codec)
            state = _abstract_state(eng, rng)
            x = sds((16, 8, 8, 3), jnp.float32)
            y = sds((16,), jnp.int32)
            # the with-gossip step variant: gossip_every=1, so the
            # ppermute rides EVERY step (weight 1 == its exchange_every)
            step_parts = [("step", eng._steps[(True, False)],
                           (state, x, y, rng), 1.0)]
        elif name == "nd":
            from theanompi_tpu.parallel.nd import NDEngine

            model = _tiny_lm()
            eng = NDEngine(model, mesh, dp_axis="data",
                           wire_codec=wire_codec)
            state = _abstract_state(eng, rng)
            tok = sds((16, 16), jnp.int32)
            step_parts = [("step", eng._steps[False], (state, tok, rng), 1.0)]
        else:
            raise ValueError(f"unknown engine {name!r}")

        out.declared_donates = bool(getattr(eng, "donates_state", False))
        out.module_file = inspect.getsourcefile(type(eng)) or ""
        out.traffic = eng.traffic_model(state)
        n_state = len(jax.tree_util.tree_leaves(state))
        for part_name, fn, args, weight in step_parts:
            sig, axis_sizes, jaxpr = _trace(fn, *args)
            out.parts.append(TracePart(
                name=part_name, signature=sig, axis_sizes=axis_sizes,
                weight=weight,
                donated=donated_flags(jaxpr, n_state),
            ))
    except Exception as e:  # noqa: BLE001 — surfaced as a finding
        out.error = f"{type(e).__name__}: {e}"
    return out


_TRACE_CACHE: dict = {}


def trace_engine(name: str, codec: str) -> EngineTrace:
    key = (name, codec)
    if key not in _TRACE_CACHE:
        _TRACE_CACHE[key] = _build_one(name, codec)
    return _TRACE_CACHE[key]


def trace_all() -> dict:
    """{(engine, codec): EngineTrace} for the full analyzed matrix."""
    return {(n, c): trace_engine(n, c)
            for n in ENGINE_NAMES for c in CODEC_SPECS}


# --------------------------------------------------------------------------
# preflight harness: engine x codec x fused configs with abstract
# operands + the raw traced jaxpr, for the memory & precision families
# (tools/analyze/memory.py / precision.py, `tmpi preflight`)
# --------------------------------------------------------------------------


@dataclass
class PreflightTrace:
    """One preflight configuration: the built engine, its ABSTRACT
    state/operands (nothing materialized), the jitted numerics-off step
    ready to lower, the raw traced jaxpr for the dtype-flow pass, and
    the engine's declared memory model.

    ``parts`` is THE per-engine traced-program enumeration —
    ``(name, jitted_fn, args, weight)`` with weight the fraction of
    training steps the program runs on (EASGD adds its elastic exchange
    at 1/avg_freq, mirroring the SPMD harness) — so consumers like the
    sharding analyzer iterate one list instead of re-hardcoding which
    engines carry a second program. ``parts[0]`` is always the step
    (== ``step_fn``/``step_args``)."""

    engine: str
    codec: str
    fused: bool
    eng: Any = None
    state: Any = None
    step_fn: Any = None
    step_args: tuple = ()
    jaxpr: Any = None
    memory: Any = None  # utils/flops.MemoryModel
    declared_donates: bool = False
    module_file: str = ""
    parts: list = field(default_factory=list)
    error: Optional[str] = None


def _build_preflight(name: str, codec: str, fused: bool) -> PreflightTrace:
    import inspect

    import jax
    import jax.numpy as jnp

    sds = jax.ShapeDtypeStruct
    wire_codec = None if codec == "none" else codec
    out = PreflightTrace(engine=name, codec=codec, fused=bool(fused))
    try:
        rng = jax.random.PRNGKey(0)
        mesh = _mesh2()
        if name == "bsp":
            from theanompi_tpu.parallel.bsp import BSPEngine

            eng = BSPEngine(_tiny_model(), mesh, wire_codec=wire_codec,
                            fused_update=fused)
        elif name == "zero1":
            from theanompi_tpu.parallel.zero import ZeroEngine

            eng = ZeroEngine(_tiny_model(), mesh, wire_codec=wire_codec,
                             fused_update=fused)
        elif name == "easgd":
            from theanompi_tpu.parallel.easgd import EASGDEngine

            eng = EASGDEngine(_tiny_model(), mesh,
                              avg_freq=EASGD_AVG_FREQ,
                              wire_codec=wire_codec, fused_update=fused)
        elif name == "gosgd":
            from theanompi_tpu.parallel.gosgd import GOSGDEngine

            eng = GOSGDEngine(_tiny_model(), mesh, wire_codec=wire_codec,
                              fused_update=fused)
        elif name == "nd":
            from theanompi_tpu.models.lm import TransformerLMModel
            from theanompi_tpu.parallel.nd import NDEngine

            recipe = TransformerLMModel.default_recipe().replace(
                batch_size=8, d_model=32, n_heads=4, n_layers=2,
                d_ff=64, input_shape=(16,), num_classes=32,
                optimizer="momentum",  # fused-capable on BOTH flags
            )
            eng = NDEngine(TransformerLMModel(recipe), mesh,
                           dp_axis="data", wire_codec=wire_codec,
                           fused_update=fused)
        else:
            raise ValueError(f"unknown preflight engine {name!r}")

        state = _abstract_state(eng, rng)
        if name == "nd":
            step_fn = eng._steps[False]
            args = (state, sds((16, 16), jnp.int32), rng)
        elif name == "gosgd":
            step_fn = eng._steps[(True, False)]
            args = (state, sds((16, 8, 8, 3), jnp.float32),
                    sds((16,), jnp.int32), rng)
        else:
            step_fn = eng._steps[False]
            args = (state, sds((16, 8, 8, 3), jnp.float32),
                    sds((16,), jnp.int32), rng)
        out.eng = eng
        out.state = state
        out.step_fn = step_fn
        out.step_args = args
        out.jaxpr = jax.make_jaxpr(step_fn)(*args)
        out.parts = [("step", step_fn, args, 1.0)]
        if name == "easgd":
            # the elastic exchange is a second compiled program, run
            # every avg_freq steps — same enumeration the SPMD harness
            # traces (_build_one's step_parts)
            out.parts.append(("exchange", eng._exchange, (state,),
                              1.0 / EASGD_AVG_FREQ))
        out.memory = eng.memory_model(state)
        out.declared_donates = bool(getattr(eng, "donates_state", False))
        out.module_file = inspect.getsourcefile(type(eng)) or ""
    except Exception as e:  # noqa: BLE001 — surfaced as a finding
        out.error = f"{type(e).__name__}: {e}"
    return out


_PREFLIGHT_CACHE: dict = {}


def preflight_trace(name: str, codec: str, fused: bool) -> PreflightTrace:
    key = (name, codec, bool(fused))
    if key not in _PREFLIGHT_CACHE:
        _PREFLIGHT_CACHE[key] = _build_preflight(name, codec, fused)
    return _PREFLIGHT_CACHE[key]
