"""Golden collective-signature snapshots.

One JSON file per (engine, codec) pair under
``theanompi_tpu/tools/analyze/golden/`` pins the exact ordered
collective schedule the engine's traced step posts — primitive, axis
names, operand dtype/shape, static trip count, per traced part
(``step``; EASGD adds ``exchange``). Any change to an engine's
collective schedule — a new psum, a reordered exchange, a dtype change
on the wire — fails ``tmpi lint`` (rule SPMD003) until the author
regenerates the snapshot with ``tmpi lint --update-golden`` and the
diff is reviewed as a deliberate wire-protocol change.

The snapshots are traced on the harness's fixed tiny-model 2-device
configuration, so shapes are stable; they pin the SCHEDULE, not the
model.
"""

from __future__ import annotations

import json
import os
from typing import Optional

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "golden")


def golden_path(engine: str, codec: str) -> str:
    tag = codec.replace(":", "_")
    return os.path.join(GOLDEN_DIR, f"{engine}_{tag}.json")


def signature_payload(trace) -> dict:
    """Serializable snapshot of an EngineTrace's collective schedule."""
    return {
        "engine": trace.engine,
        "codec": trace.codec,
        "parts": {
            p.name: p.signature.as_json() for p in trace.parts
        },
    }


def load_golden(engine: str, codec: str) -> Optional[dict]:
    path = golden_path(engine, codec)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def write_golden(trace) -> str:
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    path = golden_path(trace.engine, trace.codec)
    with open(path, "w") as f:
        json.dump(signature_payload(trace), f, indent=1, sort_keys=True)
        f.write("\n")
    return path


# --------------------------------------------------------------------------
# preflight goldens (memory & precision pre-flight, ISSUE 12): one JSON
# per (engine, codec, fused) triple holding the per-leaf HBM residency
# table (tools/analyze/memory.py) and the dtype-flow signature
# (tools/analyze/precision.py). Same contract as the collective
# snapshots: any drift fails `tmpi lint` (MEM101 / PREC101) until
# `tmpi lint --update-golden` regenerates it and the diff is reviewed.
# --------------------------------------------------------------------------


def preflight_golden_path(engine: str, codec: str, fused: bool) -> str:
    tag = codec.replace(":", "_")
    knob = "fused" if fused else "unfused"
    return os.path.join(GOLDEN_DIR, f"preflight_{engine}_{tag}_{knob}.json")


def load_preflight_golden(engine: str, codec: str,
                          fused: bool) -> Optional[dict]:
    path = preflight_golden_path(engine, codec, fused)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def update_preflight_golden(engine: str, codec: str, fused: bool,
                            memory: Optional[dict] = None,
                            precision: Optional[dict] = None) -> str:
    """Merge one family's payload into the config's golden file (the
    memory and precision passes regenerate independently under
    ``--update-golden``; each owns its block)."""
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    path = preflight_golden_path(engine, codec, fused)
    payload = load_preflight_golden(engine, codec, fused) or {
        "engine": engine, "codec": codec, "fused": bool(fused),
    }
    if memory is not None:
        payload["memory"] = memory
    if precision is not None:
        payload["precision"] = precision
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


# --------------------------------------------------------------------------
# sharding goldens (sharding & layout analyzer, ISSUE 15): one JSON per
# (engine, codec, fused) triple pinning the DECLARED per-leaf
# PartitionSpec table (the engine's ShardingRecipe resolution) — any
# drift fails `tmpi lint` (SHARD101) until `tmpi lint --update-golden`
# regenerates it and the diff is reviewed as a deliberate layout change.
# --------------------------------------------------------------------------


def sharding_golden_path(engine: str, codec: str, fused: bool) -> str:
    tag = codec.replace(":", "_")
    knob = "fused" if fused else "unfused"
    return os.path.join(GOLDEN_DIR, f"sharding_{engine}_{tag}_{knob}.json")


def load_sharding_golden(engine: str, codec: str,
                         fused: bool) -> Optional[dict]:
    path = sharding_golden_path(engine, codec, fused)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def write_sharding_golden(engine: str, codec: str, fused: bool,
                          payload: dict) -> str:
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    path = sharding_golden_path(engine, codec, fused)
    full = {"engine": engine, "codec": codec, "fused": bool(fused),
            **payload}
    with open(path, "w") as f:
        json.dump(full, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def diff_payload(gold, current, prefix: str = "") -> list:
    """Human-readable recursive diff strings between two JSON-shaped
    payloads ([] = identical) — shared by the preflight golden
    comparisons (a drifted accumulator dtype or residency row names
    its path)."""
    if type(gold) is not type(current):
        return [f"{prefix or 'payload'}: golden {gold!r} != "
                f"current {current!r}"]
    if isinstance(gold, dict):
        errs = []
        for k in sorted(set(gold) | set(current)):
            if k not in gold:
                errs.append(f"{prefix}.{k} appeared")
            elif k not in current:
                errs.append(f"{prefix}.{k} disappeared")
            else:
                errs.extend(diff_payload(gold[k], current[k],
                                         f"{prefix}.{k}"))
        return errs
    if isinstance(gold, list):
        errs = []
        if len(gold) != len(current):
            errs.append(f"{prefix}: {len(gold)} entries in golden, "
                        f"{len(current)} current")
        for i, (g, c) in enumerate(zip(gold, current)):
            errs.extend(diff_payload(g, c, f"{prefix}[{i}]"))
        return errs
    if gold != current:
        return [f"{prefix}: golden {gold!r} != current {current!r}"]
    return []


def compare_golden(trace, golden: dict) -> list:
    """Human-readable mismatch strings ([] = signatures identical)."""
    current = signature_payload(trace)
    errs = []
    cur_parts, gold_parts = current["parts"], golden.get("parts", {})
    for name in sorted(set(cur_parts) | set(gold_parts)):
        cur = cur_parts.get(name)
        gold = gold_parts.get(name)
        if cur is None or gold is None:
            errs.append(f"part {name!r} {'appeared' if gold is None else 'disappeared'}")
            continue
        if cur == gold:
            continue
        if len(cur) != len(gold):
            errs.append(
                f"part {name!r}: {len(gold)} collectives in golden, "
                f"{len(cur)} traced"
            )
        for i, (c, g) in enumerate(zip(cur, gold)):
            if c != g:
                errs.append(f"part {name!r} collective #{i}: golden {g} "
                            f"!= traced {c}")
    return errs
