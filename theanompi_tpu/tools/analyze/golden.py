"""Golden collective-signature snapshots.

One JSON file per (engine, codec) pair under
``theanompi_tpu/tools/analyze/golden/`` pins the exact ordered
collective schedule the engine's traced step posts — primitive, axis
names, operand dtype/shape, static trip count, per traced part
(``step``; EASGD adds ``exchange``). Any change to an engine's
collective schedule — a new psum, a reordered exchange, a dtype change
on the wire — fails ``tmpi lint`` (rule SPMD003) until the author
regenerates the snapshot with ``tmpi lint --update-golden`` and the
diff is reviewed as a deliberate wire-protocol change.

The snapshots are traced on the harness's fixed tiny-model 2-device
configuration, so shapes are stable; they pin the SCHEDULE, not the
model.
"""

from __future__ import annotations

import json
import os
from typing import Optional

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "golden")


def golden_path(engine: str, codec: str) -> str:
    tag = codec.replace(":", "_")
    return os.path.join(GOLDEN_DIR, f"{engine}_{tag}.json")


def signature_payload(trace) -> dict:
    """Serializable snapshot of an EngineTrace's collective schedule."""
    return {
        "engine": trace.engine,
        "codec": trace.codec,
        "parts": {
            p.name: p.signature.as_json() for p in trace.parts
        },
    }


def load_golden(engine: str, codec: str) -> Optional[dict]:
    path = golden_path(engine, codec)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def write_golden(trace) -> str:
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    path = golden_path(trace.engine, trace.codec)
    with open(path, "w") as f:
        json.dump(signature_payload(trace), f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def compare_golden(trace, golden: dict) -> list:
    """Human-readable mismatch strings ([] = signatures identical)."""
    current = signature_payload(trace)
    errs = []
    cur_parts, gold_parts = current["parts"], golden.get("parts", {})
    for name in sorted(set(cur_parts) | set(gold_parts)):
        cur = cur_parts.get(name)
        gold = gold_parts.get(name)
        if cur is None or gold is None:
            errs.append(f"part {name!r} {'appeared' if gold is None else 'disappeared'}")
            continue
        if cur == gold:
            continue
        if len(cur) != len(gold):
            errs.append(
                f"part {name!r}: {len(gold)} collectives in golden, "
                f"{len(cur)} traced"
            )
        for i, (c, g) in enumerate(zip(cur, gold)):
            if c != g:
                errs.append(f"part {name!r} collective #{i}: golden {g} "
                            f"!= traced {c}")
    return errs
