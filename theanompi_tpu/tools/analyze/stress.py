"""Deterministic thread-stress harness: the RACE analyzer's dynamic twin.

The static pass (tools/analyze/concurrency.py) proves lock DISCIPLINE;
it cannot prove the discipline is sufficient. This harness shakes the
real objects — dispatcher flush-vs-drain, serve param-swap under
request hammering, the metrics sink under scrubber-vs-close — hard
enough that a dropped lock actually loses the race inside a bounded
tier-1 test:

- **Seeded switch-interval shrinking.** Rounds run under
  ``sys.setswitchinterval`` values descending to 1e-6 s — thousands of
  preemption points per critical section instead of the default
  5 ms — with the schedule drawn from a seeded RNG so a failure
  reproduces from its seed.
- **Barrier-released threads.** Every scenario thread blocks on one
  barrier and starts in the same scheduler quantum: the interleaving
  the race needs happens in round one, not round ten thousand.
- **Injectable delay hooks.** :func:`inject_delay` wraps a method (or
  any attribute lookup) of a live object with a seeded pre/post sleep
  — widening exactly the windows the static analyzer identified as
  critical sections, so "check passes then the world changes" races
  become near-deterministic instead of one-in-a-million.
- **Thread-exception capture.** ``threading.excepthook`` is patched
  per round: a worker thread dying (ValueError on a closed file, an
  AttributeError off a torn publish) is a recorded violation, not a
  silent stderr line.
- **Deadlock bounding.** Threads that fail to join inside the round
  budget are a ``deadlock:`` violation; the harness never hangs the
  suite (the stuck daemon thread is abandoned, the run reports it).

Every run can drop one ``kind=stress`` JSONL record (schema:
tools/check_obs_schema.py) into ``<obs_dir>/stress.jsonl`` so stress
evidence rides the same telemetry stream as everything else.

Usage (tests/test_stress.py are the canonical drivers)::

    h = StressHarness(seed=0)
    res = h.run("metrics-sink", make_scenario, rounds=30)
    assert res.ok, res.violations

where ``make_scenario(rng)`` returns a :class:`Scenario` — fresh
objects per round, ``threads`` callables to race, and a ``check()``
returning invariant-violation strings.
"""

from __future__ import annotations

import json
import os
import random
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

# descending preemption pressure; the smallest value yields a context
# switch roughly every few bytecodes
DEFAULT_SWITCH_INTERVALS = (0.005, 1e-4, 1e-6)


@dataclass
class Scenario:
    """One stress round: fresh ``threads`` to race (each a 0-arg
    callable), an invariant ``check`` run after they join (returns a
    list of violation strings), and an optional ``cleanup``."""

    threads: List[Callable[[], None]]
    check: Optional[Callable[[], List[str]]] = None
    cleanup: Optional[Callable[[], None]] = None


@dataclass
class StressResult:
    scenario: str
    seed: int
    rounds: int = 0
    violations: List[str] = field(default_factory=list)
    seconds: float = 0.0
    switch_interval_min: float = min(DEFAULT_SWITCH_INTERVALS)

    @property
    def ok(self) -> bool:
        return not self.violations

    def as_record(self) -> dict:
        """One ``kind=stress`` JSONL record (schema:
        tools/check_obs_schema.py)."""
        return {
            "kind": "stress",
            "t": time.time(),
            "scenario": self.scenario,
            "seed": int(self.seed),
            "rounds": int(self.rounds),
            "ok": self.ok,
            "violations": ",".join(
                v.splitlines()[0][:160] for v in self.violations[:8]),
            "seconds": round(self.seconds, 3),
            "switch_interval_min": self.switch_interval_min,
        }


def inject_delay(obj, name: str, rng: random.Random,
                 before_s: float = 0.0, after_s: float = 0.0):
    """Wrap ``obj.name`` (a bound method or callable attribute) with a
    seeded sleep of up to ``before_s``/``after_s`` seconds around each
    call — the injectable delay hook that widens an
    analyzer-identified critical section. Returns an ``undo``
    callable. The jitter is drawn from ``rng`` per call, so the
    schedule is deterministic under a fixed seed and a fixed thread
    interleaving."""
    orig = getattr(obj, name)
    was_instance_attr = name in vars(obj)

    def wrapped(*args, **kwargs):
        if before_s:
            time.sleep(rng.random() * before_s)
        try:
            return orig(*args, **kwargs)
        finally:
            if after_s:
                time.sleep(rng.random() * after_s)

    setattr(obj, name, wrapped)

    def undo():
        if was_instance_attr:
            setattr(obj, name, orig)
        else:
            # the original came from the class: drop the instance
            # shadow instead of pinning a bound method onto it
            delattr(obj, name)

    return undo


class _NullLock:
    """A lock that locks nothing — stand-in used by the mutation
    self-tests to simulate a DROPPED lock on a live object without
    source surgery (replacing ``obj._lock`` with this is semantically
    the seeded defect the static pass flags as RACE002).

    ``enter_delay``: optional 0-arg callable run on ``__enter__`` —
    the dropped lock's acquisition point is exactly where the removed
    serialization used to sit, so a seeded sleep there widens the
    check-then-act window the way an unlucky scheduler preemption
    would, making the loss near-deterministic inside a bounded test."""

    def __init__(self, enter_delay: Optional[Callable[[], None]] = None):
        self._enter_delay = enter_delay

    def __enter__(self):
        if self._enter_delay is not None:
            self._enter_delay()
        return self

    def __exit__(self, *exc):
        return False

    def acquire(self, *a, **k):
        if self._enter_delay is not None:
            self._enter_delay()
        return True

    def release(self):
        pass


class StressHarness:
    """Run scenarios under shrinking switch intervals with exception
    capture and a wall budget. ``obs_dir``: write one ``kind=stress``
    record per :meth:`run` into ``stress.jsonl``."""

    def __init__(self, seed: int = 0, obs_dir: Optional[str] = None):
        self.seed = int(seed)
        self.obs_dir = obs_dir

    def run(
        self,
        scenario: str,
        make_scenario: Callable[[random.Random], Scenario],
        rounds: int = 20,
        switch_intervals=DEFAULT_SWITCH_INTERVALS,
        join_s: float = 20.0,
        wall_budget_s: float = 60.0,
    ) -> StressResult:
        rng = random.Random(self.seed)
        res = StressResult(scenario=scenario, seed=self.seed,
                           switch_interval_min=min(switch_intervals))
        prev_interval = sys.getswitchinterval()
        prev_hook = threading.excepthook
        t0 = time.perf_counter()
        try:
            for i in range(rounds):
                if time.perf_counter() - t0 > wall_budget_s:
                    break  # bounded: a tier-1 stress must end on time
                # shrinking schedule: the first rounds sweep every
                # interval (coarse preemption finds the easy races),
                # the long tail hammers the finest one
                si = (switch_intervals[i % len(switch_intervals)]
                      if i < 2 * len(switch_intervals)
                      else min(switch_intervals))
                errors: list = []

                def hook(args, _errors=errors):
                    _errors.append(
                        f"{args.thread.name}: "
                        f"{args.exc_type.__name__}: {args.exc_value}")

                sc = make_scenario(rng)
                barrier = threading.Barrier(len(sc.threads) + 1)

                def release_then(fn, barrier=barrier):
                    def runner():
                        barrier.wait(timeout=join_s)
                        fn()
                    return runner

                threads = [
                    threading.Thread(target=release_then(fn),
                                     name=f"tmpi-stress-{j}", daemon=True)
                    for j, fn in enumerate(sc.threads)
                ]
                threading.excepthook = hook
                sys.setswitchinterval(si)
                broken = None
                try:
                    for t in threads:
                        t.start()
                    barrier.wait(timeout=join_s)  # all start together
                    deadline = time.monotonic() + join_s
                    for t in threads:
                        t.join(max(0.0, deadline - time.monotonic()))
                    stuck = [t.name for t in threads if t.is_alive()]
                except (threading.BrokenBarrierError, RuntimeError) as e:
                    # an overloaded box delaying a spawn past join_s
                    # breaks the barrier (or t.start() hits the thread
                    # limit) — a recorded violation, never an escaped
                    # exception aborting the tier-1 test
                    broken = repr(e)
                    stuck = [t.name for t in threads if t.is_alive()]
                finally:
                    sys.setswitchinterval(prev_interval)
                    threading.excepthook = prev_hook
                res.rounds += 1
                if broken is not None:
                    res.violations.append(
                        f"round {i} (seed {self.seed}, switch {si}): "
                        f"start barrier broken: {broken} (stuck: "
                        f"{stuck or 'none'})")
                    continue
                if stuck:
                    res.violations.append(
                        f"round {i} (seed {self.seed}, switch {si}): "
                        f"deadlock: threads still alive after "
                        f"{join_s:.0f}s: {stuck}")
                    # abandoned daemons: do not run check/cleanup
                    # against state they still mutate
                    continue
                for e in errors:
                    res.violations.append(
                        f"round {i} (seed {self.seed}, switch {si}): "
                        f"thread exception: {e}")
                if sc.check is not None:
                    for v in sc.check():
                        res.violations.append(
                            f"round {i} (seed {self.seed}, switch {si}): "
                            f"{v}")
                if sc.cleanup is not None:
                    sc.cleanup()
        finally:
            sys.setswitchinterval(prev_interval)
            threading.excepthook = prev_hook
            res.seconds = time.perf_counter() - t0
            self._write_record(res)
        return res

    def _write_record(self, res: StressResult) -> None:
        if self.obs_dir is None:
            return
        os.makedirs(self.obs_dir, exist_ok=True)
        with open(os.path.join(self.obs_dir, "stress.jsonl"), "a") as f:
            f.write(json.dumps(res.as_record()) + "\n")
