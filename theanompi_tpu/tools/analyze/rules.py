"""The four jaxpr-level rule families over the engine traces.

Rule IDs (full catalog incl. AST/host rules: tools/lint.py RULES):

- **SPMD001 collective safety / axis existence** — an engine whose
  step cannot even be traced because a collective names an axis the
  mesh does not bind (jax raises at trace time; the analyzer converts
  the failure into a finding instead of crashing the lint).
- **SPMD002 divergent control flow** — a collective under a ``cond``
  whose predicate may differ across ranks with branch collective
  sequences that differ, or under a ``while`` whose trip count
  depends on rank-varying data (signature.py's uniformity analysis).
  The deadlock class.
- **SPMD003 golden-signature drift** — the traced ordered collective
  schedule differs from the reviewed snapshot (golden.py).
- **SPMD101 traffic-model drift** — wire bytes summed from the traced
  (codec-off) jaxpr disagree with the engine's declared
  ``traffic_model()`` raw bytes beyond tolerance.
- **SPMD102 codec realization** — the ``int8:ef`` trace shows no
  quantization evidence, or the compression ratio implied by the
  traces disagrees with the declared ``compression_ratio`` beyond
  tolerance — the ``tmpi_comm_*`` gauges would be advertising a win
  the program doesn't implement.
- **SPMD201 donation audit** — an engine declaring
  ``donates_state=True`` whose lowered step does not actually donate
  the state buffers (HBM doubles silently under the async pipeline).

Tolerances: the traced totals include the engines' scalar metric
pmeans (loss/error), which the analytic models deliberately exclude,
and the int8 codec's declared model pads to 128-element blocks the
value-space trace doesn't reshape — both are sub-percent on the
harness model, so the tolerances below are drift detectors (2x-wrong
formulas, forgotten amortization, dead codecs), not byte-exact
assertions.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from theanompi_tpu.tools.analyze import harness
from theanompi_tpu.tools.analyze.signature import (
    has_quantized_collective,
    signature_effective_bytes,
    signature_link_bytes,
    signature_raw_bytes,
)

TRAFFIC_REL_TOL = 0.08  # SPMD101: traced vs declared raw bytes
TRAFFIC_ABS_TOL = 512.0  # small-model scalar-metrics slack (bytes)
RATIO_REL_TOL = 0.08  # SPMD102: traced vs declared compression ratio


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    engine: str = ""

    def as_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "engine": self.engine, "message": self.message}


def control_flow_findings(trace) -> list:
    """SPMD002 from the uniformity analysis of every traced part."""
    out = []
    for part in trace.parts:
        for issue in part.signature.issues:
            out.append(Finding(
                rule="SPMD002", path=issue.file, line=issue.line,
                engine=trace.engine,
                message=f"[{trace.engine}/{trace.codec}:{part.name}] "
                        f"{issue.detail}",
            ))
    return out


def axis_findings(trace) -> list:
    """SPMD001: trace failures (unbound axis etc.) and collectives
    naming axes the engine mesh does not carry."""
    if trace.error is not None:
        hint = (" — a collective likely names an axis the engine mesh "
                "does not bind") if "axis" in trace.error.lower() else ""
        return [Finding(
            rule="SPMD001", path=trace.module_file, line=0,
            engine=trace.engine,
            message=f"[{trace.engine}/{trace.codec}] step could not be "
                    f"traced: {trace.error}{hint}",
        )]
    out = []
    for part in trace.parts:
        known = set(part.axis_sizes)
        for c in part.signature.collectives:
            missing = [a for a in c.axes if a not in known]
            if missing:
                out.append(Finding(
                    rule="SPMD001", path=c.file, line=c.line,
                    engine=trace.engine,
                    message=f"[{trace.engine}/{trace.codec}:{part.name}] "
                            f"{c.prim} over axis {missing} not present on "
                            f"the engine mesh (axes: {sorted(known)})",
                ))
    return out


def donation_findings_for(trace) -> list:
    """SPMD201: declared donates_state vs the lowered programs."""
    if trace.error is not None or not trace.declared_donates:
        return []
    out = []
    for part in trace.parts:
        if part.donated and not all(part.donated):
            undonated = sum(1 for d in part.donated if not d)
            out.append(Finding(
                rule="SPMD201", path=trace.module_file, line=0,
                engine=trace.engine,
                message=(
                    f"[{trace.engine}/{trace.codec}:{part.name}] engine "
                    f"declares donates_state=True but {undonated}/"
                    f"{len(part.donated)} state buffers are NOT donated "
                    "in the lowered step — every in-flight dispatch "
                    "holds a second state copy in HBM"
                ),
            ))
        elif not part.donated:
            out.append(Finding(
                rule="SPMD201", path=trace.module_file, line=0,
                engine=trace.engine,
                message=f"[{trace.engine}/{trace.codec}:{part.name}] "
                        "engine declares donates_state=True but the "
                        "traced step carries no donation markers at all",
            ))
    return out


def _traced_raw_amortized(trace) -> float:
    return sum(
        signature_raw_bytes(p.signature, p.axis_sizes) * p.weight
        for p in trace.parts
    )


def _traced_dcn_raw_amortized(trace) -> float:
    return sum(
        signature_link_bytes(p.signature, p.axis_sizes)["dcn"] * p.weight
        for p in trace.parts
    )


def _traced_effective_amortized(trace, codec_bytes: float) -> float:
    return sum(
        signature_effective_bytes(p.signature, p.axis_sizes, codec_bytes)
        * p.weight
        for p in trace.parts
    )


def traffic_findings(trace_off, declared=None) -> list:
    """SPMD101 on the codec-off trace: traced raw bytes vs the
    engine's declared ``traffic_model()`` raw bytes (amortized).
    ``declared`` overrides the trace's own TrafficModel (tests)."""
    if trace_off.error is not None:
        return []
    tm = declared if declared is not None else trace_off.traffic
    out = []
    traced = _traced_raw_amortized(trace_off)
    want = float(tm.raw_bytes_per_step_amortized)
    tol = max(TRAFFIC_ABS_TOL, TRAFFIC_REL_TOL * max(traced, want))
    if abs(traced - want) > tol:
        out.append(Finding(
            rule="SPMD101", path=trace_off.module_file, line=0,
            engine=trace_off.engine,
            message=(
                f"[{trace_off.engine}] traffic_model() declares "
                f"{want:.0f} raw B/step (amortized) but the traced "
                f"jaxpr moves {traced:.0f} B/step — the tmpi_comm_* "
                "gauges are drifting from the program; fix the "
                "analytic model or the exchange"
            ),
        ))
    # per-link-class leg: the DCN share of the traced wire (bytes on
    # slice-spanning hops) vs the model's declared raw DCN bytes. ICI
    # is the complement of the total, so total + DCN pins both classes.
    # Single-slice engines are trivially consistent (both sides 0).
    want_dcn = getattr(tm, "raw_dcn_bytes_per_step", None)
    if want_dcn is not None:
        traced_dcn = _traced_dcn_raw_amortized(trace_off)
        want_dcn = float(want_dcn)
        tol = max(TRAFFIC_ABS_TOL,
                  TRAFFIC_REL_TOL * max(traced_dcn, want_dcn))
        if abs(traced_dcn - want_dcn) > tol:
            out.append(Finding(
                rule="SPMD101", path=trace_off.module_file, line=0,
                engine=trace_off.engine,
                message=(
                    f"[{trace_off.engine}] traffic_model() declares "
                    f"{want_dcn:.0f} raw DCN B/step (amortized) but the "
                    f"traced jaxpr puts {traced_dcn:.0f} B/step on "
                    "slice-spanning hops — the per-link-class gauges "
                    "(tmpi_comm_dcn_*) are drifting from the program"
                ),
            ))
    return out


def codec_findings(trace_off, trace_on, declared=None) -> list:
    """SPMD102 on the codec-on trace: quantization evidence must exist
    and the traced compression ratio must match the declared one."""
    if trace_off.error is not None or trace_on.error is not None:
        return []
    tm = declared if declared is not None else trace_on.traffic
    out = []
    if not any(has_quantized_collective(p.signature)
               for p in trace_on.parts):
        out.append(Finding(
            rule="SPMD102", path=trace_on.module_file, line=0,
            engine=trace_on.engine,
            message=(
                f"[{trace_on.engine}/{trace_on.codec}] codec-on trace "
                "shows NO quantization evidence on any collective — the "
                "codec is configured but the exchange never routes "
                "through it"
            ),
        ))
        return out
    from theanompi_tpu.parallel.codec import get_codec

    codec = get_codec(trace_on.codec)
    raw = _traced_raw_amortized(trace_off)
    eff = _traced_effective_amortized(trace_on,
                                      codec.wire_bytes_per_element)
    traced_ratio = raw / eff if eff > 0 else 1.0
    want = float(tm.compression_ratio)
    if want > 0 and abs(traced_ratio - want) / want > RATIO_REL_TOL:
        out.append(Finding(
            rule="SPMD102", path=trace_on.module_file, line=0,
            engine=trace_on.engine,
            message=(
                f"[{trace_on.engine}/{trace_on.codec}] declared "
                f"compression_ratio {want:.2f} but the traces realize "
                f"{traced_ratio:.2f} (raw {raw:.0f} B -> effective "
                f"{eff:.0f} B) — the gauges' claimed win and the "
                "program disagree"
            ),
        ))
    return out


def golden_findings(trace, update: bool = False) -> list:
    """SPMD003: traced signature vs the reviewed snapshot (or rewrite
    it under ``--update-golden``)."""
    from theanompi_tpu.tools.analyze import golden as G

    if trace.error is not None:
        return []
    if update:
        G.write_golden(trace)
        return []
    gold = G.load_golden(trace.engine, trace.codec)
    if gold is None:
        return [Finding(
            rule="SPMD003", path=G.golden_path(trace.engine, trace.codec),
            line=0, engine=trace.engine,
            message=(
                f"no golden collective signature for "
                f"{trace.engine}/{trace.codec} — run "
                "`tmpi lint --update-golden` and review the snapshot"
            ),
        )]
    errs = G.compare_golden(trace, gold)
    return [Finding(
        rule="SPMD003", path=G.golden_path(trace.engine, trace.codec),
        line=0, engine=trace.engine,
        message=f"[{trace.engine}/{trace.codec}] collective signature "
                f"drifted from golden: {e} — if deliberate, regenerate "
                "with `tmpi lint --update-golden` and review the diff",
    ) for e in errs]


def analyze_engines(update_golden: bool = False,
                    engines: Optional[tuple] = None) -> list:
    """Run all jaxpr-level rule families over the engine matrix."""
    findings: list = []
    names = engines or harness.ENGINE_NAMES
    for name in names:
        t_off = harness.trace_engine(name, "none")
        t_on = harness.trace_engine(name, "int8:ef")
        for t in (t_off, t_on):
            findings.extend(axis_findings(t))
            findings.extend(control_flow_findings(t))
            findings.extend(donation_findings_for(t))
            findings.extend(golden_findings(t, update=update_golden))
        findings.extend(traffic_findings(t_off))
        findings.extend(codec_findings(t_off, t_on))
    return findings
