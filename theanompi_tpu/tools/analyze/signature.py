"""Collective-signature extraction from jaxprs.

The analyzer's core primitive: given a traced step (``jax.make_jaxpr``
over abstract operands — nothing compiled, nothing executed), walk the
equation graph and produce the ordered list of collectives the program
will post, with enough detail to verify them:

- **what**: primitive name, axis names, operand dtype/shape;
- **how often**: the static execution multiplier (``lax.scan`` /
  static ``fori_loop`` bodies multiply by their trip count);
- **wire honesty**: whether low-bit quantization evidence (int8/bf16
  intermediates — the codec layer's in-graph footprint) feeds the
  operand, so the traffic cross-check can price value-space compressed
  collectives the way ``obs/comm.py`` does;
- **where**: the user source line (for findings and per-line
  ``spmd_exempt`` suppressions).

Alongside the signature the walk runs a replicated-vs-varying dataflow
analysis — the classic SPMD uniformity question. Seeds: ``shard_map``
invars with non-empty ``in_names`` are varying (each device holds a
different shard), ``axis_index``/``ppermute``/``reduce_scatter``/
``all_to_all`` outputs are varying; ``psum``/``all_gather``/``pmin``/
``pmax`` outputs are uniform (every rank computes the same value).
A ``cond`` whose predicate is varying and whose branches post
DIFFERENT collective sequences — or a ``while`` whose predicate is
varying with collectives in its body — is the deadlock class
(rule SPMD002): ranks can disagree about which collectives to enter.
A varying ``cond`` whose branches carry identical collective
sequences is safe (the same schedule executes either way), matching
the rule the reference's gang-scheduled exchanges implicitly relied
on.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# low-bit dtypes that count as quantization evidence (the codec layer's
# int8 block kernels / bf16 casts); fp8 variants included for when the
# codec grows them
_QUANT_DTYPES = ("int8", "uint8", "bfloat16", "float8")

# collective primitives and their uniformity/wire semantics
COLLECTIVE_PRIMS = {
    "psum", "pmin", "pmax", "ppermute", "all_gather", "reduce_scatter",
    "all_to_all", "pgather",
}
# output identical on every participating rank
_UNIFORM_OUT = {"psum", "pmin", "pmax", "all_gather"}
# primitives whose OUTPUT differs per rank even on uniform input
_VARYING_OUT = {"ppermute", "reduce_scatter", "all_to_all", "axis_index",
                "pgather"}
# subjaxpr-carrying primitives we deliberately do not descend into
_OPAQUE = {"pallas_call"}


@dataclass(frozen=True)
class Collective:
    """One collective in program order."""

    prim: str
    axes: tuple  # participating mesh axis names
    dtype: str  # operand dtype (output dtype for all_gather)
    shape: tuple  # operand shape (output shape for all_gather)
    count: int  # static execution multiplier (scan trip products)
    quantized: bool = False  # low-bit evidence upstream of the operand
    file: str = ""
    line: int = 0

    def key(self) -> tuple:
        """Identity for golden comparison / branch-sequence equality —
        deliberately excludes source location and quantization evidence
        (the golden pins the SCHEDULE, per codec config)."""
        return (self.prim, self.axes, self.dtype, self.shape, self.count)

    def as_json(self) -> dict:
        return {"prim": self.prim, "axes": list(self.axes),
                "dtype": self.dtype, "shape": list(self.shape),
                "count": self.count}


@dataclass
class ControlFlowIssue:
    """A collective under potentially rank-divergent control flow
    (rule SPMD002 input)."""

    kind: str  # 'cond-mismatch' | 'while-collective'
    detail: str
    file: str = ""
    line: int = 0


@dataclass
class Signature:
    collectives: list = field(default_factory=list)
    issues: list = field(default_factory=list)

    def keys(self) -> list:
        return [c.key() for c in self.collectives]

    def as_json(self) -> list:
        return [c.as_json() for c in self.collectives]


def _source_of(eqn) -> tuple:
    """Best-effort (file, line) of the user frame that built ``eqn``."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name, int(frame.start_line)
    except Exception:  # noqa: BLE001 — source info is advisory only
        pass
    return "", 0


def _axis_tuple(eqn) -> tuple:
    ax = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
    if isinstance(ax, str):
        ax = (ax,)
    return tuple(a for a in ax if isinstance(a, str))


def _subjaxprs(value):
    """Every Jaxpr/ClosedJaxpr reachable from one eqn param value."""
    out = []
    stack = [value]
    while stack:
        v = stack.pop()
        # ClosedJaxpr exposes .eqns too — unwrap to the open Jaxpr first
        if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
            out.append(v.jaxpr)
        elif hasattr(v, "eqns"):
            out.append(v)
        elif isinstance(v, (tuple, list)):
            stack.extend(v)
    return out


def _eqn_is_quant_marker(eqn) -> bool:
    """Does this eqn (or any jaxpr nested in its params) produce a
    low-bit value? That's the codec layer's in-graph footprint — the
    quantize/dequantize chain around a value-space compressed
    collective."""
    def has_quant(jaxpr) -> bool:
        for e in jaxpr.eqns:
            for v in e.outvars:
                dt = getattr(getattr(v, "aval", None), "dtype", None)
                if dt is not None and str(dt).startswith(_QUANT_DTYPES):
                    return True
            for pv in e.params.values():
                for sub in _subjaxprs(pv):
                    if has_quant(sub):
                        return True
        return False

    for v in eqn.outvars:
        dt = getattr(getattr(v, "aval", None), "dtype", None)
        if dt is not None and str(dt).startswith(_QUANT_DTYPES):
            return True
    for pv in eqn.params.values():
        for sub in _subjaxprs(pv):
            if has_quant(sub):
                return True
    return False


class _Walker:
    """Recursive jaxpr walk threading three per-var facts: ``varying``
    (may differ across ranks) and ``quant`` (low-bit evidence
    upstream), plus the enclosing mesh's axis sizes."""

    def __init__(self):
        self.sig = Signature()
        self.axis_sizes: dict = {}

    # -- per-var fact helpers ----------------------------------------------
    @staticmethod
    def _get(facts: dict, var) -> bool:
        # Literals are uniform and unquantized
        return facts.get(id(var), False) if hasattr(var, "aval") and not \
            hasattr(var, "val") else False

    @staticmethod
    def _set(facts: dict, var, val: bool) -> None:
        facts[id(var)] = bool(val)

    # -- main walk ----------------------------------------------------------
    def walk(self, jaxpr, varying: dict, quant: dict, mult: int):
        """``jaxpr``: core.Jaxpr; ``varying``/``quant``: id(var)->bool
        maps pre-seeded for ``jaxpr.invars``."""
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            in_vary = any(self._get(varying, v) for v in eqn.invars)
            in_quant = any(self._get(quant, v) for v in eqn.invars)

            if name in COLLECTIVE_PRIMS:
                self._record_collective(eqn, mult, in_quant)
            if name == "shard_map":
                self._walk_shard_map(eqn, varying, quant, mult)
                continue
            if name == "pjit":
                self._walk_mapped(eqn.params["jaxpr"].jaxpr, eqn, varying,
                                  quant, mult)
                continue
            if name == "scan":
                body = eqn.params["jaxpr"].jaxpr
                self._walk_mapped(body, eqn, varying, quant,
                                  mult * int(eqn.params.get("length", 1)))
                continue
            if name == "while":
                self._walk_while(eqn, varying, quant, mult)
                continue
            if name == "cond":
                self._walk_cond(eqn, varying, quant, mult)
                continue
            if name not in _OPAQUE:
                # generic subjaxpr-carrying prims (custom_jvp/vjp, remat,
                # closed_call...): descend conservatively
                for pv in eqn.params.values():
                    for sub in _subjaxprs(pv):
                        sv, sq = {}, {}
                        if len(sub.invars) == len(eqn.invars):
                            for si, oi in zip(sub.invars, eqn.invars):
                                self._set(sv, si, self._get(varying, oi))
                                self._set(sq, si, self._get(quant, oi))
                        else:
                            for si in sub.invars:
                                self._set(sv, si, in_vary)
                                self._set(sq, si, in_quant)
                        self.walk(sub, sv, sq, mult)

            # forward fact propagation for this eqn's outputs
            out_vary = in_vary
            if name in _UNIFORM_OUT:
                out_vary = False
            elif name in _VARYING_OUT:
                out_vary = True
            if name in COLLECTIVE_PRIMS:
                # quantization evidence applies to the wire the operand
                # just CROSSED, not to every later collective in the
                # chain: a reduced output is a fresh value (the hier
                # strategy's in-slice all-gather after its codec'd DCN
                # psum rides fp32 and must be priced fp32). The output
                # stays marked only if it is itself low-bit (physical
                # compressed wire, e.g. a bf16 psum result).
                out_quant = _eqn_is_quant_marker(eqn)
            else:
                out_quant = in_quant or _eqn_is_quant_marker(eqn)
            for v in eqn.outvars:
                self._set(varying, v, out_vary)
                self._set(quant, v, out_quant)

    # -- collectives ---------------------------------------------------------
    def _record_collective(self, eqn, mult: int, quantized: bool) -> None:
        axes = _axis_tuple(eqn)
        # one Collective per operand: a single psum eqn can carry a whole
        # pytree's leaves (lax.pmean over a tree). all_gather's wire is
        # sized by its OUTPUTS (the gathered buffers); everything else by
        # the operands.
        refs = eqn.outvars if eqn.primitive.name == "all_gather" else \
            eqn.invars
        f, ln = _source_of(eqn)
        for ref in refs:
            aval = getattr(ref, "aval", None)
            if aval is None or not hasattr(aval, "dtype"):
                continue
            self.sig.collectives.append(Collective(
                prim=eqn.primitive.name, axes=axes,
                dtype=str(aval.dtype), shape=tuple(aval.shape),
                count=int(mult), quantized=bool(quantized),
                file=f, line=ln,
            ))

    # -- structured descent --------------------------------------------------
    def _walk_mapped(self, body, eqn, varying, quant, mult) -> None:
        """Descend into a subjaxpr whose invars map 1:1 onto the last
        ``len(body.invars)`` eqn invars (pjit, scan: consts+carry+xs)."""
        sv, sq = {}, {}
        ops = eqn.invars[-len(body.invars):] if body.invars else []
        for si, oi in zip(body.invars, ops):
            self._set(sv, si, self._get(varying, oi))
            self._set(sq, si, self._get(quant, oi))
        self.walk(body, sv, sq, mult)
        inner_out = body.outvars[-len(eqn.outvars):] if eqn.outvars else []
        for ov, iv in zip(eqn.outvars, inner_out):
            self._set(varying, ov, self._get(sv, iv))
            self._set(quant, ov, self._get(sq, iv))

    def _walk_shard_map(self, eqn, varying, quant, mult) -> None:
        body = eqn.params["jaxpr"]
        body = body.jaxpr if hasattr(body, "jaxpr") else body
        mesh = eqn.params.get("mesh")
        if mesh is not None:
            try:
                self.axis_sizes.update(dict(mesh.shape))
            except Exception:  # noqa: BLE001
                pass
        in_names = eqn.params.get("in_names", ())
        sv, sq = {}, {}
        for i, si in enumerate(body.invars):
            names = in_names[i] if i < len(in_names) else {}
            sharded = bool(names)  # any named axis -> per-device shard
            oi = eqn.invars[i] if i < len(eqn.invars) else None
            self._set(sv, si, sharded or (oi is not None
                                          and self._get(varying, oi)))
            self._set(sq, si, oi is not None and self._get(quant, oi))
        self.walk(body, sv, sq, mult)
        out_names = eqn.params.get("out_names", ())
        for i, ov in enumerate(eqn.outvars):
            names = out_names[i] if i < len(out_names) else {}
            self._set(varying, ov, bool(names))
            self._set(quant, ov, False)

    def _extract_branch(self, branch, eqn, varying, quant, mult):
        """Walk one cond branch in an isolated Walker; returns its
        signature (collectives recorded in order)."""
        sub = _Walker()
        sub.axis_sizes = self.axis_sizes
        body = branch.jaxpr if hasattr(branch, "jaxpr") else branch
        sv, sq = {}, {}
        ops = eqn.invars[1:]  # invars[0] is the branch index / predicate
        for si, oi in zip(body.invars, ops):
            sub._set(sv, si, self._get(varying, oi))
            sub._set(sq, si, self._get(quant, oi))
        sub.walk(body, sv, sq, mult)
        return sub.sig

    def _walk_cond(self, eqn, varying, quant, mult) -> None:
        pred = eqn.invars[0]
        pred_varying = self._get(varying, pred)
        branches = eqn.params.get("branches", ())
        sigs = [self._extract_branch(b, eqn, varying, quant, mult)
                for b in branches]
        for s in sigs:
            self.sig.issues.extend(s.issues)
        seqs = [s.keys() for s in sigs]
        if pred_varying and any(s for s in seqs) and not all(
                s == seqs[0] for s in seqs):
            f, ln = _source_of(eqn)
            self.sig.issues.append(ControlFlowIssue(
                kind="cond-mismatch",
                detail=(
                    "cond predicate may differ across ranks and its "
                    f"branches post different collective sequences "
                    f"{[[k[0] for k in s] for s in seqs]} — ranks taking "
                    "different branches would deadlock the gang"
                ),
                file=f, line=ln,
            ))
        if sigs:
            # signature determinism: record the heaviest branch (they are
            # identical in the safe cases the engines actually trace)
            best = max(sigs, key=lambda s: sum(
                int(np.prod(c.shape or (1,))) * c.count
                for c in s.collectives))
            self.sig.collectives.extend(best.collectives)
        in_vary = any(self._get(varying, v) for v in eqn.invars)
        in_quant = any(self._get(quant, v) for v in eqn.invars)
        for v in eqn.outvars:
            self._set(varying, v, in_vary)
            self._set(quant, v, in_quant)

    def _walk_while(self, eqn, varying, quant, mult) -> None:
        cond_j = eqn.params["cond_jaxpr"]
        body_j = eqn.params["body_jaxpr"]
        cond_body = cond_j.jaxpr if hasattr(cond_j, "jaxpr") else cond_j
        body = body_j.jaxpr if hasattr(body_j, "jaxpr") else body_j
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        carry_ops = eqn.invars[cn + bn:]
        # is any input the loop predicate can see varying?
        cond_ops = list(eqn.invars[:cn]) + list(carry_ops)
        pred_varying = any(self._get(varying, v) for v in cond_ops)
        sub = _Walker()
        sub.axis_sizes = self.axis_sizes
        sv, sq = {}, {}
        body_ops = list(eqn.invars[cn:cn + bn]) + list(carry_ops)
        for si, oi in zip(body.invars, body_ops):
            sub._set(sv, si, self._get(varying, oi))
            sub._set(sq, si, self._get(quant, oi))
        sub.walk(body, sv, sq, mult)
        self.sig.issues.extend(sub.sig.issues)
        if sub.sig.collectives and pred_varying:
            f, ln = _source_of(eqn)
            self.sig.issues.append(ControlFlowIssue(
                kind="while-collective",
                detail=(
                    "while-loop body posts collectives "
                    f"({sorted({c.prim for c in sub.sig.collectives})}) "
                    "but its trip count depends on rank-varying data — "
                    "ranks can disagree on the iteration count and "
                    "deadlock mid-loop"
                ),
                file=f, line=ln,
            ))
        self.sig.collectives.extend(sub.sig.collectives)
        for v in eqn.outvars:
            self._set(varying, v, True)  # conservative
            self._set(quant, v, any(self._get(sq, bv)
                                    for bv in body.invars))


def extract_signature(closed_jaxpr) -> tuple:
    """Walk a ClosedJaxpr (as returned by ``jax.make_jaxpr``) ->
    ``(Signature, axis_sizes)``. Top-level invars are uniform (the
    host passes every rank the same global operands; sharding only
    happens at ``shard_map`` boundaries)."""
    w = _Walker()
    jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") else \
        closed_jaxpr
    varying: dict = {}
    quant: dict = {}
    for v in jaxpr.invars:
        w._set(varying, v, False)
        w._set(quant, v, False)
    w.walk(jaxpr, varying, quant, 1)
    return w.sig, dict(w.axis_sizes)


# --------------------------------------------------------------------------
# wire-byte accounting (the jaxpr-side mirror of obs/comm.py's
# closed-form models): bytes SENT per device per execution
# --------------------------------------------------------------------------


def _axis_prod(axes: tuple, axis_sizes: dict) -> int:
    n = 1
    for a in axes:
        n *= int(axis_sizes.get(a, 1))
    return n


def collective_wire_bytes(c: Collective, axis_sizes: dict) -> float:
    """Per-device bytes one execution of ``c`` puts on the wire, using
    the same ring-lowering convention as obs/comm.py: allreduce
    ``2(n-1)/n·B``, gather/scatter halves ``(n-1)/n·B``, ppermute ``B``
    (each device forwards its buffer once)."""
    n = _axis_prod(c.axes, axis_sizes)
    if n <= 1:
        return 0.0
    elems = int(np.prod(c.shape or (1,)))
    try:
        itemsize = np.dtype(c.dtype).itemsize
    except TypeError:
        import jax.numpy as jnp

        itemsize = jnp.dtype(c.dtype).itemsize
    nbytes = float(elems * itemsize)
    if c.prim in ("psum", "pmin", "pmax"):
        return 2.0 * (n - 1) / n * nbytes
    if c.prim in ("all_gather", "reduce_scatter", "all_to_all", "pgather"):
        return (n - 1) / n * nbytes
    if c.prim == "ppermute":
        return nbytes
    return nbytes


def collective_link_bytes(c: Collective, axis_sizes: dict,
                          dcn_axis: str = "dcn") -> dict:
    """Split one collective's per-device wire bytes by link class:
    ``{"ici": ..., "dcn": ...}``. Axes that don't include ``dcn_axis``
    are pure-ICI; a collective purely over ``dcn_axis`` is pure-DCN.
    For a mixed-axis collective (flat allreduce over ('dcn','data')) a
    ring over the combined axis crosses a slice boundary on ``r-1`` of
    its ``n-1`` hops, so the DCN share of the wire is ``(r-1)/(n-1)``
    for both the allreduce and one-sided forms — the same convention as
    obs/comm.py's ``dcn_fraction``. A ppermute whose axes span slices
    is priced all-DCN (worst case: every neighbor hop may cross)."""
    total = collective_wire_bytes(c, axis_sizes)
    out = {"ici": 0.0, "dcn": 0.0}
    if total <= 0.0:
        return out
    if dcn_axis not in c.axes:
        out["ici"] = total
        return out
    n = _axis_prod(c.axes, axis_sizes)
    r = int(axis_sizes.get(dcn_axis, 1))
    s = max(1, n // max(1, r))
    if s == 1 or r <= 1:
        out["dcn"] = total if r > 1 else 0.0
        out["ici"] = total - out["dcn"]
        return out
    if c.prim == "ppermute":
        out["dcn"] = total
        return out
    frac = (r - 1) / (n - 1) if n > 1 else 0.0
    out["dcn"] = total * frac
    out["ici"] = total - out["dcn"]
    return out


def signature_link_bytes(sig: Signature, axis_sizes: dict,
                         dcn_axis: str = "dcn") -> dict:
    """Per-link-class raw wire bytes per execution, dtype-honest:
    ``{"ici": ..., "dcn": ...}`` totals over all collectives (count-
    weighted). ``ici + dcn == signature_raw_bytes`` by construction."""
    out = {"ici": 0.0, "dcn": 0.0}
    for c in sig.collectives:
        lb = collective_link_bytes(c, axis_sizes, dcn_axis)
        out["ici"] += lb["ici"] * c.count
        out["dcn"] += lb["dcn"] * c.count
    return out


def signature_raw_bytes(sig: Signature, axis_sizes: dict) -> float:
    """Total per-device wire bytes per execution, dtype-honest (what
    the traced program physically moves, fp32 for value-space-codec
    operands)."""
    return sum(collective_wire_bytes(c, axis_sizes) * c.count
               for c in sig.collectives)


def signature_effective_bytes(sig: Signature, axis_sizes: dict,
                              codec_bytes_per_element: float) -> float:
    """Codec-aware wire bytes: collectives whose operands carry low-bit
    quantization evidence but ride fp32 lanes (value-space compression
    — psum/reduce_scatter/all_gather on qdq'd values) are priced at the
    codec's analytic bytes-per-element, matching obs/comm.py's
    accounting convention; already-low-bit operands (the packed gossip
    / ring messages) are physical and keep their dtype bytes."""
    total = 0.0
    for c in sig.collectives:
        b = collective_wire_bytes(c, axis_sizes) * c.count
        try:
            itemsize = np.dtype(c.dtype).itemsize
        except TypeError:
            import jax.numpy as jnp

            itemsize = jnp.dtype(c.dtype).itemsize
        if c.quantized and itemsize >= 4:
            b *= codec_bytes_per_element / 4.0
        total += b
    return total


def has_quantized_collective(sig: Signature) -> bool:
    """Any collective carrying quantization evidence — either value-
    space (fp32 operand, low-bit upstream) or physical (low-bit
    operand dtype)."""
    for c in sig.collectives:
        if c.quantized:
            return True
        if str(c.dtype).startswith(_QUANT_DTYPES):
            return True
    return False


# --------------------------------------------------------------------------
# donation extraction
# --------------------------------------------------------------------------


def donated_flags(closed_jaxpr, n_leading: Optional[int] = None) -> tuple:
    """The ``donated_invars`` tuple of the outermost pjit equation (the
    jitted step), optionally truncated to the first ``n_leading``
    entries (= the flattened state argument's leaves)."""
    jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") else \
        closed_jaxpr
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pjit":
            d = tuple(eqn.params.get("donated_invars", ()))
            return d[:n_leading] if n_leading is not None else d
    return ()
