"""Host-concurrency race analyzer: the RACE rule family.

The host side of a run is now genuinely concurrent — MetricsDispatcher
drains, the AsyncCheckpointer writer, the CheckpointScrubber, the serve
batcher (`_loop`), the CheckpointReloader poller, heartbeat + stall
watchdog, ThreadingHTTPServer handler threads, the prefetch producer —
coordinated by ad-hoc ``threading.Lock``s. Every release so far shipped
a hand-found race in exactly this layer (scrubber-vs-prune unlink,
metrics.jsonl writer vs scrubber, serve reloader TOCTOU). Theano-MPI's
own async exchanger/monitor split bred the same class of bug; finding
them one post-mortem at a time does not scale to a production serving
fleet. This pass finds them from the AST, before they run.

**Thread-model discovery.** Over :data:`CONCURRENCY_FILES` the pass
maps every thread spawn to the code that runs on it:

- ``threading.Thread(target=self.m, name="tmpi-<role>")`` /
  ``threading.Timer`` → method ``m`` (and everything it reaches
  through ``self`` calls) executes in context ``<role>``;
- ``self._pool.submit(f, ...)`` on a ``ThreadPoolExecutor`` attr →
  ``f`` runs on the pool thread;
- classes derived from ``BaseHTTPRequestHandler`` → every handler
  method runs on a per-request server thread (context ``http``);
- module/local functions used as thread targets (the serve CLI's
  drain thread, the profiler-capture closure) get their own context;
- **callback propagation**: a callable ATTRIBUTE invoked from a
  thread context (``self.on_result(...)`` in the scrubber loop) marks
  the parameter that stored it as thread-borne; every registration
  site (``CheckpointScrubber(..., on_result=obs.note_scrub)``) then
  pulls the registered method into that thread's context. Method
  calls on other objects (``self.engine.set_params(...)`` from the
  reload poller) propagate by constructor-typed locals where
  available, falling back to unique-method-name matching.

Public methods additionally carry the ``caller`` context (the driver /
test / HTTP-frontend thread that owns the object). A method reachable
from a thread entry AND publicly callable therefore runs in ≥2
contexts — the definition of shared.

**Rules.** ``self``-attribute state written from ≥2 contexts (plain
assignment, subscript stores, or mutating calls like ``.write()`` /
``.append()`` — attributes holding ``Event``/``Queue``/locks/registry
metrics are internally synchronized and exempt; ``__init__`` writes
precede any thread and are exempt):

======== ===============================================================
RACE001  shared attribute written with NO lock anywhere
RACE002  inconsistent guarding: locked at some write sites, bare (or
         under a DIFFERENT lock) at others — the lock protects nothing
RACE003  lock-order inversion: lock B acquired under A at one site,
         A under B at another (potential deadlock), same-class locks,
         one ``self``-call deep
RACE004  filesystem TOCTOU: ``os.path.exists``/``stat`` gating an
         ``open``/``unlink``/``replace`` on the same path with no
         OSError guard — racing the prune/scrubber/reload threads
         that mutate checkpoint and obs directories underneath
RACE005  non-atomic multi-field publish: one method writes ≥2 plain
         attributes bare while another context reads them together
         under a lock — the reader's lock cannot give it a coherent
         pair the writer never published atomically
======== ===============================================================

All findings honor the shared per-line ``spmd_exempt: <reason>``
suppression (tools/lint.py). The model itself is exposed via
:func:`thread_inventory` — the stress harness
(tools/analyze/stress.py) and the README thread-model table consume
it, and the watchdog's ``stacks.txt`` grouping mirrors its
``tmpi-<role>`` names.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Optional

from theanompi_tpu.tools.analyze.astlint import AstFinding

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# every file that spawns, or runs on, a background thread — the host
# concurrency surface (module docstring)
CONCURRENCY_FILES = tuple(
    os.path.join(_PKG_ROOT, *parts) for parts in (
        ("obs", "__init__.py"),
        ("obs", "health.py"),
        ("obs", "flight.py"),
        ("obs", "metrics.py"),
        ("obs", "spans.py"),
        ("obs", "fleet.py"),
        ("obs", "exporter.py"),
        ("tools", "top.py"),
        ("serve", "engine.py"),
        ("serve", "reload.py"),
        ("serve", "frontend.py"),
        ("serve", "cli.py"),
        ("serve", "router.py"),
        ("utils", "checkpoint.py"),
        ("utils", "dispatch.py"),
        ("data", "loader.py"),
        ("launch", "worker.py"),
        ("launch", "supervisor.py"),
        ("launch", "multihost.py"),
        # launch/session.py is deliberately absent: its blocking=False
        # thread RUNS the driver (run_training executes on it
        # exclusively; wait() joins it) — it replaces the caller
        # context rather than racing it, and including it would smear a
        # phantom second context over the entire driver call tree
    )
)

# attribute initializers that make an attribute internally synchronized
# (mutating calls on them are not unguarded shared writes)
_SAFE_CTORS = {
    "Event", "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
    "ThreadPoolExecutor", "local", "Barrier",
}
# lock-like initializers: `with self.<attr>:` regions count as guarded
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
# registry-created metric families lock internally (obs/metrics.py)
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}

# mutating method names: a call `self.attr.<name>(...)` writes `attr`
_MUTATORS = {
    "write", "writelines", "flush", "close", "append", "appendleft",
    "extend", "extendleft", "insert", "pop", "popleft", "remove",
    "discard", "add", "clear", "update", "setdefault", "sort",
    "reverse", "truncate",
}

# names that are method calls on OTHER objects too generic to resolve
# by name alone (a thread-context `t.start()` must not smear its
# context over every class defining `start`)
_GENERIC_NAMES = {
    "start", "stop", "run", "join", "close", "wait", "get", "put",
    "set", "clear", "read", "write", "flush", "append", "pop", "send",
    "submit_stub", "items", "keys", "values", "update", "result",
    "shutdown", "cancel", "acquire", "release", "notify", "notify_all",
    "is_set", "is_alive", "poll", "kill", "terminate",
}

_EXISTS_FUNCS = {"exists", "isfile", "getsize", "stat", "lstat"}
_TOCTOU_SINKS = {"open", "load", "unlink", "remove", "replace",
                 "rename", "getsize", "stat"}

_CALLER = "caller"


def _term(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _self_attr(node: ast.expr) -> Optional[str]:
    """``x`` for a single-level ``self.x``, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _name_literal(call: ast.Call) -> Optional[str]:
    """The thread's ``name=`` kwarg as best-effort text (constant, or
    the constant prefix of an f-string like ``f"tmpi-hb-r{rank}"``)."""
    val = _kwarg(call, "name")
    if isinstance(val, ast.Constant) and isinstance(val.value, str):
        return val.value
    if isinstance(val, ast.JoinedStr):
        parts = []
        for v in val.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                break
        if parts:
            return "".join(parts)
    return None


@dataclass(eq=False)  # identity hash: FuncInfos key dicts/sets
class FuncInfo:
    """One analyzable function body: a method, module function, or a
    local def / lambda used as a thread target or callback."""

    name: str
    qualname: str
    path: str
    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda
    cls: Optional["ClassInfo"] = None
    contexts: set = field(default_factory=set)

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 0)


@dataclass
class ClassInfo:
    name: str
    path: str
    node: ast.ClassDef
    methods: dict = field(default_factory=dict)       # name -> FuncInfo
    lock_attrs: set = field(default_factory=set)
    safe_attrs: set = field(default_factory=set)
    # thread-entry method name -> role label
    entries: dict = field(default_factory=dict)
    is_http_handler: bool = False
    # callback attr -> set of contexts it is invoked from
    callback_ctx: dict = field(default_factory=dict)
    # param name -> attr name it is stored into (across methods)
    param_stores: dict = field(default_factory=dict)
    # attr name -> set of class names it may hold (ctor-typed stores)
    attr_types: dict = field(default_factory=dict)


@dataclass
class ThreadSpawn:
    """One discovered thread spawn site (the thread-model inventory)."""

    role: str
    path: str
    line: int
    target: str   # qualified target description
    named: bool   # carries an explicit tmpi-<role> name= kwarg


class _Model:
    """The parsed multi-file concurrency model."""

    def __init__(self, sources: dict):
        self.sources = sources
        self.trees: dict = {}
        self.classes: dict = {}          # name -> ClassInfo (last wins)
        self.module_funcs: dict = {}     # name -> FuncInfo
        self.funcs: list = []            # every FuncInfo
        self.spawns: list = []           # ThreadSpawn inventory
        self.parents: dict = {}
        # ast node (FunctionDef/Lambda) -> FuncInfo for local targets
        self.local_funcs: dict = {}
        for path, src in sources.items():
            tree = ast.parse(src)
            self.trees[path] = tree
            for node in ast.walk(tree):
                for child in ast.iter_child_nodes(node):
                    self.parents[child] = node
        self._collect()
        self._find_threads()
        self._propagate()

    # -- structure ----------------------------------------------------------
    def _collect(self) -> None:
        for path, tree in self.trees.items():
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    ci = ClassInfo(name=node.name, path=path, node=node)
                    for b in node.bases:
                        if _term(b) == "BaseHTTPRequestHandler":
                            ci.is_http_handler = True
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            fi = FuncInfo(
                                name=item.name,
                                qualname=f"{node.name}.{item.name}",
                                path=path, node=item, cls=ci,
                            )
                            ci.methods[item.name] = fi
                            self.funcs.append(fi)
                    self._classify_attrs(ci)
                    self.classes[node.name] = ci
            for item in tree.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = FuncInfo(name=item.name, qualname=item.name,
                                  path=path, node=item)
                    self.module_funcs[item.name] = fi
                    self.funcs.append(fi)
        # single-inheritance merge: a subclass shares its base's locks
        # and synchronized attrs (Counter._series is guarded by the
        # _Metric base lock) — iterate to cover chains
        for _ in range(4):
            changed = False
            for ci in self.classes.values():
                for b in ci.node.bases:
                    base = self.classes.get(_term(b))
                    if base is None:
                        continue
                    if not (base.lock_attrs <= ci.lock_attrs and
                            base.safe_attrs <= ci.safe_attrs):
                        ci.lock_attrs |= base.lock_attrs
                        ci.safe_attrs |= base.safe_attrs
                        changed = True
                    if base.is_http_handler and not ci.is_http_handler:
                        ci.is_http_handler = True
                        changed = True
            if not changed:
                break

    def _classify_attrs(self, ci: ClassInfo) -> None:
        """Lock attrs / internally-synchronized attrs from every
        ``self.x = <ctor>()`` in the class body."""
        assigned: dict = {}
        for node in ast.walk(ci.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                attr = _self_attr(node.targets[0])
                if attr is None:
                    continue
                kind = None
                v = node.value
                if isinstance(v, ast.Call):
                    name = _term(v.func)
                    if name in _LOCK_CTORS:
                        kind = "lock"
                    elif name in _SAFE_CTORS or name in _METRIC_FACTORIES:
                        kind = "safe"
                assigned.setdefault(attr, set()).add(kind)
        for attr, kinds in assigned.items():
            if kinds == {"lock"}:
                ci.lock_attrs.add(attr)
            elif kinds <= {"lock", "safe"}:
                if "safe" in kinds:
                    ci.safe_attrs.add(attr)

    # -- thread spawns ------------------------------------------------------
    def _enclosing_func(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            cur = self.parents.get(cur)
        return cur

    def _enclosing_class(self, node: ast.AST) -> Optional[str]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = self.parents.get(cur)
        return None

    def _local_def(self, scope: ast.AST, name: str) -> Optional[ast.AST]:
        """A FunctionDef named ``name`` defined inside ``scope``
        (memoized per scope — the fixpoint hits this hot)."""
        cache = getattr(self, "_local_def_cache", None)
        if cache is None:
            cache = self._local_def_cache = {}
        defs = cache.get(id(scope))
        if defs is None:
            defs = {}
            for sub in ast.walk(scope):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs.setdefault(sub.name, sub)
            cache[id(scope)] = defs
        return defs.get(name)

    def _register_local_target(self, path: str, fn_node: ast.AST,
                               role: str) -> FuncInfo:
        fi = self.local_funcs.get(fn_node)
        if fi is None:
            name = getattr(fn_node, "name", "<lambda>")
            fi = FuncInfo(name=name, qualname=f"{role}:{name}",
                          path=path, node=fn_node)
            self.local_funcs[fn_node] = fi
            self.funcs.append(fi)
        fi.contexts.add(role)
        return fi

    def _find_threads(self) -> None:
        for path, tree in self.trees.items():
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                callee = _term(node.func)
                if callee in ("Thread", "Timer"):
                    target = _kwarg(node, "target")
                    if target is None and callee == "Timer" \
                            and len(node.args) >= 2:
                        target = node.args[1]
                    if target is None and node.args:
                        target = node.args[0]
                    self._spawn(path, node, target)
                elif callee == "submit" and isinstance(
                        node.func, ast.Attribute) and node.args:
                    # pool.submit(f, ...): a ThreadPoolExecutor attr
                    pool = _self_attr(node.func.value)
                    cls = self._enclosing_class(node)
                    ci = self.classes.get(cls) if cls else None
                    if ci is not None and pool in ci.safe_attrs:
                        self._spawn(path, node, node.args[0],
                                    role_hint=f"{cls}-pool")
        for ci in self.classes.values():
            if ci.is_http_handler:
                for m in ci.methods.values():
                    m.contexts.add("http")
                self.spawns.append(ThreadSpawn(
                    role="http", path=ci.path, line=ci.node.lineno,
                    target=f"{ci.name} (per-request server thread)",
                    named=False))

    def _spawn(self, path: str, call: ast.Call, target: Optional[ast.expr],
               role_hint: Optional[str] = None) -> None:
        if target is None:
            return
        name = _name_literal(call)
        cls = self._enclosing_class(call)
        attr = _self_attr(target) if isinstance(target, ast.Attribute) \
            else None
        role = name or role_hint or "thread"
        role = role.rstrip("-")
        if attr and cls and attr in self.classes.get(
                cls, ClassInfo("", "", None)).methods:
            ci = self.classes[cls]
            role = name or role_hint or f"{cls}.{attr}"
            ci.entries[attr] = role
            ci.methods[attr].contexts.add(role)
            self.spawns.append(ThreadSpawn(
                role=role, path=path, line=call.lineno,
                target=f"{cls}.{attr}", named=bool(name)))
            return
        if isinstance(target, ast.Name):
            scope = self._enclosing_func(call)
            # a local function target (the serve CLI drain thread, the
            # profiler capture closure) — or a local alias of module
            # functions (`save_fn = save_checkpoint`)
            fn_node = self._local_def(scope, target.id) if scope else None
            if fn_node is None and scope is not None:
                for mf in self._alias_module_funcs(scope, target.id):
                    mf.contexts.add(role if name else
                                    (role_hint or f"{mf.name}-thread"))
                    self.spawns.append(ThreadSpawn(
                        role=role_hint or role, path=path,
                        line=call.lineno, target=mf.qualname,
                        named=bool(name)))
                return
            if fn_node is None and target.id in self.module_funcs:
                fn_node = self.module_funcs[target.id].node
            if fn_node is not None:
                role = name or role_hint or f"{target.id}-thread"
                fi = self._register_local_target(path, fn_node, role)
                self.spawns.append(ThreadSpawn(
                    role=role, path=path, line=call.lineno,
                    target=fi.qualname, named=bool(name)))
        elif isinstance(target, ast.Lambda):
            role = name or role_hint or "lambda-thread"
            self._register_local_target(path, target, role)
            self.spawns.append(ThreadSpawn(
                role=role, path=path, line=call.lineno,
                target="<lambda>", named=bool(name)))

    def _alias_module_funcs(self, scope: ast.AST, name: str) -> list:
        """Module functions a local name may alias (simple assignments,
        incl. conditional expressions)."""
        out = []
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Assign):
                if any(isinstance(t, ast.Name) and t.id == name
                       for t in sub.targets):
                    for n in ast.walk(sub.value):
                        if isinstance(n, ast.Name) and \
                                n.id in self.module_funcs:
                            out.append(self.module_funcs[n.id])
        return out

    # -- context propagation ------------------------------------------------
    def _factory_types(self, scope: ast.AST, fname: str) -> set:
        """Class names a LOCAL factory function named ``fname`` (defined
        anywhere inside ``scope``) may return via a direct
        ``return ClassName(...)``. The serve CLI builds its engine
        through per-branch ``_make`` factories (ServeEngine on one
        branch, DecodeEngine on the other), so ``engine = _make()``
        must ctor-type the local with EVERY branch's return type or the
        reloader/router consumers lose their dispatch targets."""
        cache = getattr(self, "_factory_cache", None)
        if cache is None:
            cache = self._factory_cache = {}
        key = (id(scope), fname)
        hit = cache.get(key)
        if hit is not None:
            return hit
        out: set = set()
        cache[key] = out
        for sub in ast.walk(scope):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub.name == fname:
                for ret in ast.walk(sub):
                    if isinstance(ret, ast.Return) and \
                            isinstance(ret.value, ast.Call):
                        cname = _term(ret.value.func)
                        if cname in self.classes:
                            out.add(cname)
        return out

    def _ctor_types(self, scope: ast.AST) -> dict:
        """Local name -> class name for ``x = ClassName(...)`` bindings
        in ``scope`` (constructor-typed locals, including calls to a
        local factory with exactly ONE return type — ambiguous
        factories stay multi-only). Memoized per scope."""
        cache = getattr(self, "_ctor_cache", None)
        if cache is None:
            cache = self._ctor_cache = {}
        hit = cache.get(id(scope))
        if hit is not None:
            return hit
        types: dict = {}
        cache[id(scope)] = types
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                    isinstance(sub.targets[0], ast.Name) and \
                    isinstance(sub.value, ast.Call):
                cname = _term(sub.value.func)
                if cname in self.classes:
                    types[sub.targets[0].id] = cname
                elif cname is not None:
                    facs = self._factory_types(scope, cname)
                    if len(facs) == 1:
                        types[sub.targets[0].id] = next(iter(facs))
        return types

    def _ctor_types_multi(self, scope: ast.AST) -> dict:
        """Like :meth:`_ctor_types` but keeping EVERY constructor type a
        local may hold (name -> set of class names): the serve CLI binds
        ``engine`` to a ``ServeEngine`` on one branch and a ``Router``
        on the other, and a duck-typed consumer must see both."""
        cache = getattr(self, "_ctor_multi_cache", None)
        if cache is None:
            cache = self._ctor_multi_cache = {}
        hit = cache.get(id(scope))
        if hit is not None:
            return hit
        types: dict = {}
        cache[id(scope)] = types
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                    isinstance(sub.targets[0], ast.Name) and \
                    isinstance(sub.value, ast.Call):
                cname = _term(sub.value.func)
                if cname in self.classes:
                    types.setdefault(sub.targets[0].id, set()).add(cname)
                elif cname is not None:
                    facs = self._factory_types(scope, cname)
                    if facs:
                        types.setdefault(sub.targets[0].id,
                                         set()).update(facs)
        return types

    def _resolve_method(self, recv: ast.expr, mname: str,
                        types: dict) -> list:
        """FuncInfos a call ``recv.mname(...)`` may dispatch to."""
        if isinstance(recv, ast.Name) and recv.id in types:
            ci = self.classes[types[recv.id]]
            m = ci.methods.get(mname)
            return [m] if m is not None else []
        if mname in _GENERIC_NAMES or mname in _MUTATORS:
            # container-mutation names (`x.add`, `x.discard`) collide
            # with real methods (Gauge.add) — never name-resolve them
            return []
        hits = [ci.methods[mname] for ci in self.classes.values()
                if mname in ci.methods]
        return hits if len(hits) == 1 else []

    def _scope_chain(self, node: ast.AST) -> list:
        """The function node plus its enclosing function scopes —
        closures see their parents' locals (the serve CLI's
        ``_drain_then_stop`` calls its sibling ``_shutdown`` and uses
        ``engine``/``reloader`` bound in ``serve_main``)."""
        chain = [node]
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                chain.append(cur)
            cur = self.parents.get(cur)
        return chain

    def _callees(self, fi: FuncInfo) -> list:
        """(callee FuncInfo, via_callback) edges out of one function.
        Memoized: the edge set is static across fixpoint iterations
        (only context SETS change)."""
        cache = getattr(self, "_callee_cache", None)
        if cache is None:
            cache = self._callee_cache = {}
        hit = cache.get(fi)
        if hit is not None:
            return hit
        out = []
        cache[fi] = out
        body = fi.node
        chain = self._scope_chain(body)
        types: dict = {}
        for scope in reversed(chain):  # innermost bindings win
            types.update(self._ctor_types(scope))
        # simple local aliases: p = self._x  (callback alias pattern)
        self_aliases: dict = {}
        if fi.cls is not None:
            for sub in ast.walk(body):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name):
                    attr = _self_attr(sub.value)
                    if attr is not None:
                        self_aliases[sub.targets[0].id] = attr
        for sub in ast.walk(body):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if isinstance(f, ast.Name):
                # local def (own body or an enclosing closure scope),
                # alias-of-self-attr callback, or module fn
                local = None
                for scope in chain:
                    local = self._local_def(scope, f.id)
                    if local is not None:
                        break
                if local is not None and local is not body:
                    lf = self.local_funcs.get(local)
                    if lf is None:
                        lf = FuncInfo(name=f.id,
                                      qualname=f"{fi.qualname}.{f.id}",
                                      path=fi.path, node=local, cls=fi.cls)
                        self.local_funcs[local] = lf
                        self.funcs.append(lf)
                    out.append(lf)
                elif f.id in self_aliases and fi.cls is not None:
                    out.append(("callback", fi.cls, self_aliases[f.id]))
                elif f.id in self.module_funcs:
                    out.append(self.module_funcs[f.id])
            elif isinstance(f, ast.Attribute):
                attr = _self_attr(f)
                if attr is not None and fi.cls is not None:
                    m = fi.cls.methods.get(attr)
                    if m is not None:
                        out.append(m)
                    elif attr not in fi.cls.lock_attrs and \
                            attr not in fi.cls.safe_attrs:
                        out.append(("callback", fi.cls, attr))
                elif isinstance(f.value, ast.Name) and \
                        f.value.id == "self":
                    pass
                else:
                    # attr-typed receiver first: ``self.engine.set_params``
                    # where __init__ stored a ctor-typed arg into
                    # ``self.engine`` dispatches to every candidate class
                    # (the unique-name fallback goes dark the moment two
                    # classes share the method name — Router/ServeEngine)
                    recv = _self_attr(f.value)
                    hits = []
                    if recv is not None and fi.cls is not None:
                        for cname in fi.cls.attr_types.get(recv, ()):
                            m2 = self.classes[cname].methods.get(f.attr)
                            if m2 is not None:
                                hits.append(m2)
                    elif isinstance(f.value, ast.Name):
                        # ctor-typed param of an enclosing scope (the
                        # handler closure's ``engine.submit``)
                        ptypes = getattr(self, "param_types", {})
                        for scope in chain:
                            for cname in ptypes.get(
                                    (scope, f.value.id), ()):
                                m2 = self.classes[cname].methods.get(
                                    f.attr)
                                if m2 is not None:
                                    hits.append(m2)
                    if hits:
                        out.extend(hits)
                    else:
                        out.extend(
                            self._resolve_method(f.value, f.attr, types))
        return out

    def _param_stores(self) -> None:
        """``self.X = <param>`` stores: which constructor/setter param
        lands in which attribute (callback registration resolution)."""
        for ci in self.classes.values():
            for m in ci.methods.values():
                params = {a.arg for a in m.node.args.args} | \
                         {a.arg for a in m.node.args.kwonlyargs}
                for sub in ast.walk(m.node):
                    if isinstance(sub, ast.Assign) and \
                            len(sub.targets) == 1:
                        attr = _self_attr(sub.targets[0])
                        if attr is None:
                            continue
                        v = sub.value
                        if isinstance(v, ast.Name) and v.id in params:
                            ci.param_stores.setdefault(
                                (m.name, v.id), set()).add(attr)
                        # `x = y or default` / conditional stores
                        elif isinstance(v, (ast.BoolOp, ast.IfExp)):
                            for n in ast.walk(v):
                                if isinstance(n, ast.Name) and \
                                        n.id in params:
                                    ci.param_stores.setdefault(
                                        (m.name, n.id), set()).add(attr)

    def _attr_ctor_types(self, sites: list) -> None:
        """Type class attributes from constructor-typed stores: direct
        ``self.x = ClassName(...)`` assignments in the class body, plus
        call-site args bound into attrs whose local binding is
        ctor-typed (``CheckpointReloader(engine, ...)`` with ``engine``
        assigned from ``ServeEngine(...)`` on one branch and
        ``Router(...)`` on the other types ``self.engine`` as BOTH —
        context propagation must reach every runtime dispatch target)."""
        for ci in self.classes.values():
            for m in ci.methods.values():
                for sub in ast.walk(m.node):
                    if isinstance(sub, ast.Assign) and \
                            len(sub.targets) == 1:
                        attr = _self_attr(sub.targets[0])
                        if attr is not None and \
                                isinstance(sub.value, ast.Call):
                            cname = _term(sub.value.func)
                            if cname in self.classes:
                                ci.attr_types.setdefault(
                                    attr, set()).add(cname)
        for ci_target, attr, val, scope, _path in sites:
            if isinstance(val, ast.Name) and scope is not None:
                for s in self._scope_chain(scope):
                    multi = self._ctor_types_multi(s)
                    for cname in multi.get(val.id, ()):
                        ci_target.attr_types.setdefault(
                            attr, set()).add(cname)
        # module-function params: ``make_handler(engine)`` with a
        # ctor-typed argument types the param inside the callee (and
        # its closures — the HTTP handler's ``engine.submit``)
        self.param_types = {}  # (fn node, param name) -> set of classes
        bindings = []  # (callee node, param name, arg name, scope chain)
        for _path, tree in self.trees.items():
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Name) or \
                        node.func.id not in self.module_funcs:
                    continue
                callee = self.module_funcs[node.func.id]
                scope = self._enclosing_func(node)
                if scope is None:
                    continue
                sig = callee.node.args
                pos = [a.arg for a in sig.args]
                bound = list(zip(pos, node.args))
                bound += [(kw.arg, kw.value) for kw in node.keywords
                          if kw.arg is not None]
                for pname, aval in bound:
                    if isinstance(aval, ast.Name):
                        bindings.append((callee.node, pname, aval.id,
                                         self._scope_chain(scope)))
        # fixpoint: a typed param flows through further call sites
        # (serve_http(engine) -> make_handler(engine) -> Handler)
        for _ in range(4):
            changed = False
            for callee_node, pname, aname, chain in bindings:
                cands: set = set()
                for s in chain:
                    cands |= self._ctor_types_multi(s).get(aname, set())
                    cands |= self.param_types.get((s, aname), set())
                cur = self.param_types.setdefault(
                    (callee_node, pname), set())
                if not cands <= cur:
                    cur |= cands
                    changed = True
            if not changed:
                break

    def _registration_sites(self) -> list:
        """Every call that may store a callable into a class attribute:
        ``(class, attr, value expr, enclosing scope, path)``."""
        sites = []
        for path, tree in self.trees.items():
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                cname = _term(node.func)
                target_cls = None
                via_method = None
                if cname in self.classes and isinstance(
                        node.func, (ast.Name, ast.Attribute)):
                    target_cls = self.classes[cname]
                    via_method = "__init__"
                elif isinstance(node.func, ast.Attribute):
                    # obj.setter(cb): resolve the setter by unique name
                    scope = self._enclosing_func(node)
                    types = self._ctor_types(scope) if scope else {}
                    hits = self._resolve_method(
                        node.func.value, node.func.attr, types)
                    if len(hits) == 1 and hits[0].cls is not None:
                        target_cls = hits[0].cls
                        via_method = hits[0].name
                if target_cls is None or via_method is None or \
                        via_method not in target_cls.methods:
                    continue
                sig = target_cls.methods[via_method].node.args
                pos_params = [a.arg for a in sig.args][1:]  # skip self
                bound = []
                for i, a in enumerate(node.args):
                    if i < len(pos_params):
                        bound.append((pos_params[i], a))
                for kw in node.keywords:
                    if kw.arg is not None:
                        bound.append((kw.arg, kw.value))
                for pname, val in bound:
                    attrs = target_cls.param_stores.get(
                        (via_method, pname))
                    if attrs:
                        for attr in attrs:
                            sites.append((target_cls, attr, val,
                                          self._enclosing_func(node), path))
        return sites

    def _propagate(self) -> None:
        self._param_stores()
        # seed: caller context on public methods and module functions.
        # HTTP handler methods are invoked only by the server machinery
        # on per-request threads — no caller context
        for fi in self.funcs:
            if fi.cls is not None and fi.cls.is_http_handler:
                continue
            if not fi.name.startswith("_") and fi.name != "__init__":
                fi.contexts.add(_CALLER)
        sites = self._registration_sites()
        self._attr_ctor_types(sites)  # before any _callees memoization
        for _ in range(12):
            changed = False
            for fi in list(self.funcs):
                if not fi.contexts:
                    continue
                src = set(fi.contexts)
                for edge in self._callees(fi):
                    if isinstance(edge, tuple):  # callback invocation
                        _, ci, attr = edge
                        cur = ci.callback_ctx.setdefault(attr, set())
                        if not src <= cur:
                            cur |= src
                            changed = True
                        continue
                    if edge.name == "__init__":
                        continue
                    if not src <= edge.contexts:
                        edge.contexts |= src
                        changed = True
            # registered callbacks inherit the contexts their storing
            # attribute is invoked from
            for ci_target, attr, val, scope, path in sites:
                ctxs = ci_target.callback_ctx.get(attr) or set()
                ctxs = ctxs - {_CALLER}
                if not ctxs:
                    continue
                marks = []
                if isinstance(val, ast.Attribute):
                    types = self._ctor_types(scope) if scope else {}
                    marks = self._resolve_method(val.value, val.attr, types)
                elif isinstance(val, ast.Name) and scope is not None:
                    local = self._local_def(scope, val.id)
                    if local is not None:
                        lf = self.local_funcs.get(local)
                        if lf is None:
                            lf = FuncInfo(
                                name=val.id, qualname=f"cb:{val.id}",
                                path=path, node=local)
                            self.local_funcs[local] = lf
                            self.funcs.append(lf)
                        marks = [lf]
                    elif val.id in self.module_funcs:
                        marks = [self.module_funcs[val.id]]
                elif isinstance(val, ast.Lambda):
                    lf = self.local_funcs.get(val)
                    if lf is None:
                        lf = FuncInfo(name="<lambda>", qualname="cb:<lambda>",
                                      path=path, node=val)
                        self.local_funcs[val] = lf
                        self.funcs.append(lf)
                    marks = [lf]
                for m in marks:
                    if not ctxs <= m.contexts:
                        m.contexts |= ctxs
                        changed = True
            if not changed:
                break


# --------------------------------------------------------------------------
# write/read/lock extraction
# --------------------------------------------------------------------------


@dataclass
class _Access:
    attr: str
    func: FuncInfo
    line: int
    locks: frozenset
    kind: str  # "assign" | "mutate" | "read"


def _method_accesses(fi: FuncInfo) -> list:
    """Every self-attribute access in one method, annotated with the
    lock set held at that statement (enclosing ``with self.<lock>:``
    blocks)."""
    ci = fi.cls
    if ci is None or fi.name in ("__init__", "__post_init__"):
        return []
    out: list = []

    def walk(node: ast.AST, locks: frozenset) -> None:
        if isinstance(node, ast.With):
            acquired = set()
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr in ci.lock_attrs:
                    acquired.add(attr)
                elif isinstance(item.context_expr, ast.Call):
                    # cond.wait-style or lock factory calls are not
                    # acquisitions of a tracked class lock
                    pass
            inner = locks | frozenset(acquired)
            for item in node.items:
                walk(item.context_expr, locks)
            for stmt in node.body:
                walk(stmt, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fi.node:
            return  # nested defs execute in their own (callback) context
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is None and isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                if attr is None and isinstance(t, ast.Tuple):
                    for el in t.elts:
                        a = _self_attr(el)
                        if a is not None:
                            out.append(_Access(a, fi, node.lineno,
                                               locks, "assign"))
                if attr is not None:
                    out.append(_Access(attr, fi, node.lineno, locks,
                                       "assign"))
        if isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t) or (
                    _self_attr(t.value) if isinstance(t, ast.Subscript)
                    else None)
                if attr is not None:
                    out.append(_Access(attr, fi, node.lineno, locks,
                                       "assign"))
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute):
            attr = _self_attr(node.func.value)
            if attr is not None and node.func.attr in _MUTATORS:
                out.append(_Access(attr, fi, node.lineno, locks, "mutate"))
        if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load):
            attr = _self_attr(node)
            if attr is not None:
                out.append(_Access(attr, fi, node.lineno, locks, "read"))
        for child in ast.iter_child_nodes(node):
            walk(child, locks)

    for stmt in fi.node.body:
        walk(stmt, frozenset())
    return out


def _class_accesses(ci: ClassInfo) -> dict:
    """Per-attribute access lists for one class. Cached on the class:
    both the shared-write and publish rules consume it, and the
    extraction walks every method body."""
    cached = getattr(ci, "_access_cache", None)
    if cached is not None:
        return cached
    by_attr: dict = {}
    for m in ci.methods.values():
        for acc in _method_accesses(m):
            if acc.attr in ci.lock_attrs or acc.attr in ci.safe_attrs:
                continue
            by_attr.setdefault(acc.attr, []).append(acc)
    ci._access_cache = by_attr
    return by_attr


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------


def _contexts(accs: list) -> set:
    out: set = set()
    for a in accs:
        out |= a.func.contexts
    return out


def _race_shared_writes(ci: ClassInfo) -> list:
    """RACE001 (unguarded shared write) + RACE002 (inconsistent
    guarding) over one class."""
    findings = []
    for attr, accs in sorted(_class_accesses(ci).items()):
        writes = [a for a in accs if a.kind != "read"]
        if not writes:
            continue
        ctxs = _contexts(writes)
        if len(ctxs) < 2:
            continue
        locked = [a for a in writes if a.locks]
        bare = [a for a in writes if not a.locks]
        roles = ", ".join(sorted(ctxs))
        if not locked:
            a = bare[0]
            findings.append(AstFinding(
                rule="RACE001", path=ci.path, line=a.line,
                message=(
                    f"'{ci.name}.{attr}' is written from {len(ctxs)} "
                    f"thread contexts ({roles}) with no lock anywhere "
                    f"— writes at lines "
                    f"{sorted({w.line for w in writes})}; guard every "
                    "write with one lock (or spmd_exempt with the "
                    "single-writer argument)"
                ),
            ))
            continue
        lock_names = {ln for a in locked for ln in a.locks}
        for a in bare:
            findings.append(AstFinding(
                rule="RACE002", path=ci.path, line=a.line,
                message=(
                    f"'{ci.name}.{attr}' is guarded by "
                    f"{sorted(lock_names)} at "
                    f"{sorted({w.line for w in locked})} but written "
                    f"BARE here while reachable from {len(ctxs)} thread "
                    f"contexts ({roles}) — a lock that only some "
                    "writers take protects nothing; take the same lock "
                    "here (or spmd_exempt with why this site cannot "
                    "race)"
                ),
            ))
        if not bare:
            # DIFFERENT locks only when no single lock is held at
            # EVERY write site — nested holds (a,b here, a alone
            # there) still share the serializing lock
            common = frozenset.intersection(*(a.locks for a in locked))
            if not common:
                per_lock: dict = {}
                for a in locked:
                    for ln in a.locks:
                        per_lock.setdefault(ln, []).append(a.line)
                a = locked[0]
                findings.append(AstFinding(
                    rule="RACE002", path=ci.path, line=a.line,
                    message=(
                        f"'{ci.name}.{attr}' is written under "
                        f"DIFFERENT locks "
                        f"({ {k: sorted(v) for k, v in per_lock.items()} }) "
                        f"from {len(ctxs)} contexts ({roles}) — no one "
                        "lock covers every write, so two locks "
                        "serialize nothing against each other; pick one"
                    ),
                ))
    return findings


def _race_lock_order(ci: ClassInfo) -> list:
    """RACE003: same-class lock-order inversion, one self-call deep."""
    # direct acquisition orders: lock B taken while A held
    direct: dict = {}  # method name -> set of (held, acquired, line)
    acquires: dict = {}  # method name -> set of locks acquired anywhere
    callsites: dict = {}  # method -> [(callee, locks held, line)]

    for m in ci.methods.values():
        edges = set()
        owned = set()
        calls = []

        def walk(node, locks, m=m, edges=edges, owned=owned, calls=calls):
            if isinstance(node, ast.With):
                acquired = {
                    a for item in node.items
                    if (a := _self_attr(item.context_expr))
                    in ci.lock_attrs
                }
                for a in acquired:
                    owned.add(a)
                    for held in locks:
                        edges.add((held, a, node.lineno))
                inner = locks | acquired
                for stmt in node.body:
                    walk(stmt, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not m.node:
                return
            if isinstance(node, ast.Call):
                attr = _self_attr(node.func) if isinstance(
                    node.func, ast.Attribute) else None
                if attr in ci.methods:
                    calls.append((attr, frozenset(locks), node.lineno))
            for child in ast.iter_child_nodes(node):
                walk(child, locks)

        for stmt in m.node.body:
            walk(stmt, set())
        direct[m.name] = edges
        acquires[m.name] = owned
        callsites[m.name] = calls

    edges: dict = {}
    for mname, es in direct.items():
        for held, acq, line in es:
            edges.setdefault((held, acq), []).append(
                (ci.methods[mname].qualname, line))
    # one call deep: holding A while calling a method that acquires B
    for mname, calls in callsites.items():
        for callee, locks, line in calls:
            for held in locks:
                for acq in acquires.get(callee, ()):
                    if acq != held:
                        edges.setdefault((held, acq), []).append(
                            (f"{ci.methods[mname].qualname} -> {callee}",
                             line))
    findings = []
    seen = set()
    for (a, b), sites in sorted(edges.items()):
        if (b, a) in edges and (b, a) not in seen:
            seen.add((a, b))
            other = edges[(b, a)]
            findings.append(AstFinding(
                rule="RACE003", path=ci.path, line=sites[0][1],
                message=(
                    f"lock-order inversion in {ci.name}: '{b}' is "
                    f"acquired under '{a}' at {sites[0][0]} (line "
                    f"{sites[0][1]}) but '{a}' under '{b}' at "
                    f"{other[0][0]} (line {other[0][1]}) — two threads "
                    "taking the pair in opposite orders deadlock; "
                    "impose one global order"
                ),
            ))
    return findings


def _race_toctou(path: str, tree: ast.Module, parents: dict) -> list:
    """RACE004: exists/stat-then-use on one path without an OSError
    guard, in files whose directories background threads mutate."""

    def _arg_names(call: ast.Call) -> set:
        names = set()
        for a in call.args:
            for n in ast.walk(a):
                if isinstance(n, ast.Name):
                    names.add(n.id)
        return names

    def _guarded(node: ast.AST) -> bool:
        """Inside a try whose handlers catch OSError-family (or
        broader), or inside an except handler (cleanup path — the
        original operation already failed)."""
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ExceptHandler):
                return True
            if isinstance(cur, ast.Try):
                for h in cur.handlers:
                    if h.type is None:
                        return True
                    names = {_term(t) for t in (
                        h.type.elts if isinstance(h.type, ast.Tuple)
                        else [h.type])}
                    if names & {"OSError", "IOError", "FileNotFoundError",
                                "Exception", "BaseException", "EnvironmentError"}:
                        return True
            cur = parents.get(cur)
        return False

    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        checks: dict = {}
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Call) and _term(sub.func) \
                    in _EXISTS_FUNCS:
                for nm in _arg_names(sub):
                    checks[nm] = sub
        if not checks:
            continue
        if _guarded(node):
            continue
        # body only: an else/elif branch runs when the exists-check
        # was FALSE — a sink there is not gated by it
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) and _term(sub.func) \
                        in _TOCTOU_SINKS:
                    hit = _arg_names(sub) & set(checks)
                    if hit and not _guarded(sub):
                        nm = sorted(hit)[0]
                        findings.append(AstFinding(
                            rule="RACE004", path=path, line=sub.lineno,
                            message=(
                                f"'{_term(sub.func)}({nm})' is gated by "
                                f"a '{_term(checks[nm].func)}({nm})' "
                                f"check at line {node.lineno} with no "
                                "OSError guard: the prune/scrubber/"
                                "reload threads mutate these "
                                "directories between check and use — "
                                "wrap the use in try/except "
                                "(FileNotFoundError is a normal "
                                "outcome here), or spmd_exempt with "
                                "why no other thread touches the path"
                            ),
                        ))
    return findings


def _race_publish(ci: ClassInfo) -> list:
    """RACE005: a method writes >=2 plain attributes bare while another
    context reads >=2 of them inside a lock-held region — the reader's
    lock implies it wants a coherent pair the writer never publishes
    atomically."""
    by_attr = _class_accesses(ci)
    # locked group reads: (func, lockset) -> attrs read under the lock
    group_reads: dict = {}
    # per-method bare writes, from the same (cached) extraction
    per_method_bare: dict = {}
    for attr, accs in by_attr.items():
        for a in accs:
            if a.kind == "read" and a.locks:
                group_reads.setdefault((a.func, a.locks), set()).add(attr)
            elif a.kind != "read" and not a.locks:
                per_method_bare.setdefault(a.func, {}).setdefault(attr, a)
    findings = []
    for m in ci.methods.values():
        bare_writes = per_method_bare.get(m, {})
        if len(bare_writes) < 2:
            continue
        for (reader, locks), attrs in group_reads.items():
            if reader is m:
                continue
            shared = attrs & set(bare_writes)
            if len(shared) < 2:
                continue
            if not (reader.contexts - m.contexts) and not (
                    m.contexts - reader.contexts):
                continue  # same contexts: no interleaving possible
            first = min((bare_writes[a] for a in shared),
                        key=lambda a: a.line)
            findings.append(AstFinding(
                rule="RACE005", path=ci.path, line=first.line,
                message=(
                    f"{ci.name}.{m.name} publishes "
                    f"{sorted(shared)} bare while "
                    f"{reader.qualname} reads the pair under "
                    f"{sorted(locks)} — the reader's lock cannot make "
                    "a multi-field publish atomic; write both fields "
                    "under the same lock, or publish one immutable "
                    "tuple by reference"
                ),
            ))
            break
    return findings


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------


def load_sources(
        overrides: Optional[dict] = None) -> dict:
    sources = {}
    for p in CONCURRENCY_FILES:
        with open(p) as f:
            sources[p] = f.read()
    if overrides:
        sources.update(overrides)
    return sources


def build_model(source_overrides: Optional[dict] = None) -> _Model:
    return _Model(load_sources(source_overrides))


def thread_inventory(model: Optional[_Model] = None) -> list:
    """The discovered thread model: one dict per spawn site (role,
    target, file, line, whether it carries a stable ``tmpi-<role>``
    name). The stress harness and the README table consume this."""
    model = model or build_model()
    return [
        {"role": s.role, "target": s.target,
         "path": os.path.relpath(s.path, _PKG_ROOT), "line": s.line,
         "named": s.named}
        for s in sorted(model.spawns,
                        key=lambda s: (s.path, s.line))
    ]


# the reviewed thread-model snapshot: every spawn site (role, target,
# file, named-ness). A new background thread, a renamed role, or a
# spawn losing its stable tmpi-<role> name is a wire-protocol-grade
# change for post-mortem attribution — it fails CI until accepted via
# `tmpi lint --update-golden`, exactly like the collective-signature
# and preflight goldens.
GOLDEN_THREAD_MODEL = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "golden",
    "thread_model.json")


def _inventory_payload(model: "_Model") -> list:
    """The golden-stable projection of the inventory: no line numbers
    (they churn on unrelated edits), sorted."""
    rows = [
        {"role": s["role"], "target": s["target"], "path": s["path"],
         "named": s["named"]}
        for s in thread_inventory(model)
    ]
    return sorted(rows, key=lambda r: (r["path"], r["target"], r["role"]))


def check_thread_model_golden(model: "_Model",
                              update: bool = False) -> list:
    """RACE101: the discovered thread model drifted from its golden."""
    import json

    payload = _inventory_payload(model)
    if update:
        os.makedirs(os.path.dirname(GOLDEN_THREAD_MODEL), exist_ok=True)
        with open(GOLDEN_THREAD_MODEL, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        return []
    if not os.path.isfile(GOLDEN_THREAD_MODEL):
        return [AstFinding(
            rule="RACE101", path=GOLDEN_THREAD_MODEL, line=0,
            message="thread-model golden missing — run `tmpi lint "
                    "--update-golden` and review the inventory")]
    with open(GOLDEN_THREAD_MODEL) as f:
        stored = json.load(f)
    if stored == payload:
        return []
    key = lambda r: (r["path"], r["target"], r["role"])  # noqa: E731
    stored_keys = {key(r) for r in stored}
    new_keys = {key(r) for r in payload}
    added = sorted(new_keys - stored_keys)
    removed = sorted(stored_keys - new_keys)
    changed = [k for k in sorted(new_keys & stored_keys)
               if next(r for r in payload if key(r) == k)
               != next(r for r in stored if key(r) == k)]
    return [AstFinding(
        rule="RACE101", path=GOLDEN_THREAD_MODEL, line=0,
        message=(
            "discovered thread model drifted from the reviewed golden "
            f"(added: {added or 'none'}; removed: {removed or 'none'}; "
            f"changed: {changed or 'none'}) — a new or renamed "
            "background thread changes post-mortem attribution; give "
            "it a stable tmpi-<role> name and accept with `tmpi lint "
            "--update-golden`"
        ))]


def concurrency_findings(
        source_overrides: Optional[dict] = None,
        update_golden: bool = False,
        check_golden: bool = True) -> list:
    """Run every RACE rule over the concurrency file set (optionally
    with in-memory source overrides — the mutation self-tests feed
    edited sources through here; fixture-only overrides usually pass
    ``check_golden=False`` since an added fixture file IS a thread-
    model change)."""
    model = build_model(source_overrides)
    findings: list = []
    if update_golden or check_golden:
        findings.extend(check_thread_model_golden(
            model, update=update_golden))
    for ci in sorted(model.classes.values(), key=lambda c: (c.path, c.name)):
        if ci.is_http_handler:
            # per-request instances are thread-confined: every request
            # gets a fresh handler object on its own server thread
            continue
        findings.extend(_race_shared_writes(ci))
        findings.extend(_race_lock_order(ci))
        findings.extend(_race_publish(ci))
    for path in sorted(model.trees):
        tree = model.trees[path]
        findings.extend(_race_toctou(path, tree, model.parents))
    return findings


def run_concurrency_lints(update_golden: bool = False) -> list:
    """tools/lint.py entry point."""
    return concurrency_findings(update_golden=update_golden)
