"""Shared compile-cache-bypassing lowering for the analyzer families.

Two lint families read COMPILED truth off a lowered executable: the
memory pre-flight (tools/analyze/memory.py — XLA ``memory_analysis()``)
and the sharding analyzer (tools/analyze/sharding.py — per-leaf
``input_shardings`` + the optimized-HLO collective set). Both need the
same discipline:

1. **Lower, never execute** — ``jitted.lower(*args).compile()`` over
   abstract operands (the PR-9 ``compiled_cost()`` rule).
2. **Bypass the persistent compilation cache** — a cache-DESERIALIZED
   executable drops its metadata: ``alias_size_in_bytes`` reads 0
   (every donation would look failed, the MEM002 false positive) and
   the sharding/HLO views degrade the same way. Measured on this
   container's jax: the cache decision is LATCHED process-wide at the
   first compile (``is_cache_used`` memoizes), so the cache state is
   reset around the bypass and again after, letting surrounding code
   re-initialize with its configured dir.
3. **Compile each harness config ONCE** — the families share one
   process-level executable cache keyed by harness config, so adding a
   family costs parsing, not a second compile of the 20-config matrix
   (the ``tmpi lint`` <90 s budget).
"""

from __future__ import annotations


def lowered_compile(jitted, *args, **kwargs):
    """``jitted.lower(*args, **kwargs).compile()`` with the persistent
    compilation cache bypassed (see module docstring) — returns the
    ``Compiled`` object for metadata reads; nothing executes."""
    import jax

    try:
        from jax._src import compilation_cache as _cc
    except Exception:  # noqa: BLE001 — private module; degrade to dir-only
        _cc = None

    def _reset():
        if _cc is not None:
            try:
                _cc.reset_cache()
            except Exception:  # noqa: BLE001
                pass

    prev = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        _reset()
        return jitted.lower(*args, **kwargs).compile()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        _reset()


_EXEC_CACHE: dict = {}


def config_executable(key: tuple, jitted, args):
    """The memoized compiled executable for one analyzer configuration
    (``key`` = e.g. ``(engine, codec, fused[, part])``). The analyzed
    tree cannot change mid-process, and the memory + sharding families
    both read the SAME executable — one compile serves both."""
    if key not in _EXEC_CACHE:
        _EXEC_CACHE[key] = lowered_compile(jitted, *args)
    return _EXEC_CACHE[key]
