"""Host-side AST lints: rank divergence and use-after-donation.

The jaxpr rules verify the compiled program; these two passes verify
the HOST code around it — the multi-controller Python that every rank
executes independently and that must still agree with its peers:

**Rank-divergence lint** (SPMD301/SPMD302,
:func:`rank_divergence_findings`). Scanned files:
``launch/worker.py``, ``launch/supervisor.py``, ``utils/checkpoint.py``
— the code that decides what every controller does next. Sources of
rank-divergent values:

- wall clocks (``time.time()``/``monotonic()``/``perf_counter()``);
- unseeded stdlib/numpy randomness (``random.*``, ``np.random.*`` —
  ``jax.random`` with explicit keys is uniform by construction);
- directory listings not wrapped in ``sorted(...)``
  (``os.listdir``/``os.scandir``/``glob.glob``: shared-storage
  ordering is filesystem- and cache-dependent per host — the PR 4
  rollback bug class);
- device enumeration not wrapped in ``sorted(...)``
  (``jax.devices()``/``jax.local_devices()``: backend enumeration
  order is unspecified across processes, and the elastic PR derives
  the cross-rank reshard transfer plan from the probed world — an
  unsorted probe gating ``load_resharded``/``put_resharded`` is the
  PR 8 divergence class);
- iteration over freshly-built sets (hash order).

SPMD302 flags every unsorted listing outright (any consumer of an
ordering-dependent result is a latent divergence). SPMD301 is the
taint rule: a source-derived value reaching the predicate of an
``if``/``while`` whose body performs a cross-rank operation
(collective helpers, engine step/exchange dispatch, checkpoint saves)
means ranks can take different sides of a gate around gang-scheduled
work — the host-side mirror of the jaxpr rule SPMD002.

**Use-after-donation lint** (SPMD202, :func:`donation_findings`).
Engines donate their state buffers (``donate_argnums=(0,)``); after a
step dispatches, the PREVIOUS state's buffers are dead. On the CPU
backend ``np.asarray(donated_leaf)`` builds a zero-copy VIEW of that
dying buffer (the flight-recorder crash class fixed in round 6: a
snapshot read garbage after the next dispatch). Any
``np.asarray``/``jnp.asarray`` whose argument mentions a name that is
also passed as the state operand of a donating engine call in the same
function is flagged — snapshots of donated state must copy
(``np.array``), not alias.

Both passes honor the shared ``spmd_exempt: <reason>`` suppression
(checked centrally in tools/lint.py).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Optional

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the host code whose per-rank agreement the SPMD programs depend on
RANK_DIVERGENCE_FILES = (
    os.path.join(_PKG_ROOT, "launch", "worker.py"),
    os.path.join(_PKG_ROOT, "launch", "supervisor.py"),
    os.path.join(_PKG_ROOT, "utils", "checkpoint.py"),
)
# host code that snapshots / inspects engine state around donating steps
DONATION_FILES = (
    os.path.join(_PKG_ROOT, "launch", "worker.py"),
    os.path.join(_PKG_ROOT, "obs", "flight.py"),
)

# call names producing rank-divergent values
_CLOCK_FUNCS = {"time", "monotonic", "perf_counter", "time_ns",
                "monotonic_ns"}
_LISTING_FUNCS = {"listdir", "scandir", "glob", "iglob"}
# terminal attribute/function names that constitute cross-rank work:
# host collective helpers + the engine dispatch protocol + checkpoint
# writes (every rank must agree to save/restore the same step)
_SINK_NAMES = {
    "process_allgather", "broadcast_one_to_all", "sync_global_devices",
    "train_step", "fused_train_step", "exchange", "eval_step",
    "save_checkpoint", "save_checkpoint_sharded", "load_checkpoint",
    "psum", "pmean", "all_gather",
    # elastic PR: the reshard transfer plan is cross-rank gang work —
    # every controller must compute the identical plan, so a
    # rank-divergent value gating it is the same bug class as a gated
    # collective
    "load_resharded", "put_resharded",
}
# device-enumeration calls: order (and, mid-failure, membership) is
# rank-divergent until pinned by sorted(...)
_DEVICE_FUNCS = {"devices", "local_devices"}
# the sources whose divergence is purely ORDERING — these (and only
# these) are laundered by a lexically-enclosing sorted(...); clock and
# random reads diverge by VALUE and no sort fixes that
_ORDERING_FUNCS = _LISTING_FUNCS | _DEVICE_FUNCS
# engine-protocol calls whose FIRST positional argument is donated
_DONATING_CALLS = {"train_step", "fused_train_step", "exchange"}


@dataclass
class AstFinding:
    rule: str
    path: str
    line: int
    message: str


def _terminal_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _qualifier(func: ast.expr) -> Optional[str]:
    """``np`` for ``np.random.rand`` / ``os`` for ``os.listdir``..."""
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_source_call(node: ast.Call) -> Optional[str]:
    """Human-readable source label if this call yields a rank-divergent
    value, else None. Unsorted listings are handled separately
    (SPMD302) but also taint."""
    name = _terminal_name(node.func)
    qual = _qualifier(node.func)
    if name in _CLOCK_FUNCS and qual == "time":
        return f"time.{name}()"
    if name in _LISTING_FUNCS and qual in ("os", "glob"):
        return f"{qual}.{name}()"
    if name in _DEVICE_FUNCS and qual == "jax":
        return f"jax.{name}()"
    if qual in ("random",) and isinstance(node.func, ast.Attribute):
        return f"random.{name}()"
    if isinstance(node.func, ast.Attribute) and isinstance(
            node.func.value, ast.Attribute):
        # np.random.* / numpy.random.*
        mid = node.func.value
        if mid.attr == "random" and isinstance(mid.value, ast.Name) and \
                mid.value.id in ("np", "numpy"):
            return f"np.random.{name}()"
    return None


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _contains_sink(node: ast.AST) -> Optional[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            if _terminal_name(sub.func) in _SINK_NAMES:
                return sub
    return None


def _parent_map(tree: ast.AST) -> dict:
    parents: dict = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _inside_sorted(node: ast.AST, parents: dict) -> bool:
    """Is this call lexically under a ``sorted(...)`` argument list
    (directly, or through a comprehension — ``sorted(f(x) for x in
    os.listdir(d))`` counts: the ordering dependence dies at the
    sort)?"""
    cur = parents.get(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
        if isinstance(cur, ast.Call) and isinstance(cur.func, ast.Name) \
                and cur.func.id == "sorted":
            return True
        cur = parents.get(cur)
    return False


def rank_divergence_findings(path: str, source: str) -> list:
    """SPMD301 (tainted predicate gating cross-rank work) + SPMD302
    (unsorted directory listing) over one file."""
    tree = ast.parse(source)
    parents = _parent_map(tree)
    findings: list = []

    # ---- SPMD302: every unsorted listing --------------------------------
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _terminal_name(node.func)
            qual = _qualifier(node.func)
            if name in _LISTING_FUNCS and qual in ("os", "glob") and \
                    not _inside_sorted(node, parents):
                findings.append(AstFinding(
                    rule="SPMD302", path=path, line=node.lineno,
                    message=(
                        f"unsorted {qual}.{name}(...): directory order is "
                        "filesystem- and attribute-cache-dependent, so "
                        "ranks sharing storage can see different orders — "
                        "wrap in sorted(...) (or spmd_exempt with why "
                        "ordering cannot matter)"
                    ),
                ))

    # ---- SPMD301: taint -> gated cross-rank work -------------------------
    def _src_label(call: ast.Call):
        """Source label, unless the call is an ORDERING-divergent
        source (directory listing / device enumeration) lexically under
        a ``sorted(...)`` — the ordering dependence dies at the sort,
        exactly as in SPMD302. VALUE-divergent sources (time.*,
        unseeded random) stay tainted: sorting a clock read does not
        make it rank-uniform."""
        lbl = _is_source_call(call)
        if lbl and _terminal_name(call.func) in _ORDERING_FUNCS \
                and _inside_sorted(call, parents):
            return None
        return lbl

    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        tainted: set = set()
        labels: dict = {}
        # two passes so loop-carried assignments propagate
        for _ in range(2):
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) or isinstance(
                        node, ast.AnnAssign):
                    value = node.value
                    if value is None:
                        continue
                    src_label = None
                    for sub in ast.walk(value):
                        if isinstance(sub, ast.Call):
                            src_label = src_label or _src_label(sub)
                    used = _names_in(value) & tainted
                    if src_label or used:
                        targets = (node.targets if isinstance(
                            node, ast.Assign) else [node.target])
                        for t in targets:
                            for nm in _names_in(t):
                                tainted.add(nm)
                                labels.setdefault(
                                    nm, src_label or labels.get(
                                        next(iter(used), None),
                                        "tainted value"))
                # set iteration: for x in set(...) / {..} — hash order
                if isinstance(node, (ast.For, ast.comprehension)):
                    it = node.iter
                    is_set = isinstance(it, ast.Set) or (
                        isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Name)
                        and it.func.id in ("set", "frozenset"))
                    if is_set:
                        for nm in _names_in(node.target):
                            tainted.add(nm)
                            labels.setdefault(nm, "set iteration order")

        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            test_sources = []
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Call):
                    lbl = _src_label(sub)
                    if lbl:
                        test_sources.append(lbl)
            hit = _names_in(node.test) & tainted
            if not (test_sources or hit):
                continue
            sink = _contains_sink(node)
            if sink is None:
                continue
            what = test_sources[0] if test_sources else \
                f"{sorted(hit)[0]} (from {labels.get(sorted(hit)[0], 'a rank-divergent source')})"
            findings.append(AstFinding(
                rule="SPMD301", path=path, line=node.lineno,
                message=(
                    f"rank-divergent value {what} gates "
                    f"'{_terminal_name(sink.func)}(...)' at line "
                    f"{sink.lineno}: controllers can take different sides "
                    "of this branch around gang-scheduled work — derive "
                    "the predicate from rank-uniform state (step counters, "
                    "allgathered agreement) or spmd_exempt with the "
                    "uniformity argument"
                ),
            ))
    return findings


def donation_findings(path: str, source: str) -> list:
    """SPMD202: ``np.asarray``/``jnp.asarray`` aliasing a name that is
    donated to an engine step in the same function."""
    tree = ast.parse(source)
    findings: list = []
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        donated: set = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and \
                    node.func.attr in _DONATING_CALLS and node.args:
                first = node.args[0]
                if isinstance(first, ast.Name):
                    donated.add(first.id)
                elif isinstance(first, ast.Attribute) and isinstance(
                        first.value, ast.Name):
                    donated.add(first.value.id)
        if not donated:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and _terminal_name(
                    node.func) == "asarray":
                qual = _qualifier(node.func)
                if qual not in ("np", "numpy", "jnp"):
                    continue
                used = set()
                for a in node.args:
                    used |= _names_in(a)
                alias = used & donated
                if alias:
                    findings.append(AstFinding(
                        rule="SPMD202", path=path, line=node.lineno,
                        message=(
                            f"{qual}.asarray(...) aliases "
                            f"{sorted(alias)[0]!r}, which is donated to a "
                            "jitted engine step in this function — on CPU "
                            "asarray is a zero-copy view of a buffer the "
                            "next dispatch invalidates; snapshot with "
                            "np.array (copies) or spmd_exempt with why "
                            "the view cannot outlive the buffer"
                        ),
                    ))
    return findings


def run_ast_lints() -> list:
    """Both passes over their default file sets."""
    findings: list = []
    for p in RANK_DIVERGENCE_FILES:
        with open(p) as f:
            findings.extend(rank_divergence_findings(p, f.read()))
    for p in DONATION_FILES:
        with open(p) as f:
            findings.extend(donation_findings(p, f.read()))
    return findings
