"""Sharding & layout analyzer — declared specs vs GSPMD's compiled truth.

The fourth lint leg (after graph/PR 7, memory/PR 12, host threads/
PR 14): every engine x codec x ``--fused-update`` configuration from
the preflight harness is LOWERED (never executed) through the shared
cache-bypassing compile (tools/analyze/lowering.py) and the COMPILED
truth is read off the executable:

- the per-leaf input shardings (``compiled.input_shardings`` — what
  GSPMD actually assigned each state leaf), checked against the
  engine's :class:`~theanompi_tpu.parallel.recipe.ShardingRecipe`
  declaration;
- the optimized-HLO collective set (``compiled.as_text()``), priced in
  wire bytes with the same ring-lowering formulas the traced-jaxpr
  accounting uses (tools/analyze/signature.py), and reconciled against
  BOTH the traced signature and the declared ``traffic_model()``.

Rules (IDs in tools/lint.py RULES):

- **SHARD001 declared-vs-compiled spec mismatch** — a state leaf whose
  compiled input sharding is not equivalent to the recipe's declared
  spec; also flags hand-rolled ``PartitionSpec(...)`` construction
  inside the engine/serve modules (specs must come from the recipe).
- **SHARD002 implicit resharding / hidden wire** — collective traffic
  present in the optimized HLO but absent from the traced jaxpr
  (GSPMD-inserted all-gather/all-to-all/collective-permute: the
  hidden-wire hazard GC3 schedules around), priced in bytes per
  collective kind; plus the compiled-truth cross-check that the
  executable's total wire agrees with the declared ``traffic_model()``
  raw bytes under the SPMD101 tolerance (codec-off configs — the
  codec-on wire is SPMD102's job).
- **SHARD003 replication bloat** — a leaf the recipe (and therefore
  ``memory_model()``/the preflight 1/n division) declares sharded that
  GSPMD compiled fully REPLICATED: the memory table is a lie, every
  device holds the whole buffer.
- **SHARD004 train->serve handoff drift** — serve's template/load
  specs (serve/reload.py ``serving_leaf_specs``) vs the training
  engine's recipe specs for the leaves serving consumes — the same
  declaration the checkpoint ``__topology__`` manifest stamps.
- **SHARD101 golden drift** — the declared per-leaf spec table drifted
  from the reviewed snapshot (``golden/sharding_*.json``; regenerate
  with ``tmpi lint --update-golden``).

Caveat: HLO collective pricing counts each op once — a collective
inside an HLO ``while`` body is priced per appearance, not per trip.
The preflight steps carry no loops (fused dispatch's ``lax.scan``
configs are pinned by the SPMD schedule goldens instead).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Optional

from theanompi_tpu.tools.analyze.rules import (
    Finding,
    TRAFFIC_ABS_TOL,
    TRAFFIC_REL_TOL,
)

# HLO collective kinds and the jaxpr primitives that legitimately
# produce them — anything in the compiled set beyond the traced set is
# GSPMD-inserted (implicit resharding)
HLO_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
             "collective-permute", "all-to-all")
_PRIM_TO_KIND = {
    "psum": "all-reduce", "pmin": "all-reduce", "pmax": "all-reduce",
    "all_gather": "all-gather", "pgather": "all-gather",
    "reduce_scatter": "reduce-scatter",
    "ppermute": "collective-permute",
    "all_to_all": "all-to-all",
}

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16,
}

# `= <result type> <collective>(` — the lhs %op names and fusion
# operands never match (no type+paren juxtaposition); `-start`/`-done`
# async halves: only the start carries the wire (the done's operand is
# the start token, and its trailing `-done(`/`-start(` spelling fails
# the `\(` anchor on the base name)
_HLO_COLL_RE = re.compile(
    r"=\s+(\([^)]*\)|[a-z0-9]+\[[^\]]*\](?:\{[^}]*\})?)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(-start)?\("
)
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([^\]]*)\]")
_GROUPS_RE = re.compile(r"replica_groups=\{\{([0-9, ]+)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")


def _type_bytes(type_str: str) -> float:
    """Total bytes of one HLO type string (tuple types sum)."""
    total = 0.0
    for dt, dims in _SHAPE_RE.findall(type_str):
        if dt not in _DTYPE_BYTES:
            continue
        elems = 1
        for d in dims.split(","):
            d = d.strip()
            if d:
                elems *= int(d)
        total += elems * _DTYPE_BYTES[dt]
    return total


@dataclass(frozen=True)
class HloCollective:
    kind: str
    result_bytes: float
    operand_bytes: float
    group_size: int

    def wire_bytes(self) -> float:
        """Per-device wire bytes, same ring-lowering convention as
        signature.collective_wire_bytes: allreduce 2(n-1)/n·B, the
        gather/scatter halves (n-1)/n of the FULL buffer, permute B.
        all-gather is sized by its result (the full gathered buffer),
        reduce-scatter by its operand (the full pre-scatter buffer)."""
        n = max(1, self.group_size)
        if n == 1:
            return 0.0
        if self.kind == "all-reduce":
            return 2.0 * (n - 1) / n * self.result_bytes
        if self.kind == "all-gather":
            return (n - 1) / n * self.result_bytes
        if self.kind == "reduce-scatter":
            return (n - 1) / n * self.operand_bytes
        if self.kind == "collective-permute":
            return self.result_bytes
        return (n - 1) / n * self.result_bytes  # all-to-all


def hlo_collectives(hlo_text: str, default_group: int = 2) -> list:
    """Every collective in an optimized-HLO module, with result/operand
    bytes and the participant-group size parsed off the op line.

    Async pairs (``*-start``/``*-done``, the standard TPU lowering):
    only the start is priced, and its TUPLE result aliases the
    operand(s) next to the in-flight destination — summing the tuple
    would double-count the wire, so starts are sized by their operands
    (all-gather/all-to-all by the largest tuple member, the gathered
    destination)."""
    out = []
    for line in hlo_text.splitlines():
        m = _HLO_COLL_RE.search(line)
        if not m:
            continue
        result_t, kind, is_start = m.group(1), m.group(2), bool(m.group(3))
        # the call argument list: everything inside the op's (balanced)
        # parens — the operand types sum over ALL data operands
        depth, i = 1, m.end()
        while i < len(line) and depth:
            if line[i] == "(":
                depth += 1
            elif line[i] == ")":
                depth -= 1
            i += 1
        operand_b = _type_bytes(line[m.end():i - 1])
        member_bytes = [
            _type_bytes(f"{dt}[{dims}]")
            for dt, dims in _SHAPE_RE.findall(result_t)
        ]
        if is_start:
            if kind in ("all-gather", "all-to-all"):
                result_b = max(member_bytes) if member_bytes else operand_b
            else:
                result_b = operand_b
        else:
            result_b = sum(member_bytes)
        gm = _GROUPS_RE.search(line)
        if gm:
            group = len([x for x in gm.group(1).split(",") if x.strip()])
        else:
            gi = _GROUPS_IOTA_RE.search(line)
            group = int(gi.group(2)) if gi else default_group
        out.append(HloCollective(
            kind=kind, result_bytes=result_b,
            operand_bytes=operand_b, group_size=group,
        ))
    return out


def hlo_kind_bytes(colls: list) -> dict:
    out = {k: 0.0 for k in HLO_KINDS}
    for c in colls:
        out[c.kind] = out.get(c.kind, 0.0) + c.wire_bytes()
    return out


def traced_kind_bytes(sig, axis_sizes: dict) -> dict:
    """The traced jaxpr signature's wire bytes grouped by the HLO
    collective kind each primitive lowers to."""
    from theanompi_tpu.tools.analyze.signature import collective_wire_bytes

    out = {k: 0.0 for k in HLO_KINDS}
    for c in sig.collectives:
        kind = _PRIM_TO_KIND.get(c.prim)
        if kind is None:
            continue
        out[kind] = out.get(kind, 0.0) + \
            collective_wire_bytes(c, axis_sizes) * c.count
    return out


@dataclass
class LeafCheck:
    """One state leaf: the recipe's declared spec vs the compiled
    input sharding GSPMD assigned it."""

    path: str
    declared: "object"  # PartitionSpec
    ndim: int
    compiled: "object"  # jax Sharding off input_shardings
    factor: int  # declared shard factor (mesh extent of the spec)

    def compiled_matches(self, mesh) -> bool:
        from jax.sharding import NamedSharding

        try:
            return bool(self.compiled.is_equivalent_to(
                NamedSharding(mesh, self.declared), self.ndim))
        except Exception:  # noqa: BLE001 — incomparable = mismatch
            return False

    def compiled_replicated(self) -> bool:
        return bool(getattr(self.compiled, "is_fully_replicated", False))


@dataclass
class PartWire:
    """One traced program's wire picture: traced-vs-compiled per-kind
    bytes, amortized by the part's execution weight."""

    name: str
    weight: float
    traced: dict
    compiled: dict


@dataclass
class ShardReport:
    engine: str
    codec: str
    fused: bool
    mesh: "object"
    leaves: list = field(default_factory=list)  # list[LeafCheck]
    parts: list = field(default_factory=list)  # list[PartWire]
    declared_raw_bytes: float = 0.0  # traffic_model amortized raw

    @property
    def compiled_wire_amortized(self) -> float:
        return sum(sum(p.compiled.values()) * p.weight for p in self.parts)

    @property
    def traced_wire_amortized(self) -> float:
        return sum(sum(p.traced.values()) * p.weight for p in self.parts)

    @property
    def hidden_bytes(self) -> float:
        """Total positive compiled-minus-traced wire per kind — the
        GSPMD-inserted share."""
        total = 0.0
        for p in self.parts:
            for k in HLO_KINDS:
                d = p.compiled.get(k, 0.0) - p.traced.get(k, 0.0)
                if d > 0:
                    total += d * p.weight
        return total

    def tag(self) -> str:
        return (f"[{self.engine}/{self.codec}"
                f"{'/fused' if self.fused else ''}]")


# --------------------------------------------------------------------------
# report construction
# --------------------------------------------------------------------------


def _state_leaf_shardings(compiled, state_template) -> list:
    """``[(path_str, sharding)]`` for the state argument (arg 0) of a
    compiled step — ``input_shardings`` returns per-arg pytrees of
    shardings whose structure matches the args."""
    import jax

    arg_shardings = compiled.input_shardings[0][0]
    out = []
    for path, sh in jax.tree_util.tree_flatten_with_path(arg_shardings)[0]:
        out.append((jax.tree_util.keystr(path), sh))
    return out


def analyze_step_sharding(compiled, state_template, recipe,
                          traced_sig, axis_sizes: dict,
                          engine: str = "", codec: str = "none",
                          fused: bool = False,
                          part: str = "step", weight: float = 1.0,
                          ) -> ShardReport:
    """Reconcile ONE compiled program against its recipe declaration and
    traced signature — the building block the matrix sweep and the
    mutation self-tests share."""
    import jax

    declared = dict(recipe.leaf_specs(state_template))
    compiled_sh = dict(_state_leaf_shardings(compiled, state_template))
    tmpl = {jax.tree_util.keystr(p): l for p, l in
            jax.tree_util.tree_flatten_with_path(state_template)[0]}
    leaves = []
    for path, spec in declared.items():
        if path not in compiled_sh:
            continue  # structure drift is a trace failure elsewhere
        leaves.append(LeafCheck(
            path=path, declared=spec,
            ndim=len(getattr(tmpl[path], "shape", ())),
            compiled=compiled_sh[path],
            factor=recipe.shard_factor(spec),
        ))
    n_default = 1
    for s in axis_sizes.values():
        n_default *= int(s)
    wire = PartWire(
        name=part, weight=float(weight),
        traced=traced_kind_bytes(traced_sig, axis_sizes),
        compiled=hlo_kind_bytes(hlo_collectives(
            compiled.as_text(), default_group=max(2, n_default))),
    )
    return ShardReport(engine=engine, codec=codec, fused=bool(fused),
                       mesh=recipe.mesh, leaves=leaves, parts=[wire])


_REPORT_CACHE: dict = {}


def config_shard_report(name: str, codec: str, fused: bool):
    """``(ShardReport | None, error | None)`` for one harness config,
    memoized per process. EASGD adds its elastic-exchange program as a
    second part (amortized 1/avg_freq), mirroring the SPMD harness."""
    from theanompi_tpu.tools.analyze import harness
    from theanompi_tpu.tools.analyze.lowering import config_executable
    from theanompi_tpu.tools.analyze.signature import extract_signature

    key = (name, codec, fused)
    if key in _REPORT_CACHE:
        return _REPORT_CACHE[key]
    pre = harness.preflight_trace(name, codec, fused)
    if pre.error is not None:
        _REPORT_CACHE[key] = (None, pre.error)
        return _REPORT_CACHE[key]
    try:
        import jax

        recipe = pre.eng.sharding_recipe()
        report = None
        step_axes: dict = {}
        # the per-engine program list comes from the harness
        # (PreflightTrace.parts) — one enumeration shared with the
        # SPMD family, so an engine growing a second traced program
        # cannot silently escape the wire reconciliation here
        for i, (part_name, fn, args, weight) in enumerate(pre.parts):
            ckey = key if i == 0 else key + (part_name,)
            compiled = config_executable(ckey, fn, args)
            jaxpr = pre.jaxpr if i == 0 else jax.make_jaxpr(fn)(*args)
            sig, axis_sizes = extract_signature(jaxpr)
            if i == 0:
                step_axes = axis_sizes
                report = analyze_step_sharding(
                    compiled, pre.state, recipe, sig, axis_sizes,
                    engine=name, codec=codec, fused=fused,
                    part=part_name, weight=weight,
                )
            else:
                axis_sizes = axis_sizes or step_axes
                n_default = 1
                for s in axis_sizes.values():
                    n_default *= int(s)
                report.parts.append(PartWire(
                    name=part_name, weight=float(weight),
                    traced=traced_kind_bytes(sig, axis_sizes),
                    compiled=hlo_kind_bytes(hlo_collectives(
                        compiled.as_text(),
                        default_group=max(2, n_default))),
                ))
        report.declared_raw_bytes = float(
            pre.eng.traffic_model(pre.state).raw_bytes_per_step_amortized)
        _REPORT_CACHE[key] = (report, None)
    except Exception as e:  # noqa: BLE001 — becomes a finding
        _REPORT_CACHE[key] = (None, f"{type(e).__name__}: {e}")
    return _REPORT_CACHE[key]


# --------------------------------------------------------------------------
# rule families
# --------------------------------------------------------------------------


def spec_findings(report: ShardReport) -> list:
    """SHARD001 (declared vs compiled) + SHARD003 (replication bloat)
    over one report's leaf table."""
    out = []
    tag = report.tag()
    for leaf in report.leaves:
        matches = leaf.compiled_matches(report.mesh)
        if not matches:
            out.append(Finding(
                rule="SHARD001", path="", line=0, engine=report.engine,
                message=(
                    f"{tag} state leaf {leaf.path} declares spec "
                    f"{leaf.declared} but the compiled executable "
                    f"assigned {leaf.compiled} — the recipe and GSPMD "
                    "disagree about this leaf's layout"
                ),
            ))
        if leaf.factor > 1 and leaf.compiled_replicated():
            out.append(Finding(
                rule="SHARD003", path="", line=0, engine=report.engine,
                message=(
                    f"{tag} state leaf {leaf.path} is declared sharded "
                    f"{leaf.factor}-way ({leaf.declared}) but compiled "
                    "fully REPLICATED — memory_model()'s 1/"
                    f"{leaf.factor} division (and the preflight peak) "
                    "is a lie; every device holds the whole buffer"
                ),
            ))
    return out


def hidden_wire_findings(report: ShardReport) -> list:
    """SHARD002: per-kind compiled-vs-traced wire reconciliation, plus
    (codec-off) the compiled-total vs declared ``traffic_model()``
    cross-check under the SPMD101 tolerance."""
    out = []
    tag = report.tag()
    for p in report.parts:
        for kind in HLO_KINDS:
            traced = p.traced.get(kind, 0.0)
            compiled = p.compiled.get(kind, 0.0)
            tol = max(TRAFFIC_ABS_TOL,
                      TRAFFIC_REL_TOL * max(traced, compiled))
            if compiled - traced > tol:
                out.append(Finding(
                    rule="SHARD002", path="", line=0,
                    engine=report.engine,
                    message=(
                        f"{tag}:{p.name} GSPMD inserted "
                        f"{compiled - traced:.0f} B/step of {kind} "
                        f"wire the traced program never posted "
                        f"(traced {traced:.0f} B, compiled "
                        f"{compiled:.0f} B) — implicit resharding; "
                        "fix the operand layouts or declare the wire "
                        "in traffic_model()"
                    ),
                ))
            elif traced - compiled > tol:
                out.append(Finding(
                    rule="SHARD002", path="", line=0,
                    engine=report.engine,
                    message=(
                        f"{tag}:{p.name} the compiled executable moves "
                        f"{traced - compiled:.0f} B/step LESS {kind} "
                        f"wire than the traced program (traced "
                        f"{traced:.0f} B, compiled {compiled:.0f} B) — "
                        "XLA elided a collective the traffic/schedule "
                        "models still charge for"
                    ),
                ))
    if report.codec == "none" and report.declared_raw_bytes > 0:
        compiled_total = report.compiled_wire_amortized
        want = report.declared_raw_bytes
        tol = max(TRAFFIC_ABS_TOL,
                  TRAFFIC_REL_TOL * max(compiled_total, want))
        if abs(compiled_total - want) > tol:
            out.append(Finding(
                rule="SHARD002", path="", line=0, engine=report.engine,
                message=(
                    f"{tag} traffic_model() declares {want:.0f} raw "
                    f"B/step (amortized) but the COMPILED executables "
                    f"move {compiled_total:.0f} B/step — the hidden-"
                    "wire pricing and the declared model disagree "
                    "beyond the SPMD101 tolerance"
                ),
            ))
    return out


# --------------------------------------------------------------------------
# SHARD101 goldens: the declared per-leaf spec table
# --------------------------------------------------------------------------


def shard_payload(report: ShardReport) -> dict:
    from theanompi_tpu.parallel.mesh import spec_to_json

    return {
        "n_devices": int(report.mesh.devices.size),
        "leaves": {
            l.path: {"spec": spec_to_json(l.declared),
                     "factor": int(l.factor)}
            for l in report.leaves
        },
    }


def golden_shard_findings(report: ShardReport, update: bool = False) -> list:
    """SHARD101: declared spec table vs the reviewed snapshot."""
    from theanompi_tpu.tools.analyze import golden as G

    path = G.sharding_golden_path(report.engine, report.codec,
                                  report.fused)
    tag = report.tag()
    if update:
        G.write_sharding_golden(report.engine, report.codec, report.fused,
                                shard_payload(report))
        return []
    gold = G.load_sharding_golden(report.engine, report.codec,
                                  report.fused)
    if gold is None:
        return [Finding(
            rule="SHARD101", path=path, line=0, engine=report.engine,
            message=f"{tag} no sharding golden — run `tmpi lint "
                    "--update-golden` and review the spec table",
        )]
    payload = shard_payload(report)
    errs = G.diff_payload({k: gold.get(k) for k in payload}, payload)
    return [Finding(
        rule="SHARD101", path=path, line=0, engine=report.engine,
        message=f"{tag} declared spec table drifted from golden: {e} — "
                "if deliberate, regenerate with `tmpi lint "
                "--update-golden` and review the diff",
    ) for e in errs]


# --------------------------------------------------------------------------
# SHARD004: train -> serve handoff
# --------------------------------------------------------------------------


def handoff_findings(serve_specs: list, train_specs: list,
                     engine: str = "bsp") -> list:
    """Compare serve's declared template specs against the training
    engine's recipe specs for the leaves serving consumes (params +
    model_state) — the same per-leaf declaration the checkpoint
    ``__topology__`` manifest stamps. Both inputs are
    ``[(path, PartitionSpec)]``."""
    from theanompi_tpu.parallel.mesh import spec_to_json

    out = []
    s = {p: spec_to_json(sp) for p, sp in serve_specs}
    t = {p: spec_to_json(sp) for p, sp in train_specs
         if p.startswith(".params") or p.startswith(".model_state")}
    for path in sorted(set(s) | set(t)):
        if path not in s or path not in t:
            side = "serve template" if path not in s else "train recipe"
            out.append(Finding(
                rule="SHARD004", path="", line=0, engine=engine,
                message=(
                    f"train->serve handoff: leaf {path} is missing from "
                    f"the {side} — the serve load template and the "
                    "stamped training state structurally disagree"
                ),
            ))
        elif s[path] != t[path]:
            out.append(Finding(
                rule="SHARD004", path="", line=0, engine=engine,
                message=(
                    f"train->serve handoff drift on {path}: the "
                    f"training recipe stamps spec {t[path]} into the "
                    f"__topology__ manifest but serve's template "
                    f"declares {s[path]} — a pod-trained checkpoint "
                    "would be served under the wrong layout"
                ),
            ))
    return out


def serve_handoff_findings() -> list:
    """SHARD004 over the harness tiny model: the BSP training recipe
    (the engine serve's checkpoint-follow loads from) vs serve's
    declared template specs."""
    from theanompi_tpu.tools.analyze import harness

    pre = harness.preflight_trace("bsp", "none", False)
    if pre.error is not None:
        return []  # surfaced by the other families
    try:
        from theanompi_tpu.serve.reload import serving_leaf_specs

        serve_specs = serving_leaf_specs(pre.eng.model)
        train_specs = pre.eng.sharding_recipe().leaf_specs(pre.state)
    except Exception as e:  # noqa: BLE001 — becomes a finding
        return [Finding(
            rule="SHARD004", path="", line=0, engine="bsp",
            message=f"train->serve handoff check could not build its "
                    f"spec tables: {type(e).__name__}: {e}",
        )]
    return handoff_findings(serve_specs, train_specs, engine="bsp")


# --------------------------------------------------------------------------
# recipe source guard: engines/serve must not hand-roll PartitionSpecs
# --------------------------------------------------------------------------

_GUARDED_FILES = (
    "parallel/bsp.py", "parallel/zero.py", "parallel/easgd.py",
    "parallel/gosgd.py", "parallel/nd.py", "serve/engine.py",
    "serve/reload.py",
)


def recipe_source_findings(root: Optional[str] = None) -> list:
    """SHARD001 (source form): a ``PartitionSpec(...)`` CALL inside an
    engine or serve module — specs must come from the ShardingRecipe
    (or parallel/mesh.py's topology helpers), otherwise the analyzer's
    declared table and the program can silently diverge again.
    ``isinstance`` references and annotations are fine; only
    construction is flagged."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))  # theanompi_tpu/
    out = []
    for rel in _GUARDED_FILES:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        src = open(path).read()
        tree = ast.parse(src)
        # names bound to jax.sharding.PartitionSpec in this module; the
        # qualified forms (jax.sharding.PartitionSpec(...) or any
        # module alias's .PartitionSpec attribute) are caught by the
        # attribute check below regardless of import style
        aliases = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module and \
                    node.module.startswith("jax.sharding"):
                for a in node.names:
                    if a.name == "PartitionSpec":
                        aliases.add(a.asname or a.name)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            hit = (isinstance(fn, ast.Name) and fn.id in aliases) or (
                isinstance(fn, ast.Attribute)
                and (fn.attr == "PartitionSpec" or fn.attr in aliases))
            if hit:
                out.append(Finding(
                    rule="SHARD001", path=path, line=node.lineno,
                    engine="",
                    message=(
                        f"hand-rolled PartitionSpec construction in "
                        f"{rel} — specs must come from the engine's "
                        "ShardingRecipe (parallel/recipe.py) so the "
                        "declared table cannot drift from the program"
                    ),
                ))
    return out


# --------------------------------------------------------------------------
# the lint entry point + obs record
# --------------------------------------------------------------------------


def shard_record(report: ShardReport, findings_count: int = 0) -> dict:
    """The ``kind=shard`` lint-report record (tools/check_obs_schema.py)
    — per-config leaf counts and the hidden-collective byte total."""
    import time

    return {
        "kind": "shard", "t": time.time(),
        "engine": report.engine, "codec": report.codec,
        "fused": bool(report.fused),
        "n_devices": int(report.mesh.devices.size),
        "leaves": len(report.leaves),
        "mismatched": sum(1 for l in report.leaves
                          if not l.compiled_matches(report.mesh)),
        "hidden_bytes": float(report.hidden_bytes),
        "compiled_wire_bytes": float(report.compiled_wire_amortized),
        "traced_wire_bytes": float(report.traced_wire_amortized),
        "declared_raw_bytes": float(report.declared_raw_bytes),
        "findings": int(findings_count),
    }


def analyze_sharding(update_golden: bool = False,
                     obs_dir: Optional[str] = None) -> list:
    """SHARD001-004 + SHARD101 over the full preflight matrix (5
    engines x {none, int8:ef} x {unfused, fused}) plus the serve
    handoff and the recipe source guard. With ``obs_dir``, one
    ``kind=shard`` record per config is appended to
    ``<obs_dir>/metrics.jsonl``."""
    from theanompi_tpu.tools.analyze import harness

    findings: list = []
    records: list = []
    for name in harness.PREFLIGHT_ENGINES:
        for codec in harness.CODEC_SPECS:
            for fused in harness.FUSED_FLAGS:
                report, err = config_shard_report(name, codec, fused)
                if err is not None:
                    # un-lowerable config: routed to the family's
                    # golden/infrastructure rule like MEM101/PREC101
                    findings.append(Finding(
                        rule="SHARD101", path="", line=0, engine=name,
                        message=(
                            f"[{name}/{codec}"
                            f"{'/fused' if fused else ''}] sharding "
                            f"analyzer could not lower the step: {err}"
                        ),
                    ))
                    continue
                fs = (spec_findings(report)
                      + hidden_wire_findings(report)
                      + golden_shard_findings(report,
                                              update=update_golden))
                findings.extend(fs)
                if obs_dir:
                    records.append(shard_record(report, len(fs)))
    findings.extend(serve_handoff_findings())
    findings.extend(recipe_source_findings())
    if obs_dir and records:
        import json

        os.makedirs(obs_dir, exist_ok=True)
        with open(os.path.join(obs_dir, "metrics.jsonl"), "a") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
    return findings
