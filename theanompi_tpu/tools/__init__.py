"""Offline data tooling (shard conversion etc.)."""
