"""Codec-coverage lint: every engine routes its exchange through the
codec layer (or says, in writing, why not).

The compressed-collectives codec (``parallel/codec.py``) only pays off
if it stays UNIVERSAL — the moment a new engine hand-rolls its own
exchange without the codec hook, ``--wire-codec`` silently stops
covering part of the fleet and the comm-bytes win erodes one special
case at a time (exactly how the original int8 ring became a one-off).
This lint fails CI when any engine module under ``parallel/`` neither
references ``parallel.codec`` nor declares an explicit exemption::

    # codec_exempt: <reason the exchange cannot ride the codec>

Scope: an "engine module" is any ``parallel/*.py`` defining a class
with BOTH ``train_step`` and ``traffic_model`` methods (the driver
protocol every sync rule implements — bsp/zero/easgd/gosgd/nd today).
Library modules (mesh, fused, pipeline, codec itself) are out of scope
by construction — EXCEPT for bucketed-exchange code: any ``def`` or
``class`` in ``parallel/*.py`` whose name mentions a bucket AND whose
body posts a collective (psum/pmean/ppermute/all_gather/psum_scatter/
all_to_all) is a wire schedule of its own and must route through the
codec layer too (the bucketed overlap allreduce composes with
``--wire-codec`` today; a future bucketed path that skips the codec
would silently shrink the fleet exactly like a codec-less engine).

Usage::

    python -m theanompi_tpu.tools.check_codec_coverage           # repo
    python -m theanompi_tpu.tools.check_codec_coverage DIR       # that dir

Exit code 1 on any uncovered engine (CI gate via tools/lint_all.py;
tests/test_check_codec_coverage.py).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Optional

PARALLEL_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "parallel"
)

# either import spelling counts as routing through the codec layer
_CODEC_REF = re.compile(
    r"from\s+theanompi_tpu\.parallel\.codec\s+import"
    r"|from\s+theanompi_tpu\.parallel\s+import\s+[^\n]*\bcodec\b"
    r"|theanompi_tpu\.parallel\.codec"
)
_EXEMPT = re.compile(r"codec_exempt:[ \t]*(\S[^\n]*)")  # reason required,
# on the SAME line — a bare marker doesn't count as an exemption


def _engine_classes(source: str) -> list:
    """Names of classes defining BOTH train_step and traffic_model —
    the driver-protocol engines this lint covers."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        methods = {
            n.name for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if {"train_step", "traffic_model"} <= methods:
            out.append(node.name)
    return out


# collective-posting calls that make a bucketed def a wire schedule
_COLLECTIVES = {"psum", "pmean", "ppermute", "all_gather", "psum_scatter",
                "all_to_all", "psum_invariant"}


def _posts_collective(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if name in _COLLECTIVES:
            return True
    return False


def _bucketed_exchange_defs(source: str) -> list:
    """Names of ``def``/``class`` nodes that (a) name a bucket and (b)
    post a collective — the bucketed-exchange code paths this lint
    holds to the same codec-or-exempt bar as full engines."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            continue
        if "bucket" not in node.name.lower():
            continue
        if _posts_collective(node):
            out.append(node.name)
    return out


def check_file(path: str) -> Optional[str]:
    """A violation string for ``path``, or None (clean / not an engine
    module / explicitly exempt)."""
    with open(path) as f:
        source = f.read()
    engines = _engine_classes(source)
    buckets = _bucketed_exchange_defs(source)
    if not engines and not buckets:
        return None
    if _CODEC_REF.search(source):
        return None
    m = _EXEMPT.search(source)
    if m:
        return None  # declared exemption, reason on record
    what = []
    if engines:
        what.append(f"engine class(es) {', '.join(sorted(engines))}")
    if buckets:
        what.append(
            f"bucketed-exchange path(s) {', '.join(sorted(buckets))}"
        )
    return (
        f"{path}: {' and '.join(what)} neither "
        "import theanompi_tpu.parallel.codec nor declare a "
        "'codec_exempt: <reason>' marker — every engine's exchange (and "
        "every bucketed wire schedule) must route through the codec "
        "layer (parallel/codec.py) so --wire-codec keeps covering the "
        "whole fleet"
    )


def check_dir(parallel_dir: str = PARALLEL_DIR) -> list:
    errs = []
    for name in sorted(os.listdir(parallel_dir)):
        if not name.endswith(".py"):
            continue
        err = check_file(os.path.join(parallel_dir, name))
        if err:
            errs.append(err)
    return errs


def main(argv: Optional[list] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    target = argv[0] if argv else PARALLEL_DIR
    errs = (
        [e for e in [check_file(target)] if e] if os.path.isfile(target)
        else check_dir(target)
    )
    for e in errs:
        print(e)
    print(
        f"codec-coverage lint on {os.path.relpath(target)}: "
        + ("OK" if not errs else f"{len(errs)} uncovered engines")
    )
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
