"""theanompi_tpu — a TPU-native rebuild of Theano-MPI.

Theano-MPI (reference: bobquest33/Theano-MPI, arXiv:1605.08325) is a
data-parallel distributed training framework for convolutional networks:
a model zoo (AlexNet, GoogLeNet, VGG16, ResNet-50, Wide-ResNet), pluggable
synchronization rules (BSP / EASGD / GoSGD), pluggable gradient-exchange
strategies, an asynchronous input pipeline, and a recorder/checkpoint layer,
all glued together with CUDA-aware MPI + NCCL.

This package provides the same behavioral contract, redesigned TPU-first:

- one SPMD program under ``jax.jit`` over a named ``jax.sharding.Mesh``
  replaces the reference's process-per-GPU ``mpirun`` model
  (reference: ``lib/base.py`` — ``MPI_GPU_Process``; empty mount, see SURVEY.md);
- gradient allreduce lowers to ``lax.psum`` over ICI instead of
  MPI/NCCL calls between steps (reference: ``lib/exchanger.py`` — ``BSP_Exchanger``);
- EASGD's center<->worker elastic averaging and GoSGD's randomized gossip
  become ``lax.ppermute`` / ``lax.psum`` collectives inside the compiled step
  (reference: ``lib/exchanger.py`` — ``EASGD_Exchanger``, ``GOSGD_Exchanger``);
- the exchanger-strategy concept survives as a swappable gradient-sync
  function (reference: ``lib/exchanger_strategy.py`` — ``Exch_allreduce``,
  ``Exch_asa32``, ``Exch_asa16``, ``Exch_nccl32``);
- Theano shared GPU params + ``lib/opt.py`` updates compile as a single
  pjit'd train step over HBM-resident ``jax.Array``s.

Session API (reference: ``launch_session.py`` / ``tmpi``)::

    from theanompi_tpu import BSP
    rule = BSP()
    rule.init(devices=8, modelfile='wrn', modelclass='WRN')  # short name or module path
    rule.wait()
"""

__version__ = "0.1.0"

import os as _os

import jax as _jax

# TPU-native default PRNG: XLA's rng-bit-generator ("rbg") instead of the
# pure-JAX threefry. Threefry lowers to a long scalar-heavy program that
# costs ~1.9 ms of a 14.4 ms AlexNet-128 train step on a v5e (dropout
# masks); rbg generates the same-shaped bits in hardware for ~0.5 ms
# (measured: 8,723 -> 9,685 img/s). Streams stay deterministic per seed;
# they differ from threefry's, and split/fold_in derivations remain
# threefry-based (only bit generation changes). Opt out / override with
# TMPI_PRNG_IMPL=threefry2x32 (empty string = leave JAX's default).
# Precedence: TMPI_PRNG_IMPL > the user's own JAX_DEFAULT_PRNG_IMPL
# (never clobber an explicit JAX-level choice) > our rbg default. A
# programmatic jax.config.update made before this import is
# indistinguishable from the default and WILL be overridden — use either
# env var to pin.
_impl = _os.environ.get("TMPI_PRNG_IMPL")
if _impl is None and "JAX_DEFAULT_PRNG_IMPL" not in _os.environ:
    _impl = "rbg"
if _impl:
    _jax.config.update("jax_default_prng_impl", _impl)

from theanompi_tpu import _jax_compat  # noqa: F401,E402  (jax API bridge)
from theanompi_tpu.launch.session import BSP, EASGD, GOSGD, SyncRule  # noqa: F401,E402
from theanompi_tpu.launch.supervisor import supervise_training  # noqa: F401,E402

__all__ = ["BSP", "EASGD", "GOSGD", "SyncRule", "__version__"]
