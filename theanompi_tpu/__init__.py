"""theanompi_tpu — a TPU-native rebuild of Theano-MPI.

Theano-MPI (reference: bobquest33/Theano-MPI, arXiv:1605.08325) is a
data-parallel distributed training framework for convolutional networks:
a model zoo (AlexNet, GoogLeNet, VGG16, ResNet-50, Wide-ResNet), pluggable
synchronization rules (BSP / EASGD / GoSGD), pluggable gradient-exchange
strategies, an asynchronous input pipeline, and a recorder/checkpoint layer,
all glued together with CUDA-aware MPI + NCCL.

This package provides the same behavioral contract, redesigned TPU-first:

- one SPMD program under ``jax.jit`` over a named ``jax.sharding.Mesh``
  replaces the reference's process-per-GPU ``mpirun`` model
  (reference: ``lib/base.py`` — ``MPI_GPU_Process``; empty mount, see SURVEY.md);
- gradient allreduce lowers to ``lax.psum`` over ICI instead of
  MPI/NCCL calls between steps (reference: ``lib/exchanger.py`` — ``BSP_Exchanger``);
- EASGD's center<->worker elastic averaging and GoSGD's randomized gossip
  become ``lax.ppermute`` / ``lax.psum`` collectives inside the compiled step
  (reference: ``lib/exchanger.py`` — ``EASGD_Exchanger``, ``GOSGD_Exchanger``);
- the exchanger-strategy concept survives as a swappable gradient-sync
  function (reference: ``lib/exchanger_strategy.py`` — ``Exch_allreduce``,
  ``Exch_asa32``, ``Exch_asa16``, ``Exch_nccl32``);
- Theano shared GPU params + ``lib/opt.py`` updates compile as a single
  pjit'd train step over HBM-resident ``jax.Array``s.

Session API (reference: ``launch_session.py`` / ``tmpi``)::

    from theanompi_tpu import BSP
    rule = BSP()
    rule.init(devices=8, modelfile='wrn', modelclass='WRN')  # short name or module path
    rule.wait()
"""

__version__ = "0.1.0"

from theanompi_tpu.launch.session import BSP, EASGD, GOSGD, SyncRule  # noqa: F401

__all__ = ["BSP", "EASGD", "GOSGD", "SyncRule", "__version__"]
