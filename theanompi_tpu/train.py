"""Train/eval step construction — the ``compile_iter_fns`` equivalent.

Reference (SURVEY.md §3.2): each model compiled a Theano ``train_fn``
(fwd+bwd, grads written to velocity shared vars), the exchanger ran MPI
between calls, then ``update_fn`` applied the averaged velocities. Here
the entire iteration — forward, backward, gradient sync collective,
optimizer update, LR schedule — is ONE jitted XLA program; the gradient
sync is a pluggable function applied to raw grads *inside* the step
(reference ordering: comm sees raw gradients, update runs post-exchange).

``make_train_step`` builds the single-device / replicated step; the
parallel layer (``theanompi_tpu.parallel``) wraps it in ``shard_map``
over a mesh and supplies the collective ``grad_sync``.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from theanompi_tpu.models.contract import Model
from theanompi_tpu.ops.optimizers import apply_updates

PyTree = Any
GradSync = Callable[[PyTree], PyTree]  # raw grads -> synced grads


class TrainState(NamedTuple):
    """The complete training state pytree — the analogue of the
    reference's Theano shared variables (params + vels) plus the step
    counter that drives the LR schedule.

    ``ef``: the wire codec's error-feedback residual accumulators
    (parallel/codec.py) — per-device quantization residuals of the
    gradient exchange, stacked ``[n_devices, ...]`` and sharded over
    the exchange axes. ``()`` (the default, zero leaves) whenever the
    codec carries no state, so codec-off runs pay nothing in state
    size, donation, or checkpoints; when present it is checkpointed
    with the rest of the state, making compressed-run resume exact."""

    params: PyTree
    model_state: PyTree  # BatchNorm running stats etc.
    opt_state: PyTree
    step: jax.Array  # int32 global step
    ef: PyTree = ()  # wire-codec error-feedback residuals (or ())


def init_train_state(model: Model, key: jax.Array) -> TrainState:
    params, model_state = model.init(key)
    opt_state = model.optimizer().init(params)
    return TrainState(params, model_state, opt_state, jnp.zeros((), jnp.int32))


def make_schedule_fn(model: Model, steps_per_epoch: int = 1):
    """``step -> lr`` honoring the recipe's schedule unit (the
    reference's ``adjust_hyperp(epoch)``, evaluated inside the compiled
    step)."""
    schedule = model.schedule()
    per_epoch = float(max(1, steps_per_epoch))
    by_epoch = model.recipe.lr_unit == "epoch"

    def schedule_lr(step):
        return schedule(step / per_epoch if by_epoch else step)

    return schedule_lr


def loss_and_grads(
    model: Model, params, model_state, images, labels, rng,
    loss_scale: float = 1.0, param_sync: Optional[Callable] = None,
):
    """The shared forward+backward core: ``-> (loss, logits,
    new_model_state, raw_grads)``. Used by make_train_step and the
    ZeRO-1 step (parallel/zero.py) so step semantics cannot drift.

    ``param_sync``: applied to the params INSIDE the differentiated
    function — the hook the bucketed overlap exchanger uses to plant
    per-bucket ``custom_vjp`` tags whose backward posts each bucket's
    collective at the point its grads are produced
    (parallel/strategies.py::BucketedOverlapSync.wrap_params). The
    returned grads are then already synced."""

    def loss_fn(params):
        if param_sync is not None:
            params = param_sync(params)
        logits, new_model_state = model.apply(
            params, model_state, images, train=True, rng=rng
        )
        loss = model.loss(logits, labels) * loss_scale
        return loss, (new_model_state, logits)

    (loss, (new_model_state, logits)), grads = jax.value_and_grad(
        loss_fn, has_aux=True
    )(params)
    if loss_scale != 1.0:
        grads = jax.tree_util.tree_map(lambda g: g / loss_scale, grads)
    return loss / loss_scale, logits, new_model_state, grads


def make_train_step(
    model: Model,
    steps_per_epoch: int = 1,
    grad_sync: Optional[GradSync] = None,
    loss_scale: float = 1.0,
    input_transform: Optional[Callable] = None,
    accum_steps: int = 1,
    numerics: bool = False,
    fused_update: bool = False,
):
    """Build the pure train step: ``(state, images, labels, rng) ->
    (state, metrics)``.

    ``accum_steps > 1``: gradient accumulation — the (per-device) batch
    is split into ``accum_steps`` microbatches folded through a
    ``lax.scan``; gradients average across microbatches BEFORE the
    exchanger sync and the single optimizer update, so the SGD
    trajectory is the large-batch one while activation memory is that
    of ``batch / accum_steps`` (beyond parity: the reference had no
    microbatching — its per-GPU batch WAS the memory limit; here config
    #5-scale global batches fit a handful of chips). BatchNorm batch
    stats update sequentially per microbatch (same running-stat stream
    as equally-sized small steps); metrics are microbatch means.

    ``steps_per_epoch`` converts the step counter to the schedule's unit
    when the recipe schedules by epoch (reference: ``adjust_hyperp(epoch)``
    ran between epochs; here the piecewise schedule is evaluated inside
    the compiled step so nothing happens on the host).

    ``grad_sync`` is the exchanger hook — under ``shard_map`` it holds the
    collective (psum mean / ring / compressed ring); None means single
    replica.

    ``fused_update``: replace the recipe's optimizer with its fused
    one-pass equivalent (ops/pallas_update.py — weight decay + clip +
    momentum + param write in one Pallas kernel per leaf, one HBM
    round-trip instead of ~4). SGD-family rules only; others refuse
    loudly. State layout matches the unfused rule, so checkpoints
    resume across the boundary.

    ``grad_sync`` objects exposing ``in_backward=True`` (the bucketed
    overlap exchanger, parallel/strategies.py) are applied to the
    PARAMS inside the differentiated loss instead of to the grads after
    it — their per-bucket collectives then overlap the tail of
    backward. Incompatible with ``accum_steps > 1`` (the sync must run
    once on the accumulated grads, not per microbatch).

    ``numerics``: compile the numerics sentinels into the step
    (obs/numerics.py) — global grad-norm (post-sync: the gradient the
    update actually sees), update-norm, new-param-norm, and a fused
    non-finite count over the grads, returned in the metrics dict under
    ``nm_``-prefixed keys. The loss/grad/update math is untouched; the
    sentinels are extra outputs of the same XLA program, so they drain
    through the dispatch pipeline with zero new host syncs.

    ``input_transform`` runs ON DEVICE at the top of the compiled step
    (e.g. uint8 -> ``(x - mean) * scale``): the host then ships compact
    uint8 batches and normalization fuses into the first conv — 4x less
    H2D traffic than shipping float32 (the reference normalized on the
    host loader, ``lib/proc_load_mpi.py``; on TPU the wire is the
    scarcer resource).

    NOTE: the local-grad → allreduce decomposition relies on classic
    pmap-style AD semantics (``shard_map(..., check_vma=False)``), under
    which the transpose of a forward psum is itself a psum (measured on
    jax 0.9 — cotangents flow across the collective), so each device's
    backward yields exactly ``d(sum over devices of local_loss)/d
    theta_local``. Summing those per-device grads over the mesh and
    dividing by n — the exchanger's psum-mean — is therefore the true
    gradient of the mean loss, and this stays EXACT even when the
    forward pass contains collectives (cross-replica BatchNorm), whose
    cross-device paths the transposed psums account for. Under
    ``check_vma=True`` the cotangent of replicated params arrives
    already globally summed ("unreduced"), so an explicit exchanger
    would double-count — verified empirically on jax 0.9; see
    tests/test_bsp.py. All shard_maps in this framework therefore use
    ``check_vma=False``. (models/transformer.py::make_nd_train_step
    generalizes this rule to multi-axis tp/sp meshes.)
    """
    if fused_update:
        from theanompi_tpu.ops.pallas_update import fuse_optimizer

        optimizer = fuse_optimizer(model.recipe.optimizer,
                                   **model.recipe.opt_kwargs)
    else:
        optimizer = model.optimizer()
    schedule_lr = make_schedule_fn(model, steps_per_epoch)
    accum_steps = max(1, int(accum_steps))
    in_backward = bool(getattr(grad_sync, "in_backward", False))
    if in_backward and accum_steps > 1:
        # in-backward buckets only: the :ef bucketed variant is
        # stateful/post-backward (in_backward=False) and composes with
        # accumulation — one bucketed sync on the accumulated grads
        raise ValueError(
            "--allreduce-buckets syncs inside backward, but "
            f"accum_steps={accum_steps} needs ONE sync on the "
            "accumulated grads — per-microbatch bucket collectives "
            "would multiply the wire volume; drop one of the two"
        )
    param_sync = grad_sync.wrap_params if in_backward else None

    def fwd_bwd(params, model_state, images, labels, rng):
        loss, logits, new_model_state, grads = loss_and_grads(
            model, params, model_state, images, labels, rng,
            loss_scale=loss_scale, param_sync=param_sync,
        )
        metrics = {"loss": loss, **model.metrics(logits, labels)}
        return new_model_state, grads, metrics

    def train_step(state: TrainState, images, labels, rng):
        if input_transform is not None:
            images = input_transform(images)

        if accum_steps == 1:
            new_model_state, grads, metrics = fwd_bwd(
                state.params, state.model_state, images, labels, rng
            )
        else:
            B = images.shape[0]
            if B % accum_steps:
                raise ValueError(
                    f"per-device batch {B} must be divisible by "
                    f"accum_steps={accum_steps}"
                )
            xm = images.reshape(accum_steps, B // accum_steps, *images.shape[1:])
            ym = labels.reshape(accum_steps, B // accum_steps, *labels.shape[1:])

            def micro(carry, inp):
                model_state, gsum = carry
                x, y, idx = inp
                model_state, grads, metrics = fwd_bwd(
                    state.params, model_state, x, y, jax.random.fold_in(rng, idx)
                )
                # Accumulate in fp32 regardless of param dtype: repeated
                # bf16 additions across microbatches would drift from the
                # large-batch trajectory this mode promises.
                gsum = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), gsum, grads
                )
                return (model_state, gsum), metrics

            gzero = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params
            )
            (new_model_state, gsum), ms = jax.lax.scan(
                micro, (state.model_state, gzero),
                (xm, ym, jnp.arange(accum_steps)),
            )
            grads = jax.tree_util.tree_map(
                lambda g, p: (g / accum_steps).astype(p.dtype),
                gsum, state.params,
            )
            metrics = jax.tree_util.tree_map(lambda m: jnp.mean(m, axis=0), ms)

        new_ef = state.ef
        if grad_sync is not None and not in_backward:
            if getattr(grad_sync, "stateful", False):
                # compressed exchange with error feedback: the strategy
                # threads the codec residuals through engine state
                # (parallel/strategies.py::codec_psum_mean, and the
                # bucketed :ef path)
                grads, new_ef = grad_sync(grads, state.ef)
            else:
                grads = grad_sync(grads)
        # (in_backward syncs already ran inside the bucket tags' vjps —
        # `grads` here is post-collective either way, so the numerics
        # sentinels below keep their post-sync meaning)

        lr = schedule_lr(state.step)
        if optimizer.apply is not None:
            # fused one-pass epilogue (ops/pallas_update.py): params and
            # velocity are rewritten in place, no update tree exists
            new_params, new_opt_state = optimizer.apply(
                grads, state.opt_state, state.params, lr
            )
            updates = None
        else:
            updates, new_opt_state = optimizer.update(
                grads, state.opt_state, state.params, lr
            )
            new_params = apply_updates(state.params, updates)

        metrics = {**metrics, "lr": lr}
        if numerics:
            from theanompi_tpu.obs.numerics import sentinel_metrics

            if updates is None:
                # fused path: reconstruct the update tree for the gauges
                # only — the numerics variant is a SEPARATE compiled
                # program, so sentinel-off hot steps pay nothing
                from theanompi_tpu.ops.optimizers import update_delta

                updates = update_delta(new_params, state.params)
            metrics = {**metrics,
                       **sentinel_metrics(grads, updates, new_params)}
        new_state = TrainState(new_params, new_model_state, new_opt_state,
                               state.step + 1, new_ef)
        return new_state, metrics

    return train_step


def make_multi_step(step_fn, k: int, stacked: bool = False):
    """Fuse ``k`` successive train steps into one compiled program via
    ``lax.scan`` — one host dispatch per k steps.

    ``step_fn`` is any pure step ``(state, x, y, rng) -> (state, metrics)``
    (e.g. from :func:`make_train_step`). With ``stacked=False`` the one
    given batch is reused every sub-step (benchmarking); with
    ``stacked=True`` images/labels carry a leading dim of size ``k`` (a
    compiled epoch slice). The mode is explicit — inferring it from
    shapes would misfire whenever batch_size == k. Per-sub-step rngs are
    derived by folding the step index into ``rng``. Returns
    ``(state, metrics)`` with metrics stacked over ``k``.

    Host dispatch costs ~10ms on tunneled backends (measured on the axon
    v5e), which swamps a ~15ms AlexNet step — scanning restores real
    device throughput. On directly-attached hardware it simply removes
    Python from the loop.
    """

    def run(state, images, labels, rng):
        if stacked and images.shape[0] != k:
            raise ValueError(
                f"stacked=True expects leading dim {k}, got {images.shape[0]}"
            )

        def body(st, idx):
            x = images[idx] if stacked else images
            y = labels[idx] if stacked else labels
            st, m = step_fn(st, x, y, jax.random.fold_in(rng, idx))
            return st, m

        return jax.lax.scan(body, state, jnp.arange(k))

    return run


def make_eval_step(
    model: Model,
    input_transform: Optional[Callable] = None,
    views: int = 1,
):
    """``(state, images, labels) -> metrics`` with loss, on eval stats.

    ``views > 1``: multi-view evaluation (the AlexNet-era 10-crop val
    protocol — 4 corners + center, each mirrored). ``images`` carries
    ``len(labels) * views`` rows, view-major per image; per-image logits
    are the mean over views before loss/metrics (reference: the
    published top-1 protocol the recipes were validated with).

    The forward itself is :func:`theanompi_tpu.models.zoo.infer_fn` —
    the same eval-mode closure the serving engine compiles, so train-
    time validation and serving can never diverge on inference
    semantics (train=False, no rng, fixed BN stats)."""
    from theanompi_tpu.models.zoo import infer_fn

    fwd = infer_fn(model)

    def eval_step(state: TrainState, images, labels):
        if input_transform is not None:
            images = input_transform(images)
        logits = fwd(state.params, state.model_state, images)
        if views > 1:
            logits = logits.reshape(-1, views, logits.shape[-1]).mean(axis=1)
        return {"loss": model.loss(logits, labels), **model.metrics(logits, labels)}

    return eval_step
