"""Weight initializers.

The reference's zoo used fixed-std gaussian inits per layer (AlexNet-era
recipes: std 0.01/0.005 with constant biases; reference:
``models/layers2.py`` weight-init helpers) plus glorot-style for later
models. Top-1 parity depends on reproducing these exactly, so they are
explicit named functions of a PRNG key — fully seeded and testable.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def constant(value: float):
    def f(key, shape, dtype=jnp.float32):
        del key
        return jnp.full(shape, value, dtype)

    return f


zeros = constant(0.0)
ones = constant(1.0)


def gaussian(std: float = 0.01, mean: float = 0.0):
    """Fixed-std normal — AlexNet/GoogLeNet recipe init."""

    def f(key, shape, dtype=jnp.float32):
        return mean + std * jax.random.normal(key, shape, dtype)

    return f


def _fans(shape):
    """(fan_in, fan_out) for dense ``(in, out)`` or conv ``HWIO`` kernels."""
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[:-2]))
    return shape[-2] * receptive, shape[-1] * receptive


def glorot_uniform(scale: float = 1.0):
    """Glorot/Xavier uniform: U(±sqrt(6/(fan_in+fan_out)))."""

    def f(key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fans(shape)
        limit = scale * np.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -limit, limit)

    return f


def he_normal(scale: float = 1.0):
    """He/Kaiming normal: N(0, sqrt(2/fan_in)) — the WRN/ResNet recipe init."""

    def f(key, shape, dtype=jnp.float32):
        fan_in, _ = _fans(shape)
        return scale * np.sqrt(2.0 / fan_in) * jax.random.normal(key, shape, dtype)

    return f


_REGISTRY = {
    "zeros": lambda: zeros,
    "ones": lambda: ones,
    "constant": constant,
    "gaussian": gaussian,
    "glorot_uniform": glorot_uniform,
    "he_normal": he_normal,
}


def get(name: str, **kwargs):
    try:
        return _REGISTRY[name](**kwargs)
    except KeyError:
        raise ValueError(f"unknown initializer {name!r}; available: {sorted(_REGISTRY)}") from None
