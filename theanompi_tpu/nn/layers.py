"""Functional layers: Conv / Pool / LRN / Dense / Dropout / BatchNorm / Sequential.

TPU-native equivalent of the reference's Theano layer classes
(reference: ``models/layers2.py`` — ``Conv`` (cuDNN ``dnn_conv``),
``Pool``, ``LRN``, ``FC``, ``Dropout``, ``Softmax``; anchors per
SURVEY.md §2.1, reference mount empty at build time).

Design:

- **NHWC** activations and **HWIO** kernels throughout — the layouts
  XLA:TPU tiles best onto the MXU (vs the reference's NCHW/cuDNN).
- Every layer is a lightweight config object with three pure methods::

      params, state = layer.init(key, in_shape)      # in_shape includes batch
      y, new_state  = layer.apply(params, state, x, train=..., rng=...)
      out_shape     = layer.out_shape(in_shape)

  ``params`` are trainable pytrees; ``state`` holds non-trainable
  buffers (BatchNorm running stats). Both are plain dicts, so the whole
  model is one transparent pytree — the analogue of the reference's
  list of Theano shared variables, but functional and shardable.
- No data-dependent Python control flow: everything traces once under
  ``jax.jit`` and compiles to a single XLA program.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from theanompi_tpu.nn import init as initializers

Shape = tuple  # includes leading batch dim


def _pair(v) -> tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def _spatial_out(h, w, kernel, stride, padding):
    """Output (h, w) for a windowed op with SAME/VALID/explicit padding."""
    kh, kw = kernel
    sh, sw = stride
    if padding == "SAME":
        return -(-h // sh), -(-w // sw)
    if padding == "VALID":
        return (h - kh) // sh + 1, (w - kw) // sw + 1
    ph, pw = _pair(padding)
    return (h + 2 * ph - kh) // sh + 1, (w + 2 * pw - kw) // sw + 1


class Layer:
    """Base class: stateless identity. Subclasses override as needed."""

    name: str = "layer"

    def init(self, key, in_shape: Shape):
        del key, in_shape
        return {}, {}

    def apply(self, params, state, x, *, train: bool = False, rng=None):
        del params, train, rng
        return x, state

    def out_shape(self, in_shape: Shape) -> Shape:
        return in_shape


class Conv(Layer):
    """2-D convolution (NHWC x HWIO -> NHWC), with AlexNet-style channel
    groups via ``feature_group_count`` (reference: ``models/layers2.py`` —
    ``Conv`` wrapping cuDNN ``dnn_conv`` with ``num_groups``).

    ``padding``: int / (int, int) explicit symmetric pad, or 'SAME'/'VALID'.
    """

    def __init__(
        self,
        out_channels: int,
        kernel: Union[int, tuple],
        stride: Union[int, tuple] = 1,
        padding: Union[int, tuple, str] = "SAME",
        groups: int = 1,
        use_bias: bool = True,
        w_init=None,
        b_init=None,
        name: str = "conv",
    ):
        self.out_channels = out_channels
        self.kernel = _pair(kernel)
        self.stride = _pair(stride)
        self.padding = padding
        self.groups = groups
        self.use_bias = use_bias
        self.w_init = w_init or initializers.he_normal()
        self.b_init = b_init or initializers.zeros
        self.name = name

    def _pad_arg(self):
        if isinstance(self.padding, str):
            return self.padding
        ph, pw = _pair(self.padding)
        return ((ph, ph), (pw, pw))

    def init(self, key, in_shape: Shape):
        cin = in_shape[-1]
        assert cin % self.groups == 0 and self.out_channels % self.groups == 0
        kh, kw = self.kernel
        wkey, bkey = jax.random.split(key)
        params = {"w": self.w_init(wkey, (kh, kw, cin // self.groups, self.out_channels))}
        if self.use_bias:
            params["b"] = self.b_init(bkey, (self.out_channels,))
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None):
        y = lax.conv_general_dilated(
            x,
            params["w"].astype(x.dtype),
            window_strides=self.stride,
            padding=self._pad_arg(),
            feature_group_count=self.groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.use_bias:
            y = y + params["b"].astype(y.dtype)
        return y, state

    def out_shape(self, in_shape: Shape) -> Shape:
        n, h, w, _ = in_shape
        oh, ow = _spatial_out(h, w, self.kernel, self.stride, self.padding)
        return (n, oh, ow, self.out_channels)


class Pool(Layer):
    """Max / average pooling (reference: ``models/layers2.py`` — ``Pool``).

    ``mode``: 'max' or 'avg'. AlexNet-style overlapping pool = 3x3 stride 2
    VALID.
    """

    def __init__(
        self,
        window: Union[int, tuple] = 2,
        stride: Optional[Union[int, tuple]] = None,
        padding: Union[int, tuple, str] = "VALID",
        mode: str = "max",
        name: str = "pool",
    ):
        self.window = _pair(window)
        self.stride = _pair(stride) if stride is not None else self.window
        self.padding = padding
        assert mode in ("max", "avg")
        self.mode = mode
        self.name = name

    def _pad_arg(self):
        if isinstance(self.padding, str):
            return self.padding
        ph, pw = _pair(self.padding)
        return ((0, 0), (ph, ph), (pw, pw), (0, 0))

    def apply(self, params, state, x, *, train=False, rng=None):
        kh, kw = self.window
        sh, sw = self.stride
        dims, strides = (1, kh, kw, 1), (1, sh, sw, 1)
        if self.mode == "max":
            # NOTE: AD of reduce_window-max lowers to select-and-scatter,
            # and that IS the measured optimum on v5e for NHWC. The
            # Theano-style eq-mask backward was tried three ways and all
            # lost: plain jnp in two formulations (~2x slower end-to-end;
            # round-4 re-measurement 135 ms vs ~3 ms for one batch-1024
            # 28x28x480 stride-1 pool — XLA won't fuse the 9-way
            # accumulation), and a register-resident Pallas kernel
            # (ops/pallas_pool.py: GoogLeNet 5094 -> 2472 img/s — NHWC
            # puts W on the sublane dim so shifted reads are misaligned
            # shuffles, and the custom call is a fusion barrier; full
            # analysis in that module's docstring). The Pallas kernel
            # stays as an opt-in (TMPI_PALLAS_POOL=1) with Theano's
            # all-maxima tie semantics.
            from theanompi_tpu.ops import pallas_pool

            if pallas_pool.routable(self.window, self.stride, self.padding, x):
                return pallas_pool.maxpool3x3_s1(x), state
            y = lax.reduce_window(
                x, -jnp.inf, lax.max, dims, strides, self._pad_arg()
            )
        else:
            summed = lax.reduce_window(
                x, 0.0, lax.add, dims, strides, self._pad_arg()
            )
            if isinstance(self.padding, str) and self.padding == "SAME":
                # normalize by actual window coverage at the borders
                ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
                counts = lax.reduce_window(
                    ones, 0.0, lax.add, dims, strides, self._pad_arg()
                )
                y = summed / counts
            else:
                y = summed / (kh * kw)
        return y, state

    def out_shape(self, in_shape: Shape) -> Shape:
        n, h, w, c = in_shape
        oh, ow = _spatial_out(h, w, self.window, self.stride, self.padding)
        return (n, oh, ow, c)


class LRN(Layer):
    """Cross-channel local response normalization — the AlexNet/GoogLeNet
    normalizer (reference: ``models/layers2.py`` — ``LRN``, pylearn2-style
    ``CrossChannelNormalization(alpha=1e-4, k=2, beta=0.75, n=5)``).

    ``y = x / (k + (alpha/n) * sum_{window n} x^2)^beta`` — the
    pylearn2/Theano convention divides ``alpha`` by the window size, which
    the reference inherited; reproduce it exactly for top-1 parity.
    """

    def __init__(self, n: int = 5, alpha: float = 1e-4, beta: float = 0.75, k: float = 2.0, name: str = "lrn"):
        # the banded window sum below is the symmetric |i-j| <= n//2
        # band, which spans n channels only for odd n (even n would
        # silently widen to n+1 vs the reference's asymmetric window)
        assert n % 2 == 1, f"LRN window n must be odd, got {n}"
        self.n = n
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.name = name

    def apply(self, params, state, x, *, train=False, rng=None):
        sq = jnp.square(x)
        # Cross-channel window sum as a banded [C, C] matmul: the MXU
        # eats it (C = 96/256), XLA fuses the square into the GEMM input
        # and the rsqrt math into its output, and AD's transpose is just
        # the same band again — where the reduce_window lowering costs
        # several full HBM passes over AlexNet's 55x55 maps (measured on
        # v5e at batch 1024: 13,969 -> 18,169 img/s for the whole train
        # step). A fully fused Pallas kernel was also tried and REJECTED:
        # XLA picks batch-minor layouts for these conv activations, and
        # a lane=C kernel's layout constraint forces ~600 MB relayout
        # copies around every call that cost more than the fusion saves.
        c = x.shape[-1]
        i = jnp.arange(c)
        band = (jnp.abs(i[:, None] - i[None, :]) <= self.n // 2).astype(x.dtype)
        # output dtype follows x (bf16 on TPU): the MXU accumulates in
        # f32 internally either way, and asking for an f32 result here
        # materializes a full-precision copy of the biggest activation
        # maps in the backward residuals (~1.2 GB at AlexNet batch 1024)
        window_sum = jnp.einsum("...c,cd->...d", sq, band)
        d = self.k + (self.alpha / self.n) * window_sum
        if self.beta == 0.75:
            # d^-0.75 = rsqrt(d) * rsqrt(sqrt(d)): sqrt/rsqrt are single
            # VPU ops where pow lowers to exp(log) — measurably cheaper
            # on the big early conv maps (agrees with pow to ~1e-6 rel)
            return (x * lax.rsqrt(d) * lax.rsqrt(lax.sqrt(d))).astype(x.dtype), state
        return (x / jnp.power(d, self.beta)).astype(x.dtype), state


class Dense(Layer):
    """Fully connected layer (reference: ``models/layers2.py`` — ``FC``)."""

    def __init__(self, out_features: int, use_bias: bool = True, w_init=None, b_init=None, name: str = "fc"):
        self.out_features = out_features
        self.use_bias = use_bias
        self.w_init = w_init or initializers.glorot_uniform()
        self.b_init = b_init or initializers.zeros
        self.name = name

    def init(self, key, in_shape: Shape):
        wkey, bkey = jax.random.split(key)
        params = {"w": self.w_init(wkey, (in_shape[-1], self.out_features))}
        if self.use_bias:
            params["b"] = self.b_init(bkey, (self.out_features,))
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None):
        y = x @ params["w"].astype(x.dtype)
        if self.use_bias:
            y = y + params["b"].astype(y.dtype)
        return y, state

    def out_shape(self, in_shape: Shape) -> Shape:
        return (*in_shape[:-1], self.out_features)


class Dropout(Layer):
    """Inverted dropout (reference: ``models/layers2.py`` — ``Dropout``;
    the reference scaled at test time, we use the equivalent inverted
    form so eval is a pure pass-through)."""

    def __init__(self, rate: float = 0.5, name: str = "dropout"):
        self.rate = rate
        self.name = name

    def apply(self, params, state, x, *, train=False, rng=None):
        if not train or self.rate == 0.0:
            return x, state
        assert rng is not None, "Dropout.apply(train=True) needs an rng"
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype), state


class BatchNorm(Layer):
    """Batch normalization with running-stat state (WRN/ResNet recipes).

    ``axis_name``: if set and the layer runs inside a mapped axis
    (``shard_map``/``pmap``), batch stats are averaged across replicas
    with ``lax.pmean`` — cross-replica BN for small per-device batches.

    Performance note (round-4 probe, experiments/resnet_bn_probe.py, TPU
    v5e, ResNet-50 batch 256, 8-step fused runs): the BN statistic
    sweeps are ~51% of the train step (op_profile: 104
    ``convert_reduce_fusion``s ≈ one fused two-moment pass per BN per
    direction), and they are already near bandwidth-optimal — ~7 GB of
    activation re-reads/step at an effective ~700 GB/s. Measured and
    REJECTED alternatives:

    - ``dtype=f32`` reduction args instead of an explicit upcast:
      2370.7 vs 2370.4 img/s — XLA already fuses the convert (no-op).
    - variadic ``lax.reduce`` computing (Σx, Σx²) in one declared pass:
      334.9 img/s, 7.1x SLOWER — XLA:TPU lowers generic variadic
      reduce as scalar code; the moments were already sibling-fused.
    - batch 512: 2343 img/s (-1%) — the sweeps scale with the batch.

    ADOPTED: normalize sweep computed in bf16 when x is bf16 (scale/
    offset still derived in fp32): 2403 vs 2370 img/s (+1.4%), MFU
    0.2905. The residual gap to MXU-bound MFU is the cost of two-pass
    BN itself — removing it needs stats fused into the producer conv's
    epilogue, which XLA does not expose; a Pallas conv is not worth
    losing the MXU conv emitters for (the LRN matmul precedent,
    measured at theanompi_tpu/nn/layers.py LRN, does not transfer:
    LRN replaced a bandwidth-bound op with a matmul, BN's reduce IS
    already minimal traffic).
    """

    def __init__(
        self,
        momentum: float = 0.9,
        eps: float = 1e-5,
        axis_name: Optional[str] = None,
        name: str = "bn",
    ):
        self.momentum = momentum
        self.eps = eps
        self.axis_name = axis_name
        self.name = name

    def init(self, key, in_shape: Shape):
        c = in_shape[-1]
        params = {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)}
        state = {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        reduce_axes = tuple(range(x.ndim - 1))
        if train:
            xf = x.astype(jnp.float32)
            # two-moment form so cross-replica stats reduce with a single pmean
            mean = jnp.mean(xf, axis=reduce_axes)
            mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean_sq = lax.pmean(mean_sq, self.axis_name)
            # clamp: fp32 cancellation can drive E[x^2]-E[x]^2 slightly negative
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
            m = self.momentum
            new_state = {
                "mean": m * state["mean"] + (1 - m) * mean,
                "var": m * state["var"] + (1 - m) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps) * params["scale"]
        if x.dtype == jnp.bfloat16:
            # bf16 normalize sweep (+1.4% measured, docstring table):
            # per-channel constants derived in fp32, the big elementwise
            # pass reads/writes bf16 only
            y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype) + params[
                "bias"
            ].astype(x.dtype)
            return y, new_state
        y = (x.astype(jnp.float32) - mean) * inv + params["bias"]
        return y.astype(x.dtype), new_state


class Activation(Layer):
    _FNS: dict[str, Callable] = {
        "relu": jax.nn.relu,
        "gelu": jax.nn.gelu,
        "tanh": jnp.tanh,
        "sigmoid": jax.nn.sigmoid,
        "identity": lambda x: x,
    }

    def __init__(self, fn: Union[str, Callable] = "relu", name: Optional[str] = None):
        self.fn = self._FNS[fn] if isinstance(fn, str) else fn
        self.name = name or (fn if isinstance(fn, str) else "act")

    def apply(self, params, state, x, *, train=False, rng=None):
        return self.fn(x), state


class Flatten(Layer):
    name = "flatten"

    def apply(self, params, state, x, *, train=False, rng=None):
        return x.reshape(x.shape[0], -1), state

    def out_shape(self, in_shape: Shape) -> Shape:
        return (in_shape[0], int(math.prod(in_shape[1:])))


class GlobalAvgPool(Layer):
    name = "gap"

    def apply(self, params, state, x, *, train=False, rng=None):
        return jnp.mean(x, axis=(1, 2)), state

    def out_shape(self, in_shape: Shape) -> Shape:
        return (in_shape[0], in_shape[-1])


class Sequential(Layer):
    """Composition of layers with per-layer namespaced params/state.

    The analogue of the reference models' layer lists, but the whole
    network is a single pytree of params + a pytree of state.
    """

    def __init__(self, layers: Sequence[Layer], name: str = "seq"):
        self.layers = list(layers)
        self.name = name
        self._keys = [f"{i:02d}_{l.name}" for i, l in enumerate(self.layers)]

    def init(self, key, in_shape: Shape):
        params, state = {}, {}
        keys = jax.random.split(key, max(1, len(self.layers)))
        shape = in_shape
        for k, lname, layer in zip(keys, self._keys, self.layers):
            if any(d <= 0 for d in shape):
                # fail with the layer name, not a ZeroDivisionError deep
                # in an initializer (e.g. GoogLeNet on an input smaller
                # than its pooling stack supports)
                raise ValueError(
                    f"{self.name}: input to layer {lname!r} has non-positive "
                    f"dims {tuple(shape)} — input_shape too small for this "
                    "architecture"
                )
            p, s = layer.init(k, shape)
            if p:
                params[lname] = p
            if s:
                state[lname] = s
            shape = layer.out_shape(shape)
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = dict(state)
        rngs = (
            jax.random.split(rng, max(1, len(self.layers))) if rng is not None else [None] * len(self.layers)
        )
        for r, lname, layer in zip(rngs, self._keys, self.layers):
            p = params.get(lname, {})
            s = state.get(lname, {})
            x, s2 = layer.apply(p, s, x, train=train, rng=r)
            if s2:
                new_state[lname] = s2
        return x, new_state

    def out_shape(self, in_shape: Shape) -> Shape:
        shape = in_shape
        for layer in self.layers:
            shape = layer.out_shape(shape)
        return shape
