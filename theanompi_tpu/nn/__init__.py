"""Functional NN layer library.

TPU-native replacement for the reference's hand-rolled Theano layer
classes (reference: ``models/layers2.py`` — ``Conv``, ``Pool``, ``FC``,
``Dropout``, ``Softmax``, ``LRN``; reference mount empty at build time,
anchors per SURVEY.md §2.1). Idiomatic JAX modules: every layer is a
lightweight object with pure ``init``/``apply`` functions over explicit
parameter and state pytrees — no framework magic, everything jit-safe.
"""

from theanompi_tpu.nn import init  # noqa: F401
from theanompi_tpu.nn.layers import (  # noqa: F401
    Activation,
    BatchNorm,
    Conv,
    Dropout,
    Flatten,
    GlobalAvgPool,
    Layer,
    Dense,
    LRN,
    Pool,
    Sequential,
)
