"""Fault-tolerant run supervisor: bounded retry + verified auto-resume.

The reference framework's failure story was "the mpirun dies" — a
crashed worker, a corrupt checkpoint, or a transient infrastructure
fault all required a human to notice, diagnose, and relaunch
(SURVEY.md §5.4). :func:`supervise_training` wraps
:func:`~theanompi_tpu.launch.worker.run_training` with the recovery
contract a production run needs:

- **Bounded retry with exponential backoff**: an attempt that dies with
  an ordinary exception is retried up to ``max_retries`` times, sleeping
  ``backoff_base * 2**(failures-1)`` (capped at ``backoff_max``) between
  attempts — a crash-looping run must not hammer shared storage or the
  scheduler.
- **Verified auto-resume**: every retry resumes from the newest
  checkpoint that passes the integrity chain
  (``latest_checkpoint(verify=True)``: per-array CRC32 manifests,
  utils/checkpoint.py) — a truncated or bit-corrupted newest file is
  walked back past, never resumed into.
- **Preemption awareness**: a run that exits via the SIGTERM grace path
  (:class:`~theanompi_tpu.utils.faults.Preempted`) already checkpointed
  and dropped a ``resumable.json`` marker; the supervisor records the
  attempt and RE-RAISES — the SIGKILL is coming, auto-resuming in-place
  would race it. The NEXT invocation sees the marker and auto-resumes
  without being told ``resume=True``.
- **Deliberate stops are not retried**: ``--on-anomaly halt`` (and a
  rollback whose budget is exhausted) raises
  :class:`~theanompi_tpu.obs.numerics.NumericsAnomaly` — retrying would
  override an explicit stop-the-run policy, so it propagates.
  ``KeyboardInterrupt``/``SystemExit`` likewise.

- **Elastic world size** (``elastic=True`` / ``tmpi --elastic``): the
  reference's process grid was fixed at launch — losing or gaining a
  device killed the run even with a good checkpoint on disk. In elastic
  mode every attempt RE-PROBES the live device world (deterministically:
  the enumeration is sorted before anything is derived from it — the
  cross-rank reshard plan must be identical on every controller) and
  passes the probed size to ``run_training(elastic=True)``, whose
  resume path reshards the newest verified checkpoint onto the new mesh
  (``utils/checkpoint.load_resharded``: topology-stamped manifests +
  bounds-based transfer plan, arXiv:2112.01075 style). A topology
  change is thereby one retry, not a dead run. Fault injection covers
  it end-to-end: ``--inject-fault shrink@K:W`` / ``grow@K:W`` kill the
  attempt with :class:`~theanompi_tpu.utils.faults.TopologyChanged`
  and pin the probed world to W for the rest of the supervised run.

Telemetry rides the existing obs stack: one ``kind=retry`` JSONL record
per failed/preempted attempt in ``<obs_dir>/supervisor.jsonl`` (schema:
tools/check_obs_schema.py) — carrying the attempt's ``world`` size so
the log shows topology across retries — plus one ``kind=topology``
record per elastic attempt, and a final ``kind=metrics`` snapshot line
carrying ``tmpi_retries_total`` / ``tmpi_preempt_resumes_total``
appended to ``<obs_dir>/metrics.jsonl``. The reshard itself (when one
happens) is recorded by the worker: a ``kind=reshard`` record and the
``tmpi_reshard_seconds`` gauge in the obs metrics stream.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

from theanompi_tpu.obs.numerics import NumericsAnomaly
from theanompi_tpu.utils.checkpoint import (
    checkpoint_step,
    clear_resumable_marker,
    latest_checkpoint,
    read_resumable_marker,
)
from theanompi_tpu.utils.faults import Preempted


class _SupervisorLog:
    """Per-attempt ``retry`` records + the final metrics snapshot,
    appended under ``obs_dir`` (inert when obs_dir is None)."""

    def __init__(self, obs_dir: Optional[str], rank: int = 0):
        self.obs_dir = obs_dir
        self.rank = int(rank)
        if obs_dir:
            os.makedirs(obs_dir, exist_ok=True)

    def _append(self, filename: str, rec: dict) -> None:
        if not self.obs_dir:
            return
        with open(os.path.join(self.obs_dir, filename), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def retry(self, attempt: int, step: int, error: BaseException,
              backoff_s: float, resumable: bool = False,
              world: Optional[int] = None) -> None:
        rec = {
            "kind": "retry", "rank": self.rank, "t": time.time(),
            "attempt": int(attempt), "step": int(step),
            "error": repr(error), "backoff_s": float(backoff_s),
            "resumable": bool(resumable),
        }
        if world is not None:
            # the attempt's world size: supervisor.jsonl alone shows
            # the topology trajectory across retries
            rec["world"] = int(world)
        self._append("supervisor.jsonl", rec)

    def topology(self, attempt: int, world: int,
                 prev_world: Optional[int] = None) -> None:
        """One record per elastic attempt: the device world it runs in
        (``prev_world`` present from the second attempt on, so a world
        change reads directly off the pair)."""
        rec = {"kind": "topology", "rank": self.rank, "t": time.time(),
               "attempt": int(attempt), "world": int(world)}
        if prev_world is not None:
            rec["prev_world"] = int(prev_world)
        self._append("supervisor.jsonl", rec)

    def snapshot(self, retries: int, preempts: int,
                 step: Optional[int] = None) -> None:
        rec = {"kind": "metrics", "t": time.time(), "source": "supervisor",
               "metrics": {"tmpi_retries_total": float(retries),
                           "tmpi_preempt_resumes_total": float(preempts)}}
        if step is not None:
            rec["step"] = int(step)
        self._append("metrics.jsonl", rec)


def _probe_world(requested: Optional[int], injector) -> int:
    """The device world size the next elastic attempt should run in:
    the LIVE device count (enumerated deterministically — sorted by
    (slice, id), the canonical mesh order — before anything is derived
    from it, so every controller computes the identical value and the
    reshard transfer plan it gates), capped by what the caller asked
    for (``requested`` is the operator's budget; growth never exceeds
    it). A fired shrink/grow fault's ``world_override`` substitutes for
    the live count in tests — the cap still applies to it."""
    import jax

    devs = sorted(jax.devices(),
                  key=lambda d: (getattr(d, "slice_index", 0), d.id))
    n_live = len(devs)
    override = None
    if injector is not None and hasattr(injector, "world_override"):
        override = injector.world_override()
    live = override if override is not None else n_live
    want = min(int(live), int(requested)) if requested else int(live)
    return max(1, min(n_live, want))


def supervise_training(
    *,
    max_retries: int = 2,
    backoff_base: float = 1.0,
    backoff_max: float = 60.0,
    ckpt_dir: Optional[str] = None,
    obs_dir: Optional[str] = None,
    resume: bool = False,
    elastic: bool = False,
    **run_kwargs: Any,
) -> dict:
    """Run :func:`run_training` under the supervisor (module docstring).

    ``ckpt_dir`` is REQUIRED when ``max_retries > 0`` — a retry without
    a checkpoint to resume from silently restarts training from scratch,
    which is never what a recovery path should do quietly. All other
    kwargs forward to ``run_training`` unchanged.

    ``elastic=True``: re-probe the device world before every attempt
    (``requested`` = the caller's ``devices`` count, honored as a cap)
    and let the resume path reshard the checkpoint onto a changed mesh
    instead of dying on it — see the module docstring. ``devices`` must
    be an int or None in elastic mode (an explicit device LIST pins the
    topology, which is the opposite of elastic).

    Returns the successful attempt's summary dict, extended with
    ``retries`` (failed attempts absorbed), ``preempt_resumes``
    (marker-driven resumes) and ``attempts`` (total runs started).
    """
    from theanompi_tpu.launch.worker import run_training

    if max_retries and not ckpt_dir:
        raise ValueError(
            "supervise_training with max_retries > 0 requires ckpt_dir — "
            "a retry can only auto-resume from a checkpoint"
        )
    if run_kwargs.get("inject_faults"):
        # one injector across ALL attempts: fired flags persist, so an
        # injected fault is transient (fires once per supervised run);
        # rebuilding per attempt would refire it on every retry and no
        # bounded retry policy could ever pass the faulted step
        from theanompi_tpu.utils.faults import FaultInjector

        if not isinstance(run_kwargs["inject_faults"], FaultInjector):
            run_kwargs["inject_faults"] = FaultInjector(
                run_kwargs["inject_faults"]
            )
    injector = run_kwargs.get("inject_faults")
    requested_world = run_kwargs.get("devices")
    if elastic:
        if requested_world is not None and not isinstance(requested_world, int):
            raise ValueError(
                "elastic supervision takes devices as a count (or None "
                "= all live devices) — an explicit device list pins the "
                "topology the elastic mode exists to renegotiate"
            )
        # the worker's resume path must reshard (not die) on a mesh
        # mismatch against the checkpoint's topology manifest
        run_kwargs["elastic"] = True
    log = _SupervisorLog(obs_dir)
    retries = 0
    preempts = 0
    attempt = 0
    world: Optional[int] = None
    if ckpt_dir and read_resumable_marker(ckpt_dir) is not None:
        # a previous invocation was preempted mid-run and checkpointed
        # inside its grace window: auto-resume, no flag needed
        preempts += 1
        resume = True
        print(f"[supervisor] resumable marker found in {ckpt_dir!r}; "
              "auto-resuming", flush=True)
    while True:
        attempt += 1
        if elastic:
            # re-probe the live world EVERY attempt (sorted enumeration
            # + injected-fault override; see _probe_world) and record it
            # — the attempt may run in a different topology than the one
            # that just died, and resume reshards onto it
            new_world = _probe_world(requested_world, injector)
            log.topology(attempt, new_world, prev_world=world)
            if world is not None and new_world != world:
                print(f"[supervisor] elastic: world {world} -> "
                      f"{new_world} device(s) for attempt {attempt}",
                      flush=True)
            run_kwargs["devices"] = new_world
            world = new_world
        if ckpt_dir:
            # consumed: if THIS attempt is preempted too it rewrites it
            clear_resumable_marker(ckpt_dir)
        try:
            summary = run_training(ckpt_dir=ckpt_dir, obs_dir=obs_dir,
                                   resume=resume, **run_kwargs)
            break
        except Preempted as e:
            # graceful preemption: checkpointed + marker written by the
            # worker. Do NOT resume in-process — SIGTERM means the kill
            # is imminent; record the attempt and let the exit happen.
            # The next supervise_training() sees the marker and resumes.
            log.retry(attempt, e.step, e, 0.0, resumable=True, world=world)
            log.snapshot(retries, preempts, step=e.step)
            raise
        except NumericsAnomaly:
            # --on-anomaly halt (or an exhausted rollback budget) is a
            # DELIBERATE stop; retrying would override the policy
            raise
        except Exception as e:  # noqa: BLE001 — the retry boundary
            retries += 1
            # verify=True deliberately duplicates the walk resume will
            # redo: the retry record's `step` field is the contract
            # "what the next attempt ACTUALLY resumes from" — after a
            # torn newest checkpoint, the unverified newest would name
            # the very file resume walks past. Retries are rare and
            # backoff-dominated; the extra decompress+CRC walk is the
            # price of an honest record.
            path = latest_checkpoint(ckpt_dir, verify=True) if ckpt_dir else None
            step = checkpoint_step(path)
            if retries > max_retries:
                log.retry(attempt, step, e, 0.0, world=world)
                log.snapshot(retries, preempts)
                raise
            backoff = min(float(backoff_max),
                          float(backoff_base) * (2 ** (retries - 1)))
            log.retry(attempt, step, e, backoff, world=world)
            print(
                f"[supervisor] attempt {attempt} failed ({e!r}); retry "
                f"{retries}/{max_retries} resumes from "
                f"{'step ' + str(step) if step >= 0 else 'scratch (no verified checkpoint)'} "
                f"after {backoff:.2f}s backoff",
                flush=True,
            )
            if backoff > 0:
                time.sleep(backoff)
            resume = True
    if ckpt_dir:
        clear_resumable_marker(ckpt_dir)
    summary["retries"] = retries
    summary["preempt_resumes"] = preempts
    summary["attempts"] = attempt
    log.snapshot(retries, preempts, step=summary.get("steps"))
    return summary
