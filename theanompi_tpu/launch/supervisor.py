"""Fault-tolerant run supervisor: bounded retry + verified auto-resume.

The reference framework's failure story was "the mpirun dies" — a
crashed worker, a corrupt checkpoint, or a transient infrastructure
fault all required a human to notice, diagnose, and relaunch
(SURVEY.md §5.4). :func:`supervise_training` wraps
:func:`~theanompi_tpu.launch.worker.run_training` with the recovery
contract a production run needs:

- **Bounded retry with exponential backoff**: an attempt that dies with
  an ordinary exception is retried up to ``max_retries`` times, sleeping
  ``backoff_base * 2**(failures-1)`` (capped at ``backoff_max``) between
  attempts — a crash-looping run must not hammer shared storage or the
  scheduler. ``retry_jitter=True`` (``--retry-jitter``) replaces the
  deterministic ladder with seeded DECORRELATED jitter
  (``sleep_k = uniform(base, 3 * sleep_{k-1})``, capped): the plain
  ladder is identical across controllers, so a pod-wide fault retries
  as a synchronized stampede against the same storage/scheduler that
  just failed — jitter de-phases the fleet while the seed (the run's
  ``seed``) keeps any ONE supervisor's schedule reproducible. The
  value actually slept is recorded in the retry record's
  ``backoff_s``.
- **Retry cause classification**: every retry record (and the final
  ``tmpi_retries_total`` snapshot) carries a ``cause`` label —
  ``crash`` / ``preempt`` / ``topology`` / ``storage`` / ``anomaly``,
  derived from the exception type (:func:`classify_retry_cause`) — so
  campaign reports and dashboards can attribute instability to the
  layer that caused it instead of lumping everything under "retried".
- **Storage scrub before resume**: a retry's resume discovery is
  preceded by one synchronous scrub pass
  (``utils/checkpoint.scrub_checkpoint_dir``): corrupt keep-chain
  members (bit-rot, torn writes) are quarantined into
  ``<ckpt_dir>/quarantine/`` so the verified walk-back is O(1) and a
  corrupt newest file can never be re-examined by every later
  discovery; a ``kind=scrub`` record lands in metrics.jsonl whenever
  the pass moved anything.
- **Verified auto-resume**: every retry resumes from the newest
  checkpoint that passes the integrity chain
  (``latest_checkpoint(verify=True)``: per-array CRC32 manifests,
  utils/checkpoint.py) — a truncated or bit-corrupted newest file is
  walked back past, never resumed into.
- **Preemption awareness**: a run that exits via the SIGTERM grace path
  (:class:`~theanompi_tpu.utils.faults.Preempted`) already checkpointed
  and dropped a ``resumable.json`` marker; the supervisor records the
  attempt and RE-RAISES — the SIGKILL is coming, auto-resuming in-place
  would race it. The NEXT invocation sees the marker and auto-resumes
  without being told ``resume=True``.
- **Deliberate stops are not retried**: ``--on-anomaly halt`` (and a
  rollback whose budget is exhausted) raises
  :class:`~theanompi_tpu.obs.numerics.NumericsAnomaly` — retrying would
  override an explicit stop-the-run policy, so it propagates.
  ``KeyboardInterrupt``/``SystemExit`` likewise.

- **Elastic world size** (``elastic=True`` / ``tmpi --elastic``): the
  reference's process grid was fixed at launch — losing or gaining a
  device killed the run even with a good checkpoint on disk. In elastic
  mode every attempt RE-PROBES the live device world (deterministically:
  the enumeration is sorted before anything is derived from it — the
  cross-rank reshard plan must be identical on every controller) and
  passes the probed size to ``run_training(elastic=True)``, whose
  resume path reshards the newest verified checkpoint onto the new mesh
  (``utils/checkpoint.load_resharded``: topology-stamped manifests +
  bounds-based transfer plan, arXiv:2112.01075 style). A topology
  change is thereby one retry, not a dead run. Fault injection covers
  it end-to-end: ``--inject-fault shrink@K:W`` / ``grow@K:W`` kill the
  attempt with :class:`~theanompi_tpu.utils.faults.TopologyChanged`
  and pin the probed world to W for the rest of the supervised run.

Telemetry rides the existing obs stack: one ``kind=retry`` JSONL record
per failed/preempted attempt in ``<obs_dir>/supervisor.jsonl`` (schema:
tools/check_obs_schema.py) — carrying the attempt's ``world`` size so
the log shows topology across retries — plus one ``kind=topology``
record per elastic attempt, and a final ``kind=metrics`` snapshot line
carrying ``tmpi_retries_total`` / ``tmpi_preempt_resumes_total``
appended to ``<obs_dir>/metrics.jsonl``. The reshard itself (when one
happens) is recorded by the worker: a ``kind=reshard`` record and the
``tmpi_reshard_seconds`` gauge in the obs metrics stream.
"""

from __future__ import annotations

import json
import os
import random
import time
from typing import Any, Optional

from theanompi_tpu.obs.numerics import NumericsAnomaly, RollbackRequested
from theanompi_tpu.utils.checkpoint import (
    checkpoint_step,
    clear_resumable_marker,
    latest_checkpoint,
    read_resumable_marker,
    scrub_checkpoint_dir,
)
from theanompi_tpu.utils.faults import Preempted, TopologyChanged

# retry cause labels (classify_retry_cause): the closed vocabulary the
# retry records, the tmpi_retries_total{cause=...} series, and the
# chaos campaign reports share
RETRY_CAUSES = ("crash", "preempt", "topology", "storage", "anomaly")


def classify_retry_cause(e: BaseException) -> str:
    """Map an attempt-killing exception to its instability layer:

    - ``preempt``:  SIGTERM-grace exits (:class:`Preempted`)
    - ``topology``: the device world changed (:class:`TopologyChanged`)
    - ``storage``:  filesystem/OS errors (ENOSPC, vanished mounts,
      unreadable checkpoints — any :class:`OSError`)
    - ``anomaly``:  numerics-policy stops (:class:`NumericsAnomaly` /
      an escaped :class:`RollbackRequested`) — recorded for the
      exhausted-retries record even though these are never retried
    - ``crash``:    everything else (the worker-loop default)
    """
    if isinstance(e, Preempted):
        return "preempt"
    if isinstance(e, TopologyChanged):
        return "topology"
    if isinstance(e, OSError):
        return "storage"
    if isinstance(e, (NumericsAnomaly, RollbackRequested)):
        return "anomaly"
    return "crash"


class _SupervisorLog:
    """Per-attempt ``retry`` records + the final metrics snapshot,
    appended under ``obs_dir`` (inert when obs_dir is None)."""

    def __init__(self, obs_dir: Optional[str], rank: int = 0):
        self.obs_dir = obs_dir
        self.rank = int(rank)
        if obs_dir:
            os.makedirs(obs_dir, exist_ok=True)

    def _append(self, filename: str, rec: dict) -> None:
        if not self.obs_dir:
            return
        with open(os.path.join(self.obs_dir, filename), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def retry(self, attempt: int, step: int, error: BaseException,
              backoff_s: float, resumable: bool = False,
              world: Optional[int] = None) -> None:
        rec = {
            "kind": "retry", "rank": self.rank, "t": time.time(),
            "attempt": int(attempt), "step": int(step),
            "error": repr(error), "backoff_s": float(backoff_s),
            "resumable": bool(resumable),
            # instability attribution (chaos PR): which layer killed
            # the attempt — crash/preempt/topology/storage/anomaly
            "cause": classify_retry_cause(error),
        }
        if world is not None:
            # the attempt's world size: supervisor.jsonl alone shows
            # the topology trajectory across retries
            rec["world"] = int(world)
        self._append("supervisor.jsonl", rec)

    def scrub(self, result: dict) -> None:
        """One ``kind=scrub`` record per retry-time scrub pass that
        quarantined anything (utils/checkpoint.scrub_checkpoint_dir),
        appended to metrics.jsonl next to the reshard/profile records
        — same shape the worker's background scrubber emits."""
        self._append("metrics.jsonl", {
            "kind": "scrub", "rank": self.rank, "t": time.time(),
            "checked": int(result["checked"]),
            "corrupt": int(result["corrupt"]),
            "quarantined": ",".join(result["quarantined"]),
            "seconds": float(result["seconds"]),
        })

    def topology(self, attempt: int, world: int,
                 prev_world: Optional[int] = None) -> None:
        """One record per elastic attempt: the device world it runs in
        (``prev_world`` present from the second attempt on, so a world
        change reads directly off the pair)."""
        rec = {"kind": "topology", "rank": self.rank, "t": time.time(),
               "attempt": int(attempt), "world": int(world)}
        if prev_world is not None:
            rec["prev_world"] = int(prev_world)
        self._append("supervisor.jsonl", rec)

    def snapshot(self, retries: int, preempts: int,
                 step: Optional[int] = None,
                 causes: Optional[dict] = None) -> None:
        metrics = {"tmpi_retries_total": float(retries),
                   "tmpi_preempt_resumes_total": float(preempts)}
        for cause, n in sorted((causes or {}).items()):
            # per-cause series, Prometheus label syntax (the same key
            # shape MetricsRegistry emits for labeled counters)
            metrics[f'tmpi_retries_total{{cause="{cause}"}}'] = float(n)
        rec = {"kind": "metrics", "t": time.time(), "source": "supervisor",
               "metrics": metrics}
        if step is not None:
            rec["step"] = int(step)
        self._append("metrics.jsonl", rec)


def _probe_world(requested: Optional[int], injector) -> int:
    """The device world size the next elastic attempt should run in:
    the LIVE device count (enumerated deterministically — sorted by
    (slice, id), the canonical mesh order — before anything is derived
    from it, so every controller computes the identical value and the
    reshard transfer plan it gates), capped by what the caller asked
    for (``requested`` is the operator's budget; growth never exceeds
    it). A fired shrink/grow fault's ``world_override`` substitutes for
    the live count in tests — the cap still applies to it."""
    import jax

    devs = sorted(jax.devices(),
                  key=lambda d: (getattr(d, "slice_index", 0), d.id))
    n_live = len(devs)
    override = None
    if injector is not None and hasattr(injector, "world_override"):
        override = injector.world_override()
    live = override if override is not None else n_live
    want = min(int(live), int(requested)) if requested else int(live)
    return max(1, min(n_live, want))


def supervise_training(
    *,
    max_retries: int = 2,
    backoff_base: float = 1.0,
    backoff_max: float = 60.0,
    retry_jitter: bool = False,
    ckpt_dir: Optional[str] = None,
    obs_dir: Optional[str] = None,
    resume: bool = False,
    elastic: bool = False,
    # fleet telemetry exporter (obs/exporter.py): explicit kwarg so it
    # is NOT forwarded to run_training — the supervisor owns the
    # exporter, started ONCE before the retry loop and stopped after
    # it, so the port stays bound and scrapers keep answering while
    # attempts die and resume
    fleet_exporter_port: int = 0,
    **run_kwargs: Any,
) -> dict:
    """Run :func:`run_training` under the supervisor (module docstring).

    ``ckpt_dir`` is REQUIRED when ``max_retries > 0`` — a retry without
    a checkpoint to resume from silently restarts training from scratch,
    which is never what a recovery path should do quietly. All other
    kwargs forward to ``run_training`` unchanged.

    ``retry_jitter=True``: decorrelated-jitter backoff instead of the
    plain exponential ladder — seeded from the run's ``seed`` kwarg,
    so one supervisor's sleep schedule is reproducible while a fleet
    of supervisors with distinct seeds de-phases (module docstring).

    ``elastic=True``: re-probe the device world before every attempt
    (``requested`` = the caller's ``devices`` count, honored as a cap)
    and let the resume path reshard the checkpoint onto a changed mesh
    instead of dying on it — see the module docstring. ``devices`` must
    be an int or None in elastic mode (an explicit device LIST pins the
    topology, which is the opposite of elastic).

    Returns the successful attempt's summary dict, extended with
    ``retries`` (failed attempts absorbed), ``preempt_resumes``
    (marker-driven resumes) and ``attempts`` (total runs started).
    """
    from theanompi_tpu.launch.worker import run_training

    if max_retries and not ckpt_dir:
        raise ValueError(
            "supervise_training with max_retries > 0 requires ckpt_dir — "
            "a retry can only auto-resume from a checkpoint"
        )
    if run_kwargs.get("inject_faults"):
        # one injector across ALL attempts: fired flags persist, so an
        # injected fault is transient (fires once per supervised run);
        # rebuilding per attempt would refire it on every retry and no
        # bounded retry policy could ever pass the faulted step
        from theanompi_tpu.utils.faults import FaultInjector

        if not isinstance(run_kwargs["inject_faults"], FaultInjector):
            run_kwargs["inject_faults"] = FaultInjector(
                run_kwargs["inject_faults"]
            )
    injector = run_kwargs.get("inject_faults")
    requested_world = run_kwargs.get("devices")
    if elastic:
        if requested_world is not None and not isinstance(requested_world, int):
            raise ValueError(
                "elastic supervision takes devices as a count (or None "
                "= all live devices) — an explicit device list pins the "
                "topology the elastic mode exists to renegotiate"
            )
        # the worker's resume path must reshard (not die) on a mesh
        # mismatch against the checkpoint's topology manifest
        run_kwargs["elastic"] = True
    log = _SupervisorLog(obs_dir)
    retries = 0
    preempts = 0
    attempt = 0
    world: Optional[int] = None
    retry_causes: dict[str, int] = {}
    # decorrelated jitter state: seeded from the run's seed MIXED with
    # a per-host/per-controller salt (hostname + TMPI_PROCESS_ID). A
    # fleet necessarily shares the training seed (step determinism
    # requires it), so seeding from it alone would make every
    # controller draw the identical backoff — the synchronized
    # stampede the jitter exists to break. Same host + same seed is
    # still reproducible.
    import socket
    import zlib as _zlib

    _salt = (_zlib.crc32(socket.gethostname().encode())
             ^ int(os.environ.get("TMPI_PROCESS_ID", 0) or 0))
    _jitter_rng = random.Random(
        (int(run_kwargs.get("seed", 0) or 0) << 20) ^ _salt)
    _prev_sleep = float(backoff_base)
    if ckpt_dir and read_resumable_marker(ckpt_dir) is not None:
        # a previous invocation was preempted mid-run and checkpointed
        # inside its grace window: auto-resume, no flag needed
        preempts += 1
        resume = True
        print(f"[supervisor] resumable marker found in {ckpt_dir!r}; "
              "auto-resuming", flush=True)
    fleet_exporter = None
    if fleet_exporter_port and obs_dir and \
            int(os.environ.get("TMPI_PROCESS_ID", 0) or 0) == 0:
        # chief-only, once per SUPERVISED run (not per attempt): the
        # /healthz endpoint keeps answering through the backoff gaps a
        # dying attempt leaves, which is exactly when a prober needs it
        try:
            from theanompi_tpu.obs.exporter import FleetExporter

            fleet_exporter = FleetExporter(
                obs_dir, fleet_exporter_port, ckpt_dir=ckpt_dir
            ).start()
            print(f"[supervisor] fleet exporter on {fleet_exporter.url} "
                  "(/metrics /fleet.json /healthz)", flush=True)
        except OSError as e:
            fleet_exporter = None
            print(f"[supervisor] WARNING: fleet exporter failed to bind "
                  f"port {fleet_exporter_port}: {e!r}; continuing "
                  "without it", flush=True)
    try:
        return _supervise_loop(
            run_training, log, ckpt_dir=ckpt_dir, obs_dir=obs_dir,
            resume=resume, elastic=elastic, max_retries=max_retries,
            backoff_base=backoff_base, backoff_max=backoff_max,
            retry_jitter=retry_jitter, injector=injector,
            requested_world=requested_world, retries=retries,
            preempts=preempts, attempt=attempt, world=world,
            retry_causes=retry_causes, jitter_rng=_jitter_rng,
            prev_sleep=_prev_sleep, run_kwargs=run_kwargs,
        )
    finally:
        if fleet_exporter is not None:
            fleet_exporter.stop()


def _supervise_loop(run_training, log, *, ckpt_dir, obs_dir, resume,
                    elastic, max_retries, backoff_base, backoff_max,
                    retry_jitter, injector, requested_world, retries,
                    preempts, attempt, world, retry_causes, jitter_rng,
                    prev_sleep, run_kwargs) -> dict:
    """The retry loop proper, split out so the exporter's try/finally
    wraps it without re-indenting the recovery logic."""
    _jitter_rng = jitter_rng
    _prev_sleep = prev_sleep
    while True:
        attempt += 1
        if elastic:
            # re-probe the live world EVERY attempt (sorted enumeration
            # + injected-fault override; see _probe_world) and record it
            # — the attempt may run in a different topology than the one
            # that just died, and resume reshards onto it
            new_world = _probe_world(requested_world, injector)
            log.topology(attempt, new_world, prev_world=world)
            if world is not None and new_world != world:
                print(f"[supervisor] elastic: world {world} -> "
                      f"{new_world} device(s) for attempt {attempt}",
                      flush=True)
            run_kwargs["devices"] = new_world
            world = new_world
        if ckpt_dir:
            # consumed: if THIS attempt is preempted too it rewrites it
            clear_resumable_marker(ckpt_dir)
        try:
            summary = run_training(ckpt_dir=ckpt_dir, obs_dir=obs_dir,
                                   resume=resume, **run_kwargs)
            break
        except Preempted as e:
            # graceful preemption: checkpointed + marker written by the
            # worker. Do NOT resume in-process — SIGTERM means the kill
            # is imminent; record the attempt and let the exit happen.
            # The next supervise_training() sees the marker and resumes.
            log.retry(attempt, e.step, e, 0.0, resumable=True, world=world)
            log.snapshot(retries, preempts, step=e.step,
                         causes=retry_causes)
            raise
        except NumericsAnomaly:
            # --on-anomaly halt (or an exhausted rollback budget) is a
            # DELIBERATE stop; retrying would override the policy
            raise
        except Exception as e:  # noqa: BLE001 — the retry boundary
            retries += 1
            cause = classify_retry_cause(e)
            retry_causes[cause] = retry_causes.get(cause, 0) + 1
            if ckpt_dir:
                # quarantine corrupt keep-chain members BEFORE the
                # discovery walk (bit-rot, torn writes): the verified
                # walk-back then never re-pays the decompress+CRC of a
                # known-bad file, and the record below names the step
                # the next attempt ACTUALLY resumes from
                scrub = scrub_checkpoint_dir(ckpt_dir)
                if scrub["corrupt"]:
                    log.scrub(scrub)
                    print(
                        f"[supervisor] scrub quarantined "
                        f"{scrub['corrupt']} corrupt checkpoint "
                        f"member(s): {scrub['quarantined']}",
                        flush=True,
                    )
            # verify=True deliberately duplicates the walk resume will
            # redo: the retry record's `step` field is the contract
            # "what the next attempt ACTUALLY resumes from" — after a
            # torn newest checkpoint, the unverified newest would name
            # the very file resume walks past. Retries are rare and
            # backoff-dominated; the extra decompress+CRC walk is the
            # price of an honest record.
            path = latest_checkpoint(ckpt_dir, verify=True) if ckpt_dir else None
            step = checkpoint_step(path)
            if retries > max_retries:
                log.retry(attempt, step, e, 0.0, world=world)
                log.snapshot(retries, preempts, causes=retry_causes)
                raise
            if retry_jitter:
                # decorrelated jitter (module docstring): the slept
                # value is what the retry record carries — the log is
                # the proof the fleet de-phased
                backoff = min(float(backoff_max), _jitter_rng.uniform(
                    float(backoff_base), max(float(backoff_base),
                                             3.0 * _prev_sleep)))
                _prev_sleep = backoff
            else:
                backoff = min(float(backoff_max),
                              float(backoff_base) * (2 ** (retries - 1)))
            log.retry(attempt, step, e, backoff, world=world)
            print(
                f"[supervisor] attempt {attempt} failed ({e!r}); retry "
                f"{retries}/{max_retries} resumes from "
                f"{'step ' + str(step) if step >= 0 else 'scratch (no verified checkpoint)'} "
                f"after {backoff:.2f}s backoff",
                flush=True,
            )
            if backoff > 0:
                time.sleep(backoff)
            resume = True
    if ckpt_dir:
        clear_resumable_marker(ckpt_dir)
    summary["retries"] = retries
    summary["preempt_resumes"] = preempts
    summary["attempts"] = attempt
    summary["retry_causes"] = dict(retry_causes)
    log.snapshot(retries, preempts, step=summary.get("steps"),
                 causes=retry_causes)
    return summary
