"""Fault-tolerant run supervisor: bounded retry + verified auto-resume.

The reference framework's failure story was "the mpirun dies" — a
crashed worker, a corrupt checkpoint, or a transient infrastructure
fault all required a human to notice, diagnose, and relaunch
(SURVEY.md §5.4). :func:`supervise_training` wraps
:func:`~theanompi_tpu.launch.worker.run_training` with the recovery
contract a production run needs:

- **Bounded retry with exponential backoff**: an attempt that dies with
  an ordinary exception is retried up to ``max_retries`` times, sleeping
  ``backoff_base * 2**(failures-1)`` (capped at ``backoff_max``) between
  attempts — a crash-looping run must not hammer shared storage or the
  scheduler.
- **Verified auto-resume**: every retry resumes from the newest
  checkpoint that passes the integrity chain
  (``latest_checkpoint(verify=True)``: per-array CRC32 manifests,
  utils/checkpoint.py) — a truncated or bit-corrupted newest file is
  walked back past, never resumed into.
- **Preemption awareness**: a run that exits via the SIGTERM grace path
  (:class:`~theanompi_tpu.utils.faults.Preempted`) already checkpointed
  and dropped a ``resumable.json`` marker; the supervisor records the
  attempt and RE-RAISES — the SIGKILL is coming, auto-resuming in-place
  would race it. The NEXT invocation sees the marker and auto-resumes
  without being told ``resume=True``.
- **Deliberate stops are not retried**: ``--on-anomaly halt`` (and a
  rollback whose budget is exhausted) raises
  :class:`~theanompi_tpu.obs.numerics.NumericsAnomaly` — retrying would
  override an explicit stop-the-run policy, so it propagates.
  ``KeyboardInterrupt``/``SystemExit`` likewise.

Telemetry rides the existing obs stack: one ``kind=retry`` JSONL record
per failed/preempted attempt in ``<obs_dir>/supervisor.jsonl`` (schema:
tools/check_obs_schema.py) and a final ``kind=metrics`` snapshot line
carrying ``tmpi_retries_total`` / ``tmpi_preempt_resumes_total``
appended to ``<obs_dir>/metrics.jsonl``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

from theanompi_tpu.obs.numerics import NumericsAnomaly
from theanompi_tpu.utils.checkpoint import (
    checkpoint_step,
    clear_resumable_marker,
    latest_checkpoint,
    read_resumable_marker,
)
from theanompi_tpu.utils.faults import Preempted


class _SupervisorLog:
    """Per-attempt ``retry`` records + the final metrics snapshot,
    appended under ``obs_dir`` (inert when obs_dir is None)."""

    def __init__(self, obs_dir: Optional[str], rank: int = 0):
        self.obs_dir = obs_dir
        self.rank = int(rank)
        if obs_dir:
            os.makedirs(obs_dir, exist_ok=True)

    def _append(self, filename: str, rec: dict) -> None:
        if not self.obs_dir:
            return
        with open(os.path.join(self.obs_dir, filename), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def retry(self, attempt: int, step: int, error: BaseException,
              backoff_s: float, resumable: bool = False) -> None:
        self._append("supervisor.jsonl", {
            "kind": "retry", "rank": self.rank, "t": time.time(),
            "attempt": int(attempt), "step": int(step),
            "error": repr(error), "backoff_s": float(backoff_s),
            "resumable": bool(resumable),
        })

    def snapshot(self, retries: int, preempts: int,
                 step: Optional[int] = None) -> None:
        rec = {"kind": "metrics", "t": time.time(), "source": "supervisor",
               "metrics": {"tmpi_retries_total": float(retries),
                           "tmpi_preempt_resumes_total": float(preempts)}}
        if step is not None:
            rec["step"] = int(step)
        self._append("metrics.jsonl", rec)


def supervise_training(
    *,
    max_retries: int = 2,
    backoff_base: float = 1.0,
    backoff_max: float = 60.0,
    ckpt_dir: Optional[str] = None,
    obs_dir: Optional[str] = None,
    resume: bool = False,
    **run_kwargs: Any,
) -> dict:
    """Run :func:`run_training` under the supervisor (module docstring).

    ``ckpt_dir`` is REQUIRED when ``max_retries > 0`` — a retry without
    a checkpoint to resume from silently restarts training from scratch,
    which is never what a recovery path should do quietly. All other
    kwargs forward to ``run_training`` unchanged.

    Returns the successful attempt's summary dict, extended with
    ``retries`` (failed attempts absorbed), ``preempt_resumes``
    (marker-driven resumes) and ``attempts`` (total runs started).
    """
    from theanompi_tpu.launch.worker import run_training

    if max_retries and not ckpt_dir:
        raise ValueError(
            "supervise_training with max_retries > 0 requires ckpt_dir — "
            "a retry can only auto-resume from a checkpoint"
        )
    if run_kwargs.get("inject_faults"):
        # one injector across ALL attempts: fired flags persist, so an
        # injected fault is transient (fires once per supervised run);
        # rebuilding per attempt would refire it on every retry and no
        # bounded retry policy could ever pass the faulted step
        from theanompi_tpu.utils.faults import FaultInjector

        if not isinstance(run_kwargs["inject_faults"], FaultInjector):
            run_kwargs["inject_faults"] = FaultInjector(
                run_kwargs["inject_faults"]
            )
    log = _SupervisorLog(obs_dir)
    retries = 0
    preempts = 0
    attempt = 0
    if ckpt_dir and read_resumable_marker(ckpt_dir) is not None:
        # a previous invocation was preempted mid-run and checkpointed
        # inside its grace window: auto-resume, no flag needed
        preempts += 1
        resume = True
        print(f"[supervisor] resumable marker found in {ckpt_dir!r}; "
              "auto-resuming", flush=True)
    while True:
        attempt += 1
        if ckpt_dir:
            # consumed: if THIS attempt is preempted too it rewrites it
            clear_resumable_marker(ckpt_dir)
        try:
            summary = run_training(ckpt_dir=ckpt_dir, obs_dir=obs_dir,
                                   resume=resume, **run_kwargs)
            break
        except Preempted as e:
            # graceful preemption: checkpointed + marker written by the
            # worker. Do NOT resume in-process — SIGTERM means the kill
            # is imminent; record the attempt and let the exit happen.
            # The next supervise_training() sees the marker and resumes.
            log.retry(attempt, e.step, e, 0.0, resumable=True)
            log.snapshot(retries, preempts, step=e.step)
            raise
        except NumericsAnomaly:
            # --on-anomaly halt (or an exhausted rollback budget) is a
            # DELIBERATE stop; retrying would override the policy
            raise
        except Exception as e:  # noqa: BLE001 — the retry boundary
            retries += 1
            # verify=True deliberately duplicates the walk resume will
            # redo: the retry record's `step` field is the contract
            # "what the next attempt ACTUALLY resumes from" — after a
            # torn newest checkpoint, the unverified newest would name
            # the very file resume walks past. Retries are rare and
            # backoff-dominated; the extra decompress+CRC walk is the
            # price of an honest record.
            path = latest_checkpoint(ckpt_dir, verify=True) if ckpt_dir else None
            step = checkpoint_step(path)
            if retries > max_retries:
                log.retry(attempt, step, e, 0.0)
                log.snapshot(retries, preempts)
                raise
            backoff = min(float(backoff_max),
                          float(backoff_base) * (2 ** (retries - 1)))
            log.retry(attempt, step, e, backoff)
            print(
                f"[supervisor] attempt {attempt} failed ({e!r}); retry "
                f"{retries}/{max_retries} resumes from "
                f"{'step ' + str(step) if step >= 0 else 'scratch (no verified checkpoint)'} "
                f"after {backoff:.2f}s backoff",
                flush=True,
            )
            if backoff > 0:
                time.sleep(backoff)
            resume = True
    if ckpt_dir:
        clear_resumable_marker(ckpt_dir)
    summary["retries"] = retries
    summary["preempt_resumes"] = preempts
    summary["attempts"] = attempt
    log.snapshot(retries, preempts, step=summary.get("steps"))
    return summary
