"""The training driver: epoch loop, validation, checkpointing.

Rebuild of the reference's sync-rule worker processes (reference: BSP
``Worker.run`` epoch/iteration loop with data wait -> train_iter ->
exchange -> record, per-epoch validation, ``adjust_hyperp``, rank-0
checkpoint; SURVEY.md §3.2, §2.1 "Sync-rule drivers"). One driver covers
all rules — the rule picks which compiled step function it runs:

- ``bsp``:   BSP step over a ``('data',)`` mesh (parallel/bsp.py)
- ``easgd``: elastic-averaging step over a worker mesh (parallel/easgd.py)
- ``gosgd``: gossip step (parallel/gosgd.py)

There are no worker processes to manage: the mesh is the workers, and
the driver is plain single-controller Python around jitted SPMD steps
(multi-controller runs call this same function once per host).
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from theanompi_tpu.data import get_dataset
from theanompi_tpu.data.loader import PrefetchLoader
from theanompi_tpu.models.contract import Model
from theanompi_tpu.parallel import make_mesh
from theanompi_tpu.parallel.mesh import host_local_batch_slice, put_global_batch
from theanompi_tpu.utils import (
    Recorder,
    checkpoint_step,
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
)
from theanompi_tpu.utils.checkpoint import (
    AsyncCheckpointer,
    clear_resumable_marker,
    save_checkpoint_sharded,
    write_resumable_marker,
)
from theanompi_tpu.utils.faults import FaultInjector, Preempted
from theanompi_tpu.obs.numerics import NumericsAnomaly, RollbackRequested


def _layout_mismatch(a: dict, b: dict) -> bool:
    """One comparator for pipeline stack layout dicts, shared by the
    sidecar pre-flight check and the in-checkpoint embedded check so the
    two defenses can never silently diverge."""
    return (a.get("interleave", 1), a.get("n_stages")) != (
        b.get("interleave", 1), b.get("n_stages")
    )


def pipeline_layout_guard(
    ckpt_dir: str, pp: int, pp_interleave: int, resume: bool
) -> dict:
    """Interleaved pipeline stacking PERMUTES layers on the stacked axis
    (parallel/pipeline.py::stack_pipeline_params), and every layout
    produces identical leaf shapes — so a checkpoint written under one
    ``--pp/--pp-interleave`` would silently load layer-permuted under
    another. A ``pipeline_layout.json`` sidecar records the stacking
    layout; resume refuses a mismatch loudly. Plain GPipe stacking
    (interleave=1) is layout-invariant across ``--pp``, so only the
    interleaved case pins the stage count.

    The sidecar is the fast pre-flight check only — the layout is ALSO
    embedded in each checkpoint's metadata (``extra_meta``) and
    cross-checked at load, so checkpoints copied without the sidecar
    still refuse to resume layer-permuted. Returns the current layout
    dict for that embedding."""
    import json as _json
    import tempfile

    path = os.path.join(ckpt_dir, "pipeline_layout.json")
    current = {
        "interleave": int(pp_interleave),
        "n_stages": int(pp) if pp_interleave > 1 else None,
    }
    stored = {"interleave": 1, "n_stages": None}
    try:
        # open directly (no exists() pre-check): rank 0 may legitimately
        # remove a stale sidecar while another rank is here, and a
        # vanished file is the layout-invariant default, not corruption
        with open(path) as f:
            stored = _json.load(f)
    except FileNotFoundError:
        pass
    except (ValueError, OSError):
        # unreadable sidecar: only fatal if there are checkpoints it
        # was supposed to describe
        if latest_checkpoint(ckpt_dir) is not None:
            raise ValueError(
                f"{path!r} is unreadable but {ckpt_dir!r} holds "
                "checkpoints whose pipeline stack layout it should "
                "record — delete the checkpoints (or restore the "
                "sidecar) before reusing this dir"
            )
        stored = current  # nothing at stake; rewrite below
    mismatch = _layout_mismatch(stored, current)
    if resume and mismatch:
        raise ValueError(
            f"checkpoints in {ckpt_dir!r} use pipeline stack layout "
            f"{stored} but this run requests {current} — resuming "
            "would silently permute transformer layers; rerun with "
            "the matching --pp/--pp-interleave (or a fresh ckpt-dir)"
        )
    if not resume and mismatch and latest_checkpoint(ckpt_dir) is not None:
        # refusing here (not just overwriting the sidecar) is what
        # keeps a LATER --resume from pairing the rewritten sidecar
        # with the old differently-permuted checkpoints
        raise ValueError(
            f"{ckpt_dir!r} already holds checkpoints with pipeline "
            f"stack layout {stored}; this run requests {current} — "
            "use a fresh --ckpt-dir (or delete the old checkpoints)"
        )
    if jax.process_index() == 0:
        os.makedirs(ckpt_dir, exist_ok=True)
        if current["interleave"] > 1:
            fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".tmp")
            with os.fdopen(fd, "w") as f:
                _json.dump(current, f)
            os.replace(tmp, path)  # atomic: no truncated sidecar
        elif os.path.exists(path):
            try:
                os.remove(path)  # back to the layout-invariant default
            except FileNotFoundError:
                pass  # another run cleaning the same dir got there first
    return current


def run_training(
    rule: str = "bsp",
    model_cls: type[Model] = None,
    devices=None,
    *,
    strategy: str = "psum",
    # compressed-collectives wire codec (parallel/codec.py):
    # none|bf16|int8, optional ':ef' suffix for error feedback — every
    # engine's exchange path consumes it (BSP psum/ring, ZeRO
    # scatter+gather, EASGD elastic psum, GoSGD gossip, ND grad psums)
    wire_codec: str = "none",
    # MFU-push knobs (ROADMAP item 2a/2b): fused_update swaps the
    # optimizer epilogue for the one-pass Pallas kernel
    # (ops/pallas_update.py) on EVERY engine; allreduce_buckets (MB,
    # 0 = off) chunks the BSP gradient allreduce into buckets whose
    # psums launch inside backward (parallel/strategies.py)
    fused_update: bool = False,
    allreduce_buckets: float = 0.0,
    n_slices: Optional[int] = None,
    steps_per_dispatch: int = 1,
    # async dispatch pipeline (utils/dispatch.py): keep up to this many
    # steps in flight before the host blocks on a metrics D2H; 1 = the
    # classic per-step sync (bit-identical recorder rows either way)
    dispatch_depth: int = 1,
    accum_steps: int = 1,
    # N-D parallelism axes (BSP rule only; LM models — parallel/nd.py):
    tp: int = 1,
    sp: int = 1,
    pp: int = 1,
    expert: int = 1,
    microbatches: Optional[int] = None,
    pp_interleave: int = 1,
    # ZeRO-1 optimizer-state sharding (BSP rule only; parallel/zero.py)
    zero: int = 0,
    n_epochs: Optional[int] = None,
    max_steps: Optional[int] = None,
    dataset: Optional[str] = None,
    dataset_kwargs: Optional[dict] = None,
    recipe_overrides: Optional[dict] = None,
    seed: int = 0,
    save_dir: Optional[str] = None,
    ckpt_dir: Optional[str] = None,
    ckpt_every_epochs: int = 1,
    async_checkpoint: bool = True,
    sharded_ckpt: bool = False,
    # background checkpoint scrubber (chaos PR,
    # utils/checkpoint.CheckpointScrubber): re-verify the keep-chain
    # every N seconds and quarantine corrupt members (at-rest bit-rot,
    # torn writes) into <ckpt_dir>/quarantine/ — kind=scrub records +
    # tmpi_scrub_* gauges ride the obs stream; 0 = off (the supervisor
    # still runs one synchronous pass before each retry's resume)
    scrub_interval: float = 0.0,
    resume: bool = False,
    print_freq: int = 40,
    run_name: Optional[str] = None,
    tensorboard: bool = False,
    prefetch_depth: int = 2,
    return_recorder: bool = False,
    profile_dir: Optional[str] = None,
    profile_steps: int = 4,
    # observability subsystem (obs/): metrics snapshots + span trace +
    # heartbeat under obs_dir; stall watchdog when stall_timeout > 0
    obs_dir: Optional[str] = None,
    stall_timeout: float = 0.0,
    metrics_snapshot_freq: int = 0,
    # fleet telemetry exporter (obs/exporter.py): chief-only HTTP
    # server on this port tailing obs_dir into the merged FleetView
    # (/metrics, /fleet.json, /healthz); 0 = off. Under the supervisor
    # the exporter is started ONCE outside the retry loop instead
    # (launch/supervisor.py), so it survives retries.
    fleet_exporter_port: int = 0,
    # numerics flight recorder (obs/numerics.py, obs/flight.py):
    # numerics_freq > 0 compiles the sentinel gauges into every Nth
    # step (grad/update/param norms, fused non-finite count, per-rule
    # divergence) — they drain through the dispatch pipeline, zero new
    # host syncs; anomalies (NaN/Inf, EWMA spikes) are detected at
    # drain time and handled per on_anomaly: 'record' (log + gauges),
    # 'dump' (also write the anomaly_rank{r}/ triage bundle), 'halt'
    # (dump, then stop training), 'rollback' (dump, then restore the
    # last verified checkpoint and keep training — see rollback_budget/
    # rollback_skip below). flight_window sizes the ring of drained
    # step records the bundle preserves.
    numerics_freq: int = 0,
    flight_window: int = 64,
    on_anomaly: str = "dump",
    # model-drift watchdog (obs/drift.py): EWMA band the tmpi_model_err_*
    # gauges may wander inside before a drift anomaly fires (and the
    # flight recorder writes its anomaly_rank{r}-drift/ bundle)
    drift_tolerance: float = 0.25,
    # anomaly rollback (--on-anomaly rollback): on a confirmed anomaly
    # restore the last VERIFIED checkpoint and keep training — at most
    # rollback_budget times per run; on replay, skip rollback_skip data
    # batches at the anomalous step (a persistent bad batch must not
    # re-poison every attempt)
    rollback_budget: int = 2,
    rollback_skip: int = 1,
    # elastic world size (elastic PR): resume may land on a DIFFERENT
    # mesh than the checkpoint was saved under — instead of dying on
    # the shape/sharding mismatch, reshard the state onto the current
    # mesh via the checkpoint's topology manifest
    # (utils/checkpoint.load_resharded). Per-replica batch rescales
    # implicitly (the BSP global batch is mesh-invariant);
    # elastic_lr_scale='linear' additionally scales the recipe's base
    # LR by n_new/n_saved (the per-worker-batch rules grow their
    # GLOBAL batch with the world, where linear scaling is the
    # standard correction; default 'none' leaves the schedule alone).
    elastic: bool = False,
    elastic_lr_scale: str = "none",
    # SIGTERM grace (preemption): > 0 installs a handler; the train
    # loop then checkpoints, marks the run resumable, and exits cleanly
    # (Preempted) instead of dying mid-step
    sigterm_grace: float = 0.0,
    # deterministic fault injection (utils/faults.py): KIND@STEP specs —
    # crash/sigterm/sigkill/ckpt_truncate/nan_batch/loader_stall — so
    # recovery paths are exercised by tests, not trusted on faith
    inject_faults: Optional[list] = None,
    # persistent XLA compilation cache: repeated runs (bench sweeps,
    # requeued jobs) skip recompiling identical programs
    compile_cache_dir: Optional[str] = None,
    # rule-specific kwargs (EASGD avg_freq etc.) forwarded to the rule's
    # step builder
    **rule_kwargs: Any,
) -> dict:
    """Train ``model_cls`` under a sync rule; returns a summary dict.

    The recipe is the model's own (reference: model-owned hyperparams,
    SURVEY.md §5.6); ``recipe_overrides`` is the session's override hook.

    ``async_checkpoint`` (default True) writes epoch checkpoints on a
    background thread overlapped with the next epoch's steps (reference
    parity is the synchronous rank-0 save; SURVEY.md §5.4) — ordering,
    durability-on-return, and the multi-host synchronous fallback are
    handled by :class:`~theanompi_tpu.utils.checkpoint.AsyncCheckpointer`.
    """
    if model_cls is None:
        raise ValueError("model_cls is required")

    if compile_cache_dir:
        # set BEFORE any compile; the threshold knob is left to the
        # environment (conftest/session config own it where they care)
        jax.config.update("jax_compilation_cache_dir", compile_cache_dir)

    recipe = model_cls.default_recipe()
    if recipe_overrides:
        recipe = recipe.replace(**recipe_overrides)
    if elastic_lr_scale not in ("none", "linear"):
        raise ValueError(
            f"elastic_lr_scale must be 'none' or 'linear', "
            f"got {elastic_lr_scale!r}"
        )
    # Elastic resume: peek the newest verified checkpoint's topology
    # manifest BEFORE the model/engine build — the saved world size
    # drives the LR-rescale hook (and nothing else; the reshard itself
    # happens against the live state template at resume time below).
    saved_world = None
    # The LR-rescale anchor: the world size the run's base LR was tuned
    # for. Forwarded through every manifest as elastic.base_world so the
    # scale stays n_target/base across ANY number of reshard/resume
    # cycles — anchoring to the resumed checkpoint's own world instead
    # would silently drop the scale after the first post-reshard save
    # (that checkpoint is stamped with the NEW world).
    base_world = None
    # Peek on EVERY resume (not just elastic ones): a plain --resume in
    # the middle of an elastic sequence must keep forwarding the
    # original anchor, or the next elastic resume rescales against the
    # wrong base.
    if resume and ckpt_dir:
        from theanompi_tpu.utils.checkpoint import read_topology_manifest

        _peek = latest_checkpoint(ckpt_dir, verify=True)
        _manifest = read_topology_manifest(_peek) if _peek else None
        if _manifest and _manifest.get("mesh"):
            saved_world = int(np.prod(_manifest["mesh"]["shape"]))
            base_world = int(
                (_manifest.get("elastic") or {}).get("base_world")
                or saved_world
            )
    if elastic and saved_world and elastic_lr_scale == "linear":
        # deterministic probe (sorted device enumeration) shared with
        # the supervisor — the scale must be rank-uniform
        from theanompi_tpu.launch.supervisor import _probe_world

        if isinstance(devices, int) and devices:
            _n_target = devices
        elif devices is not None:
            # explicit device list: the mesh below is built over exactly
            # these (make_mesh supports lists) — probing ALL live
            # devices here would scale the LR by the wrong ratio
            _n_target = len(devices)
        else:
            _n_target = _probe_world(None, None)
        if _n_target != base_world and "lr" in (recipe.sched_kwargs or {}):
            _sk = dict(recipe.sched_kwargs)
            _sk["lr"] = float(_sk["lr"]) * _n_target / base_world
            recipe = recipe.replace(sched_kwargs=_sk)
            print(
                f"[elastic] linear LR rescale: world {base_world} -> "
                f"{_n_target}, base lr now {_sk['lr']:g}", flush=True,
            )
    if (
        rule.lower() in ("easgd", "gosgd")
        and int(rule_kwargs.get("group_size", 1)) > 1
        and recipe.bn_axis_name is None
        and "bn_axis_name" not in (recipe_overrides or {})
    ):
        # a worker GROUP must be statistically one worker: sync BN batch
        # stats across the group's data axis (override explicitly via
        # recipe_overrides={'bn_axis_name': None} for per-chip BN)
        from theanompi_tpu.parallel.mesh import DATA_AXIS

        recipe = recipe.replace(bn_axis_name=DATA_AXIS)
    model = model_cls(recipe)

    dataset = dataset or recipe.dataset
    if dataset == "synthetic" and getattr(model, "is_lm", False):
        # `tmpi ... --synthetic` on an LM means "synthetic tokens", not
        # float image batches (which would crash tracing the embedding
        # lookup with a float indexer)
        dataset = "lm_synthetic"
    dataset_kwargs = dict(dataset_kwargs or {})
    if dataset in ("synthetic", "imagenet_synthetic"):
        # Synthetic stand-ins default to the MODEL's shapes, so
        # `tmpi ... --synthetic` works for ImageNet-shaped models instead
        # of failing deep in a matmul on 32x32 defaults.
        if dataset == "synthetic":
            dataset_kwargs.setdefault("image_shape", tuple(recipe.input_shape))
        else:
            dataset_kwargs.setdefault("crop", recipe.input_shape[0])
        dataset_kwargs.setdefault("n_classes", recipe.num_classes)
    elif dataset in ("lm_synthetic", "lm_text"):
        # token datasets default to the MODEL's sequence length / vocab
        dataset_kwargs.setdefault("seq_len", recipe.input_shape[0])
        if dataset == "lm_synthetic":
            dataset_kwargs.setdefault("vocab", recipe.num_classes)
    rule = rule.lower()
    from theanompi_tpu.parallel.codec import get_codec

    codec = get_codec(wire_codec)  # validate the spec before any build
    fuse = max(1, int(steps_per_dispatch))
    tp, sp, pp, expert = int(tp), int(sp), int(pp), int(expert)
    zero = int(zero or 0)
    nd_active = max(tp, sp, pp, expert) > 1
    if nd_active or zero:
        what = "--tp/--sp/--pp/--expert" if nd_active else "--zero"
        if rule != "bsp":
            raise ValueError(f"{what} compose with the BSP rule only")
        if strategy != "psum":
            raise ValueError(f"{what} use the in-step psum sync (strategy 'psum')")
        if n_slices and n_slices > 1:
            raise ValueError(f"{what} do not compose with --slices yet")
        if accum_steps != 1:
            raise ValueError(f"{what} do not compose with --accum-steps yet")
        if rule_kwargs:
            raise ValueError(f"{what} got unexpected options {sorted(rule_kwargs)}")
    if nd_active and zero:
        raise ValueError("--zero composes with plain BSP only (ND shards "
                         "optimizer state per its own param specs already)")
    allreduce_buckets = float(allreduce_buckets or 0.0)
    if allreduce_buckets and (rule != "bsp" or zero or nd_active):
        raise ValueError(
            "--allreduce-buckets buckets the BSP in-step gradient "
            "allreduce only (ZeRO's scatter/gather and the ND sharded-"
            "axis psums own their own schedules; EASGD/GoSGD exchange "
            "periodically — there is no every-step allreduce to bucket)"
        )
    if microbatches is not None and pp <= 1:
        raise ValueError("--microbatches requires --pp (GPipe microbatching)")
    if pp_interleave > 1 and pp <= 1:
        raise ValueError("--pp-interleave requires --pp (virtual stages)")
    if nd_active:
        if not getattr(model, "is_lm", False):
            raise ValueError(
                "--tp/--sp/--pp/--expert need an LM model "
                "(theanompi_tpu.models.lm TransformerLMModel / MoELMModel); "
                f"{model_cls.__name__} is classifier-shaped"
            )
        if (expert > 1) != bool(getattr(model, "is_moe", False)):
            raise ValueError(
                "--expert N trains MoELMModel (Switch-MoE); dense "
                "TransformerLMModel uses --tp/--sp/--pp"
                if expert > 1
                else "MoELMModel trains via --expert N"
            )
    if n_slices and n_slices > 1:
        if rule == "bsp":
            from theanompi_tpu.parallel.mesh import make_multislice_mesh

            mesh = make_multislice_mesh(devices, n_slices=n_slices)
        else:
            # EASGD/GoSGD across slices (BASELINE config #4's pod shape:
            # worker groups inside a slice, async exchange over DCN):
            # the engine builds the (worker, data) mesh itself — hand it
            # the flat slice-major device list + the slice count to
            # validate group/slice alignment (make_worker_group_mesh)
            mesh = make_mesh(devices)
            rule_kwargs["n_slices"] = n_slices
    elif nd_active:
        # ND mesh: exactly the active axes, data-major (slice-major
        # device order comes from make_mesh; collectives over the
        # trailing axes stay densest on ICI)
        base = make_mesh(devices)
        devs = np.asarray(base.devices).reshape(-1)
        from jax.sharding import Mesh as _Mesh

        from theanompi_tpu.parallel.nd import DP_AXIS, SP_AXIS, TP_AXIS

        if expert > 1:
            from theanompi_tpu.models.moe import EXPERT_AXIS

            if pp > 1:
                raise ValueError(
                    "--expert composes with data parallelism, --tp and "
                    "--sp (expert x pp is not implemented)"
                )
            if len(devs) % (expert * sp * tp):
                raise ValueError(
                    f"{len(devs)} devices do not divide "
                    f"--expert {expert} x --sp {sp} x --tp {tp}"
                )
            dp = len(devs) // (expert * sp * tp)
            # dp major: the (dp, expert) joint batch sharding keeps each
            # controller's host rows contiguous (NDEngine.host_batch_part);
            # tp innermost: its per-block psum pairs ride adjacent chips
            names = ((DP_AXIS,) if dp > 1 else ()) + (EXPERT_AXIS,) + (
                (SP_AXIS,) if sp > 1 else ()
            ) + ((TP_AXIS,) if tp > 1 else ())
            shape = ((dp,) if dp > 1 else ()) + (expert,) + (
                (sp,) if sp > 1 else ()
            ) + ((tp,) if tp > 1 else ())
            nd_axes = dict(ep_axis=EXPERT_AXIS,
                           dp_axis=DP_AXIS if dp > 1 else None,
                           sp_axis=SP_AXIS if sp > 1 else None,
                           tp_axis=TP_AXIS if tp > 1 else None)
        elif pp > 1:
            if len(devs) % (pp * tp * sp):
                raise ValueError(
                    f"{len(devs)} devices do not divide "
                    f"--pp {pp} x --tp {tp} x --sp {sp}"
                )
            dp = len(devs) // (pp * tp * sp)
            # tp innermost: the per-layer psum pairs ride adjacent
            # devices (densest ICI); pipe outermost — its ppermute runs
            # once per schedule tick, not twice per layer
            names = ("pipe",) + ((DP_AXIS,) if dp > 1 else ()) + (
                (SP_AXIS,) if sp > 1 else ()
            ) + ((TP_AXIS,) if tp > 1 else ())
            shape = (pp,) + ((dp,) if dp > 1 else ()) + (
                (sp,) if sp > 1 else ()
            ) + ((tp,) if tp > 1 else ())
            nd_axes = dict(pipe_axis="pipe",
                           dp_axis=DP_AXIS if dp > 1 else None,
                           sp_axis=SP_AXIS if sp > 1 else None,
                           tp_axis=TP_AXIS if tp > 1 else None,
                           microbatches=microbatches,
                           pp_interleave=pp_interleave)
        else:
            if len(devs) % (tp * sp):
                raise ValueError(
                    f"{len(devs)} devices do not divide --tp {tp} x --sp {sp}"
                )
            dp = len(devs) // (tp * sp)
            names = (DP_AXIS,) + ((TP_AXIS,) if tp > 1 else ()) + (
                (SP_AXIS,) if sp > 1 else ()
            )
            shape = (dp,) + ((tp,) if tp > 1 else ()) + ((sp,) if sp > 1 else ())
            nd_axes = dict(dp_axis=DP_AXIS,
                           tp_axis=TP_AXIS if tp > 1 else None,
                           sp_axis=SP_AXIS if sp > 1 else None)
        mesh = _Mesh(devs.reshape(shape), names)
    else:
        mesh = make_mesh(devices)
    n_dev = mesh.devices.size
    # Batch semantics per rule (reference meaning, SURVEY.md §3.3/§3.5):
    # - bsp:  recipe.batch_size is the GLOBAL batch, sharded across the
    #         mesh (lockstep SGD is defined by its global batch).
    # - easgd/gosgd: recipe.batch_size is the PER-WORKER batch — every
    #         worker (device) trains on its own full batch each local
    #         step, exactly like the reference's per-rank streams; the
    #         global images/step is n_workers x batch_size.
    per_worker_rules = ("easgd", "gosgd")
    if rule not in ("bsp", *per_worker_rules):
        raise ValueError(f"unknown rule {rule!r}; available: bsp, easgd, gosgd")
    if rule == "bsp" and rule_kwargs:
        raise ValueError(
            f"rule 'bsp' got unexpected options {sorted(rule_kwargs)} "
            "(avg_freq/alpha/p_push/group_size apply to EASGD/GoSGD only)"
        )
    if rule in per_worker_rules and strategy != "psum":
        raise ValueError("strategy applies to the BSP rule only")
    if strategy == "hier" and not (n_slices and n_slices > 1):
        raise ValueError(
            "strategy 'hier' is the cross-slice hierarchical exchange — "
            "it needs a multislice mesh (--slices N with N > 1); on a "
            "single slice the flat 'psum' is already optimal"
        )
    # fuse>1 works for every rule: BSP scans allreduce-inside steps;
    # EASGD embeds its elastic exchange at the avg_freq boundaries
    # inside the scan; GoSGD ships per-substep gossip-cadence flags
    # Async-rule worker groups: each worker = group_size chips, so the
    # worker count (and the global batch multiplier) is n_dev / group_size
    # (bsp with group_size already raised above)
    group_size = (
        int(rule_kwargs.get("group_size", 1)) if rule in per_worker_rules else 1
    )
    if group_size > 1 and n_dev % group_size:
        raise ValueError(
            f"{n_dev} devices do not divide into groups of {group_size}"
        )
    n_workers = n_dev // max(1, group_size)
    batch = recipe.batch_size * (n_workers if rule in per_worker_rules else 1)

    data = get_dataset(dataset, **dataset_kwargs)
    if tuple(data.image_shape) != tuple(recipe.input_shape):
        raise ValueError(
            f"dataset {dataset!r} yields images {tuple(data.image_shape)} but "
            f"model {model_cls.__name__} expects {tuple(recipe.input_shape)}; "
            "pass dataset_kwargs/--dataset matching the recipe (or override "
            "recipe.input_shape)"
        )
    if data.n_classes != recipe.num_classes:
        raise ValueError(
            f"dataset {dataset!r} has {data.n_classes} classes but model head "
            f"expects {recipe.num_classes} (override recipe.num_classes or the "
            "dataset's n_classes)"
        )
    steps_per_epoch = data.n_train_batches(batch)
    if steps_per_epoch == 0:
        raise ValueError(
            f"dataset has {data.n_train} train examples < the global batch "
            f"{batch} ({'= n_workers x recipe.batch_size' if rule in per_worker_rules else '= recipe.batch_size'})"
        )
    n_epochs = n_epochs if n_epochs is not None else recipe.n_epochs

    vbatch = recipe.val_batch_size or batch
    if nd_active:
        # tokens shard P(batch_axis, seq_axis); seq divides sp, batch
        # divides the batch axis x (for pipelines) the microbatch count
        T = recipe.input_shape[0]
        if sp > 1 and T % sp:
            raise ValueError(f"sequence length {T} not divisible by --sp {sp}")
        batch_div = expert * max(1, n_dev // (expert * sp * tp)) if expert > 1 else (
            (microbatches or pp) * max(1, n_dev // (pp * tp * sp)) if pp > 1
            else n_dev // (tp * sp)
        )
        for name, b in (("batch", batch), ("val batch", vbatch)):
            if batch_div and b % batch_div:
                raise ValueError(
                    f"global {name} {b} not divisible by {batch_div} "
                    "(batch-axis devices x microbatches)"
                )
    else:
        if batch % n_dev:
            raise ValueError(f"global batch {batch} not divisible by {n_dev} devices")
        if vbatch % n_dev:
            raise ValueError(f"val batch {vbatch} not divisible by {n_dev} devices")
    if data.n_val and vbatch > data.n_val:
        # n_val_batches() would be 0: the val loop would yield NOTHING
        # and summary['val'] silently never set (this exact failure
        # shipped in an early n=64 experiment run)
        raise ValueError(
            f"val batch {vbatch} exceeds the dataset's {data.n_val} val "
            "examples — validation would silently run zero batches "
            "(set recipe val_batch_size or enlarge the val split)"
        )

    # Device-side normalization (dataset opt-in): the loader ships
    # compact uint8 batches and (x - mean) * scale fuses into the
    # compiled step — 4x less H2D than float32 (the reference normalized
    # in the host loader; on TPU the wire is the scarcer resource).
    eval_views = int(getattr(data, "val_views", 1))
    input_transform = None
    dtf = getattr(data, "device_transform", None)
    if dtf is not None:
        mean_c = jnp.asarray(dtf["mean"], jnp.float32)
        scale_c = jnp.float32(dtf["scale"])

        def input_transform(x):
            return (x.astype(jnp.float32) - mean_c) * scale_c

    if nd_active:
        from theanompi_tpu.parallel.nd import NDEngine

        engine = NDEngine(
            model, mesh, steps_per_epoch=steps_per_epoch,
            wire_codec=codec, fused_update=fused_update, **nd_axes,
        )
    elif zero:
        from theanompi_tpu.parallel.zero import ZeroEngine

        engine = ZeroEngine(
            model, mesh, steps_per_epoch=steps_per_epoch,
            input_transform=input_transform, eval_views=eval_views,
            wire_codec=codec, fused_update=fused_update,
        )
    elif rule == "bsp":
        from theanompi_tpu.parallel.bsp import BSPEngine

        engine = BSPEngine(
            model, mesh, steps_per_epoch=steps_per_epoch, strategy=strategy,
            input_transform=input_transform, eval_views=eval_views,
            accum_steps=accum_steps, wire_codec=codec,
            fused_update=fused_update, allreduce_buckets=allreduce_buckets,
        )
    elif rule == "easgd":
        from theanompi_tpu.parallel.easgd import EASGDEngine

        engine = EASGDEngine(
            model, mesh, steps_per_epoch=steps_per_epoch,
            input_transform=input_transform, eval_views=eval_views,
            accum_steps=accum_steps, wire_codec=codec,
            fused_update=fused_update, **rule_kwargs,
        )
    else:
        from theanompi_tpu.parallel.gosgd import GOSGDEngine

        engine = GOSGDEngine(
            model, mesh, steps_per_epoch=steps_per_epoch,
            input_transform=input_transform, eval_views=eval_views,
            accum_steps=accum_steps, wire_codec=codec,
            fused_update=fused_update, **rule_kwargs,
        )

    # Topology stamp for every checkpoint this run writes (elastic PR):
    # the ENGINE's mesh identity (EASGD/GoSGD group mode reshapes the
    # driver mesh internally) + the engine's per-leaf elastic reshard
    # policies — what load_resharded needs to move the checkpoint onto
    # a different world later. Stamping is unconditional and cheap (a
    # small JSON entry per save); elasticity is an attribute of the
    # RESUME, not the save.
    from theanompi_tpu.parallel.mesh import mesh_topology

    topo_meta = {"mesh": mesh_topology(getattr(engine, "mesh", mesh))}
    _espec = getattr(engine, "elastic_spec", None)
    if _espec is not None:
        topo_meta["elastic"] = _espec()
    # the engine's ShardingRecipe identity (parallel/recipe.py) rides
    # the manifest too: the stamp then records both the DECLARED spec
    # source and the live-array specs it placed, so the sharding
    # analyzer's train->serve handoff check reads one artifact
    _srecipe = getattr(engine, "sharding_recipe", None)
    if _srecipe is not None:
        topo_meta["recipe"] = _srecipe().as_json()
    # Forward the run's LR-scale anchor (see base_world above): resumed
    # runs keep the ORIGINAL world; fresh runs anchor to the world they
    # launch on.
    topo_meta.setdefault("elastic", {})["base_world"] = int(
        base_world or getattr(engine, "mesh", mesh).devices.size
    )

    # Multi-controller: this host produces only its slice of every
    # global batch (reference: per-rank loader feed, lib/proc_load_mpi.py)
    n_proc = jax.process_count()
    if n_proc > 1 and nd_active:
        # ND token layouts own their host slice: contiguous dp/expert
        # row ranges where the sharding permits, full-batch feed where
        # tokens are replicated across hosts (pure tp/sp) or microbatch-
        # major interleaving makes slices non-contiguous (pipelines) —
        # see NDEngine.host_batch_part
        part = engine.host_batch_part(batch)
        vpart = engine.host_batch_part(vbatch)
    else:
        part = host_local_batch_slice(mesh, batch) if n_proc > 1 else None
        vpart = host_local_batch_slice(mesh, vbatch) if n_proc > 1 else None
        if n_proc > 1 and (batch % n_proc or vbatch % n_proc):
            raise ValueError(
                f"global batch {batch} / val batch {vbatch} must divide the "
                f"{n_proc} controller processes"
            )

    rec = Recorder(
        rank=jax.process_index(), print_freq=print_freq,
        # files are written by the rank-0 controller only (reference:
        # rank-0 recorder save); console prints keep their rank prefix
        save_dir=save_dir if jax.process_index() == 0 else None,
        # run_name override: committed experiments name artifacts after
        # the EXPERIMENT, not the model class (round-3 weak item 6:
        # results/digits_bsp/ held files named cifar10_bsp.jsonl)
        run_name=run_name or f"{model.name}_{rule}",
        tensorboard=tensorboard,
    )
    if profile_dir and jax.process_index() == 0:
        # reference: the recorder WAS the profiler (host brackets); the
        # XLA in-step comm/compute split needs a device trace (§5.1).
        # Offset is relative to the first tick, so resume is handled.
        rec.enable_profile(profile_dir, start_offset=2, n_steps=profile_steps)
    rng = jax.random.PRNGKey(seed)
    state = engine.init_state(rng)
    start_epoch = 0
    summary_resumed_from = None
    # set when an elastic resume actually resharded: the obs facade is
    # built later, so the reshard record/metrics are emitted then
    pending_reshard = None
    # data batches skipped by anomaly rollbacks in this training
    # timeline (restored from checkpoint meta on resume): every replay
    # position below must count BATCHES CONSUMED = step + skipped, or a
    # later resume would re-feed one already-trained batch per skip and
    # shift every subsequent step's data
    skipped_prior = 0
    layout_meta = None
    if ckpt_dir:
        # validates for EVERY rule (a fresh non-pipeline run must not
        # clobber an interleaved dir either); writes/clears the sidecar
        layout = pipeline_layout_guard(ckpt_dir, pp, pp_interleave, resume)
        layout_meta = {"pipeline_layout": layout}

    def _place_restored(restored):
        # restored leaves are full host arrays; under multi-controller
        # the SPMD step needs global sharded jax Arrays — each process
        # commits only its addressable shards (jnp.asarray would make
        # process-local arrays). Shared by resume and anomaly rollback.
        shardings = getattr(engine, "state_shardings", None)
        if n_proc > 1 and shardings is not None:
            return jax.tree_util.tree_map(
                lambda a, s: jax.make_array_from_callback(
                    np.shape(a), s, lambda idx, a=a: np.asarray(a)[idx]
                ),
                restored, shardings,
            )
        return jax.tree_util.tree_map(jnp.asarray, restored)

    if resume and ckpt_dir:
        # verify=True: the integrity chain (per-array CRC manifests)
        # walks back past a corrupt/truncated newest checkpoint instead
        # of resuming into a load-time explosion
        path = latest_checkpoint(ckpt_dir, verify=True)
        if n_proc > 1:
            # Every controller must resume from the SAME step or the
            # lockstep SPMD program diverges/deadlocks. ckpt_dir must be
            # shared storage (same contract as the reference's NFS-visible
            # rank-0 save). Allgather every rank's resolved step and have
            # EVERY rank (including 0) compare the full vector, so all
            # processes fail together instead of rank 0 sailing into a
            # collective that will never complete.
            from jax.experimental import multihost_utils

            steps_seen = np.asarray(
                multihost_utils.process_allgather(
                    np.int64(checkpoint_step(path))
                )
            ).reshape(-1)
            if not np.all(steps_seen == steps_seen[0]):
                raise RuntimeError(
                    f"controller processes resolved different checkpoint "
                    f"steps {steps_seen.tolist()} (this is process "
                    f"{jax.process_index()}): ckpt_dir={ckpt_dir!r} is not "
                    "shared storage visible to all controllers (required "
                    "for --resume)"
                )
        if path:
            from theanompi_tpu.utils.checkpoint import read_checkpoint_meta

            ckpt_meta = read_checkpoint_meta(path)
            saved_layout = ckpt_meta.get("pipeline_layout")
            if saved_layout is not None and layout_meta is not None and (
                _layout_mismatch(saved_layout, layout_meta["pipeline_layout"])
            ):
                # defense in depth vs a deleted/absent sidecar: the
                # checkpoint itself knows the stack layout it was saved
                # under (every layout has identical leaf shapes, so a
                # mismatch would otherwise load silently layer-permuted)
                raise ValueError(
                    f"checkpoint {path!r} embeds pipeline stack layout "
                    f"{saved_layout} but this run requests "
                    f"{layout_meta['pipeline_layout']} — rerun with the "
                    "matching --pp/--pp-interleave"
                )
            if elastic:
                # mesh-portable restore: same saved/live topology loads
                # exactly like the plain path (bit-identical resume); a
                # topology mismatch reshards each leaf onto the live
                # mesh under the manifest's elastic policies —
                # returning device-placed global arrays directly (the
                # sharded-set path never assembles a full array here)
                from theanompi_tpu.utils.checkpoint import load_resharded

                _t0 = time.perf_counter()
                restored, saved_rng, rs_info = load_resharded(
                    path, state, getattr(engine, "mesh", mesh)
                )
                if rs_info["resharded"]:
                    state = restored
                    pending_reshard = {
                        "step": engine.get_step(state),
                        "from_world": rs_info["from_world"],
                        "to_world": rs_info["to_world"],
                        "seconds": time.perf_counter() - _t0,
                        "leaves": rs_info["leaves"],
                        "per_replica_batch": batch // n_dev,
                    }
                    print(
                        f"[elastic] resharded {path} onto the live mesh: "
                        f"world {rs_info['from_world']} -> "
                        f"{rs_info['to_world']}, {rs_info['leaves']} "
                        f"leaves, per-replica batch {batch // n_dev}",
                        flush=True,
                    )
                else:
                    state = _place_restored(restored)
            else:
                restored, saved_rng = load_checkpoint(path, state)
                state = _place_restored(restored)
            if saved_rng is not None:
                # already wrapped with the impl that wrote it — a
                # pre-rbg-default threefry checkpoint keeps resuming
                rng = saved_rng
            # positioning counts BATCHES CONSUMED, not steps: rollback
            # skips consumed batches without training steps, and the
            # checkpoint records how many (see skipped_prior above)
            skipped_prior = int(ckpt_meta.get("skipped_batches", 0))
            start_epoch = (engine.get_step(state) + skipped_prior) // steps_per_epoch
            summary_resumed_from = engine.get_step(state)
            print(f"resumed from {path} at step {engine.get_step(state)}", flush=True)

    if hasattr(engine, "place_batch"):
        # engine-owned placement (ND engines: tokens shard over
        # (batch, seq) axes / microbatch-major — not the leading-dim-
        # only layout put_global_batch assumes)
        def place(b):
            return engine.place_batch(*b)
    else:
        def place(b):
            # global rows inferred per array (local rows x process_count):
            # x and y may carry different row counts (10-crop val ships
            # views x batch image rows against batch label rows)
            x, y = b
            return (put_global_batch(mesh, x), put_global_batch(mesh, y))

    def place_group(group):
        # fused dispatch: stack g host batches -> ONE [g, batch, ...]
        # transfer (dim 0 replicated, dim 1 sharded); ND engines own the
        # stacked layout (token specs / microbatch-major)
        if hasattr(engine, "place_group"):
            return engine.place_group(group)
        from theanompi_tpu.parallel.mesh import put_stacked_batches

        xs = np.stack([b[0] for b in group])
        ys = np.stack([b[1] for b in group])
        return put_stacked_batches(mesh, xs), put_stacked_batches(mesh, ys)

    def grouper(it, k):
        buf = []
        for b in it:
            buf.append(b)
            if len(buf) == k:
                yield buf
                buf = []
        if buf:  # epoch remainder: a smaller fused program (cached)
            yield buf

    summary: dict = {"epochs": [], "rule": rule, "model": model.name,
                     "resumed_from_step": summary_resumed_from}
    # images shipped per dispatch ('step' timing bracket) — fused
    # dispatches carry g x batch, so throughput must be computed from
    # this ledger, not batch / mean_time (which undercounts g-fold)
    dispatch_images: list[int] = []
    # sharded_ckpt: per-host shard files, no cross-host gather / rank-0
    # memory spike; restorable under any process count (SURVEY.md §5.4)
    ckpt_writer = (
        AsyncCheckpointer(sharded=sharded_ckpt)
        if (ckpt_dir and async_checkpoint) else None
    )
    sync_save = save_checkpoint_sharded if sharded_ckpt else save_checkpoint
    step_count = engine.get_step(state)
    # Mid-epoch resume (checkpoint written after a max_steps truncation):
    # fast-forward past the batches the restored timeline already
    # consumed — trained steps PLUS rollback-skipped batches — so data
    # order and epoch accounting stay exact.
    skip_batches = (step_count + skipped_prior) % steps_per_epoch
    if skip_batches and os.environ.get("TMPI_CHAOS_MUTATE") == "refeed":
        # chaos oracle self-test mutation (tools/chaos.py --mutate
        # refeed): deliberately re-feed the last already-consumed batch
        # on resume — a seeded recovery-accounting bug the campaign's
        # invariant oracle MUST catch (and shrink); never set outside
        # the chaos runner's mutation mode
        skip_batches -= 1
    from theanompi_tpu.obs import Observability

    # obs facade: span log + heartbeat per rank, metrics snapshots on
    # rank 0, stall watchdog when requested; inert when obs_dir is None.
    # Created HERE, immediately before the try whose finally closes it:
    # any earlier raise (resume mismatch, layout guard, init OOM) must
    # not leak its threads / open files / the process-global span hook.
    nfreq = max(0, int(numerics_freq))
    if nfreq and obs_dir is None:
        print(
            f"[rank {jax.process_index()}] WARNING: --numerics-freq "
            f"without --obs-dir: sentinels and anomaly detection run "
            f"(on_anomaly={on_anomaly!r} is honored) but no numerics "
            "telemetry or flight dump can be written",
            flush=True,
        )
    obs = Observability(
        obs_dir,
        rank=jax.process_index(),
        stall_timeout=stall_timeout,
        snapshot_freq=metrics_snapshot_freq,
        numerics_freq=nfreq,
        flight_window=flight_window,
        on_anomaly=on_anomaly,
        drift_tolerance=drift_tolerance,
    )
    fleet_exporter = None
    if fleet_exporter_port and obs.enabled and jax.process_index() == 0:
        # chief-only fleet telemetry plane (obs/exporter.py): tail the
        # obs dir every rank writes into, serve the merged FleetView
        # over HTTP. Best-effort — a taken port degrades to
        # no-exporter, never to a failed run. (Supervised runs start
        # the exporter in launch/supervisor.py instead, outside the
        # retry loop, and do not forward the port here.)
        try:
            from theanompi_tpu.obs.exporter import FleetExporter

            fleet_exporter = FleetExporter(
                obs_dir, fleet_exporter_port, topology=topo_meta
            ).start()
            print(f"[rank 0] fleet exporter on {fleet_exporter.url} "
                  "(/metrics /fleet.json /healthz)", flush=True)
        except OSError as e:
            fleet_exporter = None
            print(f"[rank 0] WARNING: fleet exporter failed to bind "
                  f"port {fleet_exporter_port}: {e!r}; continuing "
                  "without it", flush=True)
    if pending_reshard is not None:
        # the reshard ran before the obs facade existed; emit its
        # kind=reshard record + tmpi_reshard_* metrics now
        obs.note_reshard(**pending_reshard)
        summary["resharded_from_world"] = pending_reshard["from_world"]
        summary["resharded_to_world"] = pending_reshard["to_world"]
    if obs.enabled:
        # bracket delegation: timing histograms into the obs registry,
        # wait/step/comm brackets doubling as trace spans
        rec.registry = obs.registry
        rec.spans = obs.spans
        if hasattr(engine, "traffic_model"):
            # each sync rule declares its analytic wire model
            # (obs/comm.py); never let an accounting bug take down
            # training
            try:
                obs.set_traffic_model(engine.traffic_model(state))
            except Exception as e:  # noqa: BLE001
                print(f"[obs] traffic model unavailable for {rule!r}: "
                      f"{e!r}", flush=True)
        if nfreq and hasattr(engine, "numerics_model"):
            # ... and its numerics declaration (obs/numerics.py):
            # which sentinels ride the step, which divergence gauge
            # the rule supports, what extra wire the gauge costs
            try:
                obs.set_numerics_model(engine.numerics_model(state))
            except Exception as e:  # noqa: BLE001
                print(f"[obs] numerics model unavailable for {rule!r}: "
                      f"{e!r}", flush=True)
        if hasattr(engine, "cost_model") and n_proc == 1:
            # ... and the compiled-step cost model (utils/flops.py):
            # FLOPs + HBM bytes of the step executable, feeding the
            # live tmpi_mfu / tmpi_hbm_gbps / tmpi_step_*_frac gauges
            # and the per-snapshot kind=profile attribution record
            # (obs/attribution.py). The lowering compiles (persistent-
            # cache-friendly) but never executes; single-controller
            # only — abstract lowering has no multihost story yet.
            try:
                obs.set_cost_model(engine.cost_model(state, batch))
            except Exception as e:  # noqa: BLE001
                print(f"[obs] cost model unavailable for {rule!r}: "
                      f"{e!r}", flush=True)
        if hasattr(engine, "memory_model"):
            # ... and the declared state residency (utils/flops.py
            # MemoryModel): the predicted per-device HBM high-water the
            # drift watchdog diffs against device.memory_stats()
            try:
                obs.set_memory_model(engine.memory_model(state))
            except Exception as e:  # noqa: BLE001
                print(f"[obs] memory model unavailable for {rule!r}: "
                      f"{e!r}", flush=True)

    def _flight_state_saver(dump_dir):
        # best-effort param-state capture into the triage bundle (the
        # anomalous step's params/opt state, NaNs and all); closure
        # reads the CURRENT state/step — the dump happens at drain
        # time, on the driver thread
        sync_save(dump_dir, state, step_count, rng=rng, keep=1,
                  topology=topo_meta)

    obs.set_flight_state_saver(_flight_state_saver)
    from theanompi_tpu.utils.dispatch import MetricsDispatcher

    # Async dispatch pipeline (utils/dispatch.py): the ONLY
    # host<->device sync in the train loops below lives in the
    # dispatcher's drain (lint: tools/check_hot_loop.py). depth=1
    # reproduces the classic per-step sync exactly. on_row feeds each
    # drained row (already host-side) to the flight ring + anomaly
    # detection — numerics telemetry adds no sync of its own. Wired
    # only when something can consume it: sentinels requested, or a
    # stall watchdog whose dump would preserve the ring (plain obs runs
    # keep their drain path lean).
    disp = MetricsDispatcher(
        rec, depth=dispatch_depth, on_step_seconds=obs.note_step_seconds,
        on_row=obs.on_row
        if (nfreq or (obs.enabled and stall_timeout > 0)) else None,
    )
    obs.attach_dispatcher(disp)
    if disp.depth > 1 and not getattr(engine, "donates_state", False):
        print(
            f"[rank {jax.process_index()}] WARNING: engine {rule!r} does "
            f"not donate its state buffers on this mesh; dispatch_depth="
            f"{disp.depth} keeps extra params+opt copies live in HBM",
            flush=True,
        )
    train_loop_s = 0.0  # wall time inside the train loops (the
    # denominator of summary['host_blocked_frac'])
    # -- fault-tolerance state (fault-tolerant run supervisor PR) -------
    # injected faults fire at deterministic steps (utils/faults.py);
    # SIGTERM flips a flag the train loops poll, so preemption
    # checkpoints and exits cleanly inside the grace window; the
    # rollback policy restores the last VERIFIED checkpoint on a
    # confirmed anomaly and keeps training within its budget.
    # accept a pre-built injector: the supervisor passes ONE instance
    # through every retry attempt, so its fired flags persist and an
    # injected fault is transient (fires once per supervised run, not
    # once per attempt — refiring every attempt would model a permanent
    # bug no retry policy could absorb)
    faults = (
        inject_faults if isinstance(inject_faults, FaultInjector)
        else (FaultInjector(inject_faults) if inject_faults else None)
    )
    if faults is not None:
        # storage faults (enospc/slow_write) fire INSIDE the checkpoint
        # write — install the injector as the writer shim for this run
        # (cleared in the finally; the hook is process-global because
        # the async writer thread has no per-save plumbing)
        from theanompi_tpu.utils.checkpoint import set_write_fault_hook

        set_write_fault_hook(faults.write_fault)
        # slice-granular topology faults (slice_down) resolve their
        # survivor world from the mesh THIS attempt actually built —
        # re-registered every attempt, so an elastic retry's shrunk
        # shape is what the next whole-slice loss subtracts from
        from theanompi_tpu.parallel.mesh import slice_topology

        faults.set_topology(*slice_topology(mesh))
    # background keep-chain scrubber (chaos PR): periodic re-verify +
    # quarantine of corrupt checkpoint members, reported through the
    # obs facade (kind=scrub + tmpi_scrub_* gauges)
    scrubber = None
    if ckpt_dir and scrub_interval and scrub_interval > 0:
        from theanompi_tpu.utils.checkpoint import CheckpointScrubber

        scrubber = CheckpointScrubber(
            ckpt_dir, interval=float(scrub_interval),
            on_result=obs.note_scrub,
        )
        scrubber.start()
    rollbacks = 0
    rollback_budget_left = (
        max(0, int(rollback_budget)) if on_anomaly == "rollback" else 0
    )
    skip_from_step: Optional[int] = None  # anomalous step whose batch
    # window the post-rollback replay skips (per-step path)
    skip_data_batches = 0
    skipped_steps_total = skipped_prior  # timeline total, persisted in
    # every checkpoint's meta so replay positioning survives resume
    # set the moment an anomaly is detected in the LIVE state (a flush
    # during preemption/unwinding making the first detection): both the
    # preemption save and the finally's crash save honor it, so a
    # poisoned state can never become the newest resumable checkpoint
    _state_poisoned = False

    def _save_meta():
        # checkpoint meta: pipeline layout + (when any) the rollback-
        # skipped batch count — the replay-position correction a later
        # resume needs (batches consumed = step + skipped)
        m = dict(layout_meta or {})
        if skipped_steps_total:
            m["skipped_batches"] = skipped_steps_total
        return m or None
    # step of the newest durable checkpoint: the crash-path save in the
    # finally below must not duplicate a boundary save (-1 = none yet)
    last_ckpt_step = step_count if summary_resumed_from is not None else -1
    _preempt = {"flag": False}
    _prev_sigterm = None
    if sigterm_grace and sigterm_grace > 0:
        if threading.current_thread() is threading.main_thread():

            def _on_sigterm(signum, frame):
                _preempt["flag"] = True
                print(
                    f"[rank {jax.process_index()}] SIGTERM: will "
                    f"checkpoint and exit within the {sigterm_grace}s "
                    "grace window",
                    flush=True,
                )

            _prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
        else:
            print(
                f"[rank {jax.process_index()}] WARNING: sigterm_grace "
                "needs the main thread (signal handlers cannot be "
                "installed from session-API background threads); "
                "preemption grace is off for this run",
                flush=True,
            )
    # the device trace and the JSONL log must be closed even when a
    # step raises (OOM, loader failure, Ctrl-C) — close() stops a
    # live capture and warns if the window never opened
    try:
        epoch = start_epoch
        while epoch < n_epochs:
          try:
            rec.start_epoch()
            epoch_steps = 0
            t_loop0 = time.perf_counter()
            if fuse > 1:
                # fused dispatch: groups of <= fuse batches, stacked and
                # shipped in one transfer, run by one compiled program
                import itertools

                with PrefetchLoader(
                    grouper(
                        itertools.islice(
                            data.train_epoch(epoch, batch, seed=seed, part=part),
                            skip_batches,
                            None,
                        ),
                        fuse,
                    ),
                    place_group,
                    # depth counts GROUPS here: keep device-resident input
                    # comparable to the per-step path (depth x fuse steps
                    # prefetched would scale input HBM by fuse)
                    depth=max(1, prefetch_depth // fuse),
                ) as loader:
                    skip_batches = 0
                    rec.start("wait")
                    for xs, ys in loader:
                        if _preempt["flag"]:
                            raise Preempted(step_count)
                        disp.note_wait(rec.end("wait"))
                        if max_steps and step_count + xs.shape[0] > max_steps:
                            # trim the final group to land exactly on max_steps
                            keep = max_steps - step_count
                            xs, ys = xs[:keep], ys[:keep]
                        rec.profile_tick(step_count)
                        g = int(xs.shape[0])
                        if faults is not None:
                            # fused injection at GROUP granularity: a
                            # fault due anywhere in the group fires
                            # before its dispatch; nan_batch poisons
                            # the whole stacked transfer (the sentinel
                            # machinery reads it identically)
                            faults.check_step(step_count + 1, step_count + g)
                            xs = faults.poison_batch(
                                xs, step_count + 1, step_count + g
                            )
                        # the SAME sequential splits the per-step path draws,
                        # shipped stacked — fused training is bit-identical
                        subs = []
                        for _ in range(g):
                            rng, s = jax.random.split(rng)
                            subs.append(s)
                        # numerics under fusion: the dispatch unit is
                        # the GROUP, so the cadence gates at group
                        # granularity — the numerics variant runs only
                        # for groups that contain a step on the nfreq
                        # grid (then sentinels ride every substep of
                        # that group; per-substep gating would split
                        # the compiled program). GoSGD's param-sized
                        # divergence pmean is therefore still amortized
                        # by raising --numerics-freq.
                        nm_group = bool(nfreq) and (
                            (step_count + g) // nfreq > step_count // nfreq
                        )
                        state, metrics = engine.fused_train_step(
                            state, xs, ys, jnp.stack(subs),
                            numerics=nm_group,
                        )
                        step_count += g
                        epoch_steps += g
                        dispatch_images.append(batch * g)
                        # liveness first (watchdog/heartbeat learn of the
                        # dispatch immediately — a hung collective stops
                        # the drain, and with it further dispatches,
                        # within `depth` groups), then rows + step timing
                        # via the dispatcher's drain — the only host sync
                        # in this loop
                        obs.on_step(step_count, substeps=g)
                        disp.push(step_count, metrics,
                                  n_images=batch * g, substeps=g)
                        rec.start("wait")
                        if max_steps and step_count >= max_steps:
                            break
                    # the epoch-tail wait (the loader's StopIteration
                    # fetch) must be credited too, or the flush below
                    # would attribute it to the in-flight steps AND the
                    # wait bracket — double counting that breaks the
                    # span-fraction invariant
                    disp.note_wait(rec.end("wait"))
                disp.flush()
                rec.end_epoch(epoch, n_images=epoch_steps * batch)
            else:
                with PrefetchLoader(
                    data.train_epoch(epoch, batch, seed=seed, part=part),
                    place,
                    depth=prefetch_depth,
                ) as loader:
                    rec.start("wait")
                    for xg, yg in loader:
                        if skip_batches:
                            skip_batches -= 1
                            continue
                        if skip_from_step is not None and (
                            step_count + 1 == skip_from_step
                        ):
                            # post-rollback replay reached the anomalous
                            # step again: skip its batch window (consume
                            # the data and its rng splits, train
                            # nothing) so a persistent bad batch cannot
                            # re-poison every rollback attempt
                            skip_from_step = None
                            skip_data_batches = max(0, int(rollback_skip))
                        if skip_data_batches:
                            skip_data_batches -= 1
                            skipped_steps_total += 1
                            rng, _ = jax.random.split(rng)
                            continue
                        if _preempt["flag"]:
                            raise Preempted(step_count)
                        disp.note_wait(rec.end("wait"))
                        if faults is not None:
                            faults.check_step(step_count + 1)
                            xg = faults.poison_batch(xg, step_count + 1)
                        rec.profile_tick(step_count)
                        rng, sub = jax.random.split(rng)
                        # sentinel cadence: every nfreq-th step runs the
                        # numerics variant of the SAME compiled step
                        # (extra scalar outputs; obs/numerics.py) — the
                        # scalars drain with the loss, no host sync here
                        state, metrics = engine.train_step(
                            state, xg, yg, sub,
                            numerics=bool(nfreq)
                            and (step_count + 1) % nfreq == 0,
                        )
                        step_count += 1
                        epoch_steps += 1
                        dispatch_images.append(batch)
                        # liveness first (watchdog/heartbeat track
                        # dispatched progress; a hang stops dispatches
                        # within `depth` steps), then the row + step
                        # timing via the dispatcher's drain (step
                        # N-depth+1 while this step runs) — the per-step
                        # host round trip lives ONLY there
                        obs.on_step(step_count)
                        disp.push(step_count, metrics, n_images=batch)
                        # periodic exchange (EASGD avg_freq; reference: worker
                        # loop calling exchanger.exchange() — recorded as 'comm')
                        if engine.exchange_every and step_count % engine.exchange_every == 0:
                            # exchange boundary: drain in-flight metrics
                            # first so the comm bracket below times the
                            # collective, not K backlogged steps
                            disp.flush()
                            rec.start("comm")
                            state = engine.exchange(state)
                            # sync on a leaf of the exchanged state: without it
                            # the bracket measures only async dispatch and the
                            # collective's real cost bleeds into the next
                            # wait/step brackets
                            cdt = rec.end(
                                "comm", sync=jax.tree_util.tree_leaves(state)[0]
                            )
                            # the comm gauge's denominator includes the
                            # exchange's wall time on the steps that pay
                            # it (amortized bytes / local-only time would
                            # report gbps above the physical link)
                            obs.note_step_seconds(
                                (disp.last_step_seconds or 0.0) + cdt
                            )
                        rec.start("wait")
                        if max_steps and step_count >= max_steps:
                            break
                    # credit the epoch-tail wait (see the fused path)
                    disp.note_wait(rec.end("wait"))
                disp.flush()
                rec.end_epoch(epoch, n_images=epoch_steps * batch)

            train_loop_s += time.perf_counter() - t_loop0

            # validation (reference: per-epoch val loop on the worker/server)
            val_accum: Optional[dict] = None
            n_val = 0
            rec.start("eval")
            for vx, vy in data.val_epoch(vbatch, part=vpart):
                vm = engine.eval_step(state, *place((vx, vy)))
                # device-side accumulation: the adds dispatch async and
                # the ONE D2H for the whole val epoch happens below —
                # the old per-batch float(v) was a hidden host round
                # trip per val batch (the same tax the train loop paid).
                # Accumulate in float32 regardless of the metric dtype
                # (the old host sum was float64; low-precision metrics
                # would drift far worse summed in their own dtype)
                vm = jax.tree_util.tree_map(
                    lambda a: jnp.asarray(a, jnp.float32), vm
                )
                val_accum = (
                    vm if val_accum is None
                    else jax.tree_util.tree_map(jnp.add, val_accum, vm)
                )
                n_val += 1
            rec.end(
                "eval",
                sync=None if val_accum is None
                else jax.tree_util.tree_leaves(val_accum)[0],
            )
            if n_val:
                val_metrics = {k: float(v) / n_val for k, v in val_accum.items()}
                rec.val_metrics(epoch, val_metrics)
                summary["val"] = val_metrics
                # a non-finite val metric is an anomaly even when the
                # sentinel cadence skipped the poisoning train step
                obs.check_val_metrics(epoch, step_count, val_metrics)

            if ckpt_dir and (epoch + 1) % ckpt_every_epochs == 0:
                rec.start("checkpoint")
                if ckpt_writer is not None:
                    # overlapped with the next epoch's steps; ordering +
                    # durability enforced by the writer (drained in the
                    # finally below before the summary returns) — this
                    # bracket times only the enqueue; the real write is
                    # spanned inside utils/checkpoint.py on its thread
                    ckpt_writer.save(ckpt_dir, state, step_count, rng=rng,
                                     extra_meta=_save_meta(),
                                     topology=topo_meta)
                else:
                    sync_save(ckpt_dir, state, step_count, rng=rng,
                              extra_meta=_save_meta(), topology=topo_meta)
                rec.end("checkpoint")
                last_ckpt_step = step_count
                if faults is not None:
                    # post-save storage mutations (ckpt_truncate /
                    # bitrot / partial_set): mangle the newest COMMITTED
                    # checkpoint the way torn writes / at-rest bit-rot /
                    # a lost shard file would (the async save must be
                    # durable first, or the PREVIOUS file would be the
                    # one mutated) — latest_checkpoint(verify=True) and
                    # the scrubber must absorb them
                    due = faults.storage_mutations_due(step_count)
                    if due:
                        if ckpt_writer is not None:
                            ckpt_writer.wait()
                        for spec in due:
                            faults.apply_storage_mutation(spec, ckpt_dir)
            rec.save()
            obs.snapshot(step=step_count)  # epoch-boundary metrics snapshot
            summary["epochs"].append(epoch)
            if max_steps and step_count >= max_steps:
                break
            epoch += 1
          except RollbackRequested as rb:
            # --on-anomaly rollback: restore the newest VERIFIED
            # checkpoint and keep training. The dispatcher's in-flight
            # entries belong to steps the restore is about to erase —
            # discard them, never drain (draining would re-run anomaly
            # detection on the very rows that fired). With the budget
            # exhausted, no ckpt_dir, or nothing verified on disk, the
            # raise stands and rollback degrades to halt semantics.
            disp.discard()
            if rollback_budget_left <= 0 or not ckpt_dir:
                raise
            if ckpt_writer is not None:
                try:
                    ckpt_writer.wait()  # the pre-anomaly boundary save
                except Exception as e:  # noqa: BLE001
                    print(f"checkpoint writer failed before rollback "
                          f"(suppressed): {e!r}", flush=True)
            path = latest_checkpoint(ckpt_dir, verify=True)
            if n_proc > 1:
                # same agreement guard as the resume path: every
                # controller must restore the SAME step (an NFS
                # attribute cache or a short sharded set can make one
                # rank resolve an older checkpoint) or the lockstep
                # SPMD replay diverges/deadlocks silently
                from jax.experimental import multihost_utils

                steps_seen = np.asarray(
                    multihost_utils.process_allgather(
                        np.int64(checkpoint_step(path))
                    )
                ).reshape(-1)
                if not np.all(steps_seen == steps_seen[0]):
                    raise RuntimeError(
                        f"controller processes resolved different "
                        f"rollback checkpoints {steps_seen.tolist()} "
                        f"(this is process {jax.process_index()}): "
                        f"ckpt_dir={ckpt_dir!r} views disagree"
                    ) from rb
            if path is None:
                raise
            rollback_budget_left -= 1
            rollbacks += 1
            restored, saved_rng = load_checkpoint(path, state)
            state = _place_restored(restored)
            if saved_rng is not None:
                rng = saved_rng
            step_count = engine.get_step(state)
            last_ckpt_step = step_count
            # replay from the restored boundary; the per-step path
            # skips the anomalous step's batch window when it gets
            # there (fused dispatch replays without skipping: transient
            # faults clear on replay, persistent ones exhaust the
            # budget)
            skip_from_step = (
                rb.step if (rollback_skip and fuse == 1) else None
            )
            skip_data_batches = 0
            # position by BATCHES CONSUMED in the restored timeline:
            # the checkpoint's meta records the batches earlier
            # rollbacks skipped before it was written — skips after it
            # are erased with the state they fed
            from theanompi_tpu.utils.checkpoint import read_checkpoint_meta

            skipped_steps_total = int(
                read_checkpoint_meta(path).get("skipped_batches", 0)
            )
            consumed = step_count + skipped_steps_total
            epoch = consumed // steps_per_epoch
            skip_batches = consumed % steps_per_epoch
            obs.note_rollback(rb.step, step_count, rollback_budget_left,
                              skipped=int(rollback_skip) if fuse == 1 else 0)
            print(
                f"[rank {jax.process_index()}] anomaly rollback: restored "
                f"{path} at step {step_count} (anomaly at step {rb.step}; "
                f"budget left {rollback_budget_left})",
                flush=True,
            )
          except Preempted:
            # SIGTERM grace: persist what we have — drain the in-flight
            # rows, make any async save durable, write a final
            # checkpoint at the current step, and mark the run
            # resumable so the supervisor's next invocation picks it
            # up. The re-raise unwinds through the finally below
            # (recorder/obs close) and reaches the CLI/supervisor as a
            # clean, resumable exit.
            try:
                disp.flush()
            except NumericsAnomaly as e:
                # the drained tail held the FIRST detection of an
                # anomaly: the live state is poisoned — it must NOT
                # become the newest resumable checkpoint (quarantine
                # invariant; the flag also disarms the finally's crash
                # save); the marker still lands, so the next invocation
                # resumes from the last GOOD checkpoint
                _state_poisoned = True
                print(f"numerics anomaly surfaced during preemption "
                      f"flush; skipping the final checkpoint: {e!r}",
                      flush=True)
            except Exception as e:  # noqa: BLE001
                print(f"dispatch flush failed during preemption "
                      f"(suppressed): {e!r}", flush=True)
            if ckpt_dir:
                if ckpt_writer is not None:
                    # suppressed like the rollback path: a failed
                    # BACKGROUND write must not replace the clean
                    # Preempted exit (the sync save below still runs)
                    try:
                        ckpt_writer.wait()
                    except Exception as e:  # noqa: BLE001
                        print(f"checkpoint writer failed during "
                              f"preemption (suppressed): {e!r}",
                              flush=True)
                if step_count != last_ckpt_step and not _state_poisoned:
                    # best-effort like the crash-save path: a failed
                    # final save (quota, transient NFS) must not
                    # replace the clean Preempted exit — the last
                    # boundary checkpoint is still a valid resume
                    # point, and the marker below records it
                    try:
                        sync_save(ckpt_dir, state, step_count, rng=rng,
                                  extra_meta=_save_meta(),
                                  topology=topo_meta)
                        last_ckpt_step = step_count
                    except Exception as e:  # noqa: BLE001
                        print(f"final preemption checkpoint failed "
                              f"(suppressed; marker will point at step "
                              f"{last_ckpt_step}): {e!r}", flush=True)
                if jax.process_index() == 0:
                    write_resumable_marker(ckpt_dir, last_ckpt_step,
                                           "sigterm")
            raise

    finally:
        # best-effort drain of in-flight step metrics BEFORE the
        # recorder closes: an exception mid-epoch with dispatch_depth>1
        # leaves up to depth-1 executed steps buffered — their rows are
        # exactly the pre-crash tail a post-mortem reads, and sync mode
        # would have persisted them (clean exits reach here with the
        # buffer already empty: the boundary flushes ran). Suppressed on
        # failure: a poisoned device value must not mask the training
        # exception already propagating. SKIPPED when unwinding a
        # BaseException (KeyboardInterrupt/SystemExit): Ctrl-C on a
        # wedged collective is the canonical escape hatch, and the
        # flush's block_until_ready would never return — the recorder
        # and obs must still close so the process can exit.
        # ... and wrapped so a KeyboardInterrupt arriving DURING the
        # flush's device sync still reaches rec.close()/obs.close()
        # in the inner finally below.
        try:
            _exc = sys.exc_info()[0]
            if _exc is None or issubclass(_exc, Exception):
                try:
                    disp.flush()
                except NumericsAnomaly as e:
                    # first detection arrived in the unwinding flush:
                    # the state is poisoned — record that so the crash
                    # save below cannot quarantine-break (the anomaly
                    # itself stays suppressed; the original exception
                    # keeps propagating)
                    _state_poisoned = True
                    print(f"numerics anomaly surfaced during error-"
                          f"unwinding flush (suppressed): {e!r}",
                          flush=True)
                except Exception as e:  # noqa: BLE001
                    print(f"dispatch flush failed during error unwinding "
                          f"(suppressed): {e!r}", flush=True)
            if (
                _exc is not None
                and issubclass(_exc, Exception)
                and not issubclass(_exc, NumericsAnomaly)
                and not _state_poisoned
                and ckpt_dir
                and step_count > last_ckpt_step
            ):
                # crash-path durability: an exception with an async save
                # still in flight must not lose the newest state — wait()
                # the pending write, then attempt ONE final synchronous
                # checkpoint at the crash step (the disp.flush() pattern
                # above, applied to state). Best-effort: a poisoned
                # device value can fail the gather, and that failure
                # must not mask the training exception propagating.
                # Skipped for NumericsAnomaly unwinds (halt / rollback
                # budget exhausted): that state IS the anomalous one —
                # making it the newest resumable checkpoint would poison
                # every future resume; the flight dump's state/ capture
                # already preserves it for triage, quarantined from the
                # resume chain.
                try:
                    if ckpt_writer is not None:
                        ckpt_writer.wait()
                    sync_save(ckpt_dir, state, step_count, rng=rng,
                              extra_meta=_save_meta(), topology=topo_meta)
                    last_ckpt_step = step_count
                    print(
                        f"[rank {jax.process_index()}] crash checkpoint "
                        f"saved at step {step_count}",
                        flush=True,
                    )
                except Exception as e:  # noqa: BLE001
                    print(f"crash checkpoint failed during error "
                          f"unwinding (suppressed): {e!r}", flush=True)
        finally:
            try:
                if ckpt_writer is not None:
                    # may re-raise a failed background write — but never
                    # let that replace a training exception already
                    # propagating (the original would survive only as
                    # __context__)
                    if sys.exc_info()[0] is not None:
                        try:
                            ckpt_writer.close()
                        except Exception as e:  # noqa: BLE001
                            print(
                                f"checkpoint writer failed during error "
                                f"unwinding (suppressed): {e!r}",
                                flush=True,
                            )
                    else:
                        ckpt_writer.close()
            finally:
                try:
                    rec.close()  # trace + JSONL must close even then
                finally:
                    # final snapshot + span summary + health-thread
                    # shutdown; after rec.close() so the recorder's last
                    # emissions land
                    try:
                        obs.close()
                    finally:
                        try:
                            if fleet_exporter is not None:
                                # server down + tailer joined; the last
                                # merged view stays in fleet.jsonl for
                                # post-mortem `tmpi top --once`
                                try:
                                    fleet_exporter.stop()
                                except Exception as e:  # noqa: BLE001
                                    print(f"fleet exporter stop failed "
                                          f"(suppressed): {e!r}",
                                          flush=True)
                            if faults is not None:
                                # uninstall the process-global writer
                                # shim (installed where faults armed) —
                                # AFTER the crash/preempt saves above,
                                # so a due write fault can still hit
                                # them like any other save
                                from theanompi_tpu.utils.checkpoint import (
                                    set_write_fault_hook as _clear_wfh,
                                )

                                _clear_wfh(None)
                            if scrubber is not None:
                                scrubber.stop()
                        finally:
                            if _prev_sigterm is not None:
                                # restore the caller's SIGTERM disposition
                                # (tests and stacked runs share the process)
                                signal.signal(signal.SIGTERM, _prev_sigterm)
    # reached only on success: a completed run consumed any resumable
    # marker a preempted predecessor left — otherwise a later SUPERVISED
    # run reusing this ckpt_dir would silently flip into resume mode
    # off the stale marker (the supervisor clears its own, but plain
    # --resume completions must too)
    if ckpt_dir and jax.process_index() == 0:
        clear_resumable_marker(ckpt_dir)
    summary["steps"] = step_count
    # device-truth step counter (host-fetched AFTER training): the host
    # loop counts dispatches, the device counts executions — a tunneled
    # backend that silently drops work (tools/repro_tunnel_fault.py)
    # shows up as a mismatch here
    summary["device_steps"] = engine.get_step(state)
    # dispatch-pipeline accounting: how much of the train loop the host
    # spent BLOCKED on device syncs (the per-step tax dispatch_depth>1
    # removes; bench.py reports this as host_blocked_frac)
    summary["dispatch_depth"] = disp.depth
    # numerics flight recorder: anomalies seen at drain time (0 when
    # numerics is off) — a nonzero count with policy 'record'/'dump' is
    # the "check the triage bundle" signal for sweep drivers
    summary["anomalies"] = obs.anomaly_count
    # anomaly-rollback accounting (--on-anomaly rollback): restores of
    # the last verified checkpoint, and the data batches the replay
    # skipped at the anomalous steps
    summary["rollbacks"] = rollbacks
    summary["skipped_steps"] = skipped_steps_total
    if ckpt_writer is not None:
        # boundary saves the ENOSPC-safe async writer absorbed (torn
        # attempt, chain intact — utils/checkpoint.AsyncCheckpointer):
        # nonzero means the checkpoint cadence silently degraded, which
        # a success summary must not hide
        summary["ckpt_storage_failures"] = ckpt_writer.storage_failures
    summary["host_blocked_s"] = round(disp.host_blocked_s, 6)
    summary["train_loop_s"] = round(train_loop_s, 6)
    summary["host_blocked_frac"] = (
        round(min(1.0, disp.host_blocked_s / train_loop_s), 6)
        if train_loop_s > 0 else None
    )
    k_recent = min(50, len(dispatch_images))
    t_recent = rec.mean_time("step", k_recent)
    summary["images_per_sec"] = (
        (sum(dispatch_images[-k_recent:]) / k_recent) / t_recent
        if (k_recent and t_recent) else 0.0
    )
    if obs.cost is not None and summary["images_per_sec"]:
        # achieved utilization from the SHARED cost model (the same
        # numbers the live gauges carry; bench e2e/codec-sweep read
        # these off the summary): per-step seconds recovered from the
        # throughput ledger so fused dispatches amortize correctly
        _sps = batch / summary["images_per_sec"]
        _mfu = obs.cost.mfu(_sps)
        summary["mfu"] = round(_mfu, 4) if _mfu is not None else None
        summary["tflops_per_sec"] = round(obs.cost.flops / _sps / 1e12, 6)
    if return_recorder:
        summary["recorder"] = rec
    return summary
