"""The training driver: epoch loop, validation, checkpointing.

Rebuild of the reference's sync-rule worker processes (reference: BSP
``Worker.run`` epoch/iteration loop with data wait -> train_iter ->
exchange -> record, per-epoch validation, ``adjust_hyperp``, rank-0
checkpoint; SURVEY.md §3.2, §2.1 "Sync-rule drivers"). One driver covers
all rules — the rule picks which compiled step function it runs:

- ``bsp``:   BSP step over a ``('data',)`` mesh (parallel/bsp.py)
- ``easgd``: elastic-averaging step over a worker mesh (parallel/easgd.py)
- ``gosgd``: gossip step (parallel/gosgd.py)

There are no worker processes to manage: the mesh is the workers, and
the driver is plain single-controller Python around jitted SPMD steps
(multi-controller runs call this same function once per host).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from theanompi_tpu.data import get_dataset
from theanompi_tpu.data.loader import PrefetchLoader
from theanompi_tpu.models.contract import Model
from theanompi_tpu.parallel import make_mesh
from theanompi_tpu.parallel.mesh import put_global_batch
from theanompi_tpu.utils import (
    Recorder,
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
)


def run_training(
    rule: str = "bsp",
    model_cls: type[Model] = None,
    devices=None,
    *,
    strategy: str = "psum",
    n_epochs: Optional[int] = None,
    max_steps: Optional[int] = None,
    dataset: Optional[str] = None,
    dataset_kwargs: Optional[dict] = None,
    recipe_overrides: Optional[dict] = None,
    seed: int = 0,
    save_dir: Optional[str] = None,
    ckpt_dir: Optional[str] = None,
    ckpt_every_epochs: int = 1,
    resume: bool = False,
    print_freq: int = 40,
    prefetch_depth: int = 2,
    # rule-specific kwargs (EASGD avg_freq etc.) forwarded to the rule's
    # step builder
    **rule_kwargs: Any,
) -> dict:
    """Train ``model_cls`` under a sync rule; returns a summary dict.

    The recipe is the model's own (reference: model-owned hyperparams,
    SURVEY.md §5.6); ``recipe_overrides`` is the session's override hook.
    """
    if model_cls is None:
        raise ValueError("model_cls is required")

    recipe = model_cls.default_recipe()
    if recipe_overrides:
        recipe = recipe.replace(**recipe_overrides)
    model = model_cls(recipe)

    data = get_dataset(dataset or recipe.dataset, **(dataset_kwargs or {}))
    batch = recipe.batch_size
    steps_per_epoch = data.n_train_batches(batch)
    if steps_per_epoch == 0:
        raise ValueError(
            f"dataset has {data.n_train} train examples < batch size {batch}"
        )
    n_epochs = n_epochs if n_epochs is not None else recipe.n_epochs

    mesh = make_mesh(devices)
    n_dev = mesh.devices.size
    if batch % n_dev:
        raise ValueError(f"global batch {batch} not divisible by {n_dev} devices")
    vbatch = recipe.val_batch_size or batch
    if vbatch % n_dev:
        raise ValueError(f"val batch {vbatch} not divisible by {n_dev} devices")

    rule = rule.lower()
    if rule == "bsp":
        from theanompi_tpu.parallel.bsp import BSPEngine

        if rule_kwargs:
            raise ValueError(
                f"rule 'bsp' got unexpected options {sorted(rule_kwargs)} "
                "(avg_freq/alpha/p_push apply to EASGD/GoSGD only)"
            )
        engine = BSPEngine(
            model, mesh, steps_per_epoch=steps_per_epoch, strategy=strategy
        )
    elif rule == "easgd":
        from theanompi_tpu.parallel.easgd import EASGDEngine

        if strategy != "psum":
            raise ValueError("strategy applies to the BSP rule only")
        engine = EASGDEngine(model, mesh, steps_per_epoch=steps_per_epoch, **rule_kwargs)
    elif rule == "gosgd":
        from theanompi_tpu.parallel.gosgd import GOSGDEngine

        if strategy != "psum":
            raise ValueError("strategy applies to the BSP rule only")
        engine = GOSGDEngine(model, mesh, steps_per_epoch=steps_per_epoch, **rule_kwargs)
    else:
        raise ValueError(f"unknown rule {rule!r}; available: bsp, easgd, gosgd")

    rec = Recorder(
        rank=jax.process_index(), print_freq=print_freq, save_dir=save_dir,
        run_name=f"{model.name}_{rule}",
    )
    rng = jax.random.PRNGKey(seed)
    state = engine.init_state(rng)
    start_epoch = 0
    if resume and ckpt_dir:
        path = latest_checkpoint(ckpt_dir)
        if path:
            restored, saved_rng = load_checkpoint(path, state)
            state = jax.tree_util.tree_map(jnp.asarray, restored)
            if saved_rng is not None:
                rng = jnp.asarray(saved_rng)
            start_epoch = engine.get_step(state) // steps_per_epoch
            print(f"resumed from {path} at step {engine.get_step(state)}", flush=True)

    def place(b):
        x, y = b
        return (
            put_global_batch(mesh, jnp.asarray(x)),
            put_global_batch(mesh, jnp.asarray(y)),
        )

    summary: dict = {"epochs": [], "rule": rule, "model": model.name}
    step_count = engine.get_step(state)
    # Mid-epoch resume (checkpoint written after a max_steps truncation):
    # fast-forward past the batches the restored step count already
    # consumed, so data order and epoch accounting stay exact.
    skip_batches = step_count % steps_per_epoch
    for epoch in range(start_epoch, n_epochs):
        rec.start_epoch()
        epoch_steps = 0
        loader = PrefetchLoader(
            data.train_epoch(epoch, batch, seed=seed), place, depth=prefetch_depth
        )
        rec.start("wait")
        for xg, yg in loader:
            if skip_batches:
                skip_batches -= 1
                continue
            rec.end("wait")
            rng, sub = jax.random.split(rng)
            rec.start("step")
            state, metrics = engine.train_step(state, xg, yg, sub)
            rec.end("step", sync=metrics["loss"])
            step_count += 1
            epoch_steps += 1
            # periodic exchange (EASGD avg_freq; reference: worker loop
            # calling exchanger.exchange() — recorded as 'comm')
            if engine.exchange_every and step_count % engine.exchange_every == 0:
                rec.start("comm")
                state = engine.exchange(state)
                rec.end("comm")
            rec.train_metrics(step_count, metrics, n_images=batch)
            rec.start("wait")
            if max_steps and step_count >= max_steps:
                loader.close()
                break
        rec.end("wait")
        rec.end_epoch(epoch, n_images=epoch_steps * batch)

        # validation (reference: per-epoch val loop on the worker/server)
        val_accum: dict[str, float] = {}
        n_val = 0
        for vx, vy in data.val_epoch(vbatch):
            vm = engine.eval_step(state, *place((vx, vy)))
            for k, v in vm.items():
                val_accum[k] = val_accum.get(k, 0.0) + float(v)
            n_val += 1
        if n_val:
            val_metrics = {k: v / n_val for k, v in val_accum.items()}
            rec.val_metrics(epoch, val_metrics)
            summary["val"] = val_metrics

        if ckpt_dir and (epoch + 1) % ckpt_every_epochs == 0:
            save_checkpoint(ckpt_dir, state, step_count, rng=rng)
        rec.save()
        summary["epochs"].append(epoch)
        if max_steps and step_count >= max_steps:
            break

    rec.close()
    summary["steps"] = step_count
    summary["images_per_sec"] = (
        batch / rec.mean_time("step", 50) if rec.mean_time("step", 50) else 0.0
    )
    return summary
