"""Session API: sync-rule classes mirroring the reference's launcher.

Reference (``tmpi`` / ``launch_session.py``, SURVEY.md §1 L7, §3.1): the
user constructs a rule object and calls
``rule.init(devices, modelfile, modelclass)`` then ``rule.wait()``; the
reference built an ``mpirun`` command line spawning one OS process per
GPU. On TPU there is no mpirun and no process-per-device: ``init``
resolves the model class, builds a ``jax.sharding.Mesh`` over the
requested devices, and starts ONE SPMD training driver (in-process, or
in a background thread so ``wait()`` keeps the reference's semantics).
"""

from __future__ import annotations

import importlib
import importlib.util
import threading
from typing import Any, Optional, Sequence, Union


def resolve_model(modelfile: str, modelclass: str):
    """Import ``modelclass`` from ``modelfile``.

    The reference passed a python file path + class name over argv to the
    workers (reference: ``launch_session.py``); here modelfile is a zoo
    short name (``wrn``, ``alexnet``, ...), a module path
    (``theanompi_tpu.models.model_zoo.wrn``), or a ``.py`` file path.
    """
    from theanompi_tpu.models import MODEL_REGISTRY, get_model

    if modelfile in MODEL_REGISTRY:
        modelfile = MODEL_REGISTRY[modelfile][0]
    if modelfile.endswith(".py"):
        spec = importlib.util.spec_from_file_location("_tmpi_model", modelfile)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    else:
        mod = importlib.import_module(modelfile)
    return getattr(mod, modelclass)


class SyncRule:
    """Base rule: subclasses set ``rule_name`` and default driver kwargs."""

    rule_name: str = "base"

    def __init__(self, **rule_kwargs):
        self.rule_kwargs = rule_kwargs
        self._thread: Optional[threading.Thread] = None
        self._result: Any = None
        self._error: Optional[BaseException] = None

    def init(
        self,
        devices: Union[int, Sequence, None] = None,
        modelfile: str = "theanompi_tpu.models.model_zoo.wrn",
        modelclass: str = "WRN",
        blocking: bool = False,
        **overrides,
    ):
        """Start training. ``devices``: device count (first N), an explicit
        device list, or None for all. With ``blocking=False`` (reference
        semantics) training runs in a background thread and ``wait()``
        joins it."""
        from theanompi_tpu.launch.worker import run_training

        self._thread = None
        self._result = None
        self._error = None
        model_cls = resolve_model(modelfile, modelclass)
        kwargs = {**self.rule_kwargs, **overrides}

        def _run():
            try:
                self._result = run_training(
                    rule=self.rule_name, model_cls=model_cls, devices=devices, **kwargs
                )
            except BaseException as e:  # surfaced in wait()
                self._error = e

        if blocking:
            _run()
            if self._error is not None:
                raise self._error
            return self._result
        self._thread = threading.Thread(target=_run, name=f"tmpi-{self.rule_name}", daemon=True)
        self._thread.start()
        return self

    def wait(self):
        """Block until training finishes (reference: ``rule.wait()`` blocked
        on the mpirun child)."""
        if self._thread is not None:
            self._thread.join()
        if self._error is not None:
            raise self._error
        return self._result


class BSP(SyncRule):
    """Bulk-synchronous data parallelism: per-step gradient allreduce
    (reference: ``lib/exchanger.py`` — ``BSP_Exchanger``)."""

    rule_name = "bsp"


class EASGD(SyncRule):
    """Elastic-averaging SGD: workers + center replica, periodic elastic
    exchange (reference: ``lib/exchanger.py`` — ``EASGD_Exchanger``)."""

    rule_name = "easgd"


class GOSGD(SyncRule):
    """Gossip SGD: randomized peer-to-peer weighted averaging
    (reference: ``lib/exchanger.py`` — ``GOSGD_Exchanger``)."""

    rule_name = "gosgd"
