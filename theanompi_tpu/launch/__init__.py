"""Launch layer: session API + worker training drivers.

TPU-native replacement for the reference's ``tmpi`` CLI and
``launch_session.py`` session scripts (SURVEY.md §1 L7).
"""
