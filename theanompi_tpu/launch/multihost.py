"""Multi-controller process launcher — the ``mpirun`` equivalent.

Reference: the launcher built an ``mpirun -n N python worker.py`` command
line with per-rank device env (``lib/base.py`` + rule ``init()``;
SURVEY.md §3.1). On TPU pods each HOST already runs one controller
process (started by the pod runtime / GKE / SLURM, picked up via
``TMPI_AUTO_INIT=1``), so a production launcher is usually unnecessary.
This module provides the same capability for the cases that need it:

- **Local simulation**: N controller processes on one machine, each
  owning a slice of virtual CPU devices — the multi-host integration
  test bed (``--xla_force_host_platform_device_count``), usable by any
  developer without a pod.
- **Ad-hoc clusters**: print/spawn the env each host needs.

``spawn_local(n_proc, argv)`` forks this Python interpreter N times with
``TMPI_*`` env set; rank 0's output streams through; returns exit codes.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import Optional, Sequence


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def controller_env(
    process_id: int,
    num_processes: int,
    coordinator: str,
    devices_per_proc: Optional[int] = None,
    platform: Optional[str] = None,
) -> dict:
    """The env one controller process needs to join the world."""
    env = {
        "TMPI_COORDINATOR": coordinator,
        "TMPI_NUM_PROCESSES": str(num_processes),
        "TMPI_PROCESS_ID": str(process_id),
    }
    if devices_per_proc is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={devices_per_proc}"
        ).strip()
    if platform is not None:
        # Plain JAX_PLATFORMS can be clobbered by site hooks that run at
        # interpreter start (seen with the axon TPU plugin); the CLI also
        # applies TMPI_FORCE_PLATFORM via jax.config before backend init.
        env["JAX_PLATFORMS"] = platform
        env["TMPI_FORCE_PLATFORM"] = platform
    return env


def spawn_local(
    n_proc: int,
    argv: Sequence[str],
    devices_per_proc: Optional[int] = None,
    coordinator: Optional[str] = None,
    timeout: Optional[float] = None,
) -> list[int]:
    """Run ``python -m/argv`` as ``n_proc`` cooperating controller
    processes on this machine (CPU simulation of a multi-host pod).
    Streams rank-0 output; captures other ranks to buffers printed on
    failure. Returns the per-rank exit codes.
    """
    coordinator = coordinator or f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(n_proc):
        env = dict(os.environ)
        env.update(
            controller_env(
                pid, n_proc, coordinator,
                devices_per_proc=devices_per_proc,
                platform="cpu" if devices_per_proc is not None else None,
            )
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, *argv],
                env=env,
                stdout=None if pid == 0 else subprocess.PIPE,
                stderr=None if pid == 0 else subprocess.STDOUT,
                text=pid != 0,
            )
        )
    codes = []
    for pid, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        codes.append(p.returncode)
        if p.returncode != 0 and pid != 0 and out:
            sys.stderr.write(f"--- rank {pid} output ---\n{out}\n")
    return codes
