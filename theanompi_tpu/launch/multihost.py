"""Multi-controller process launcher — the ``mpirun`` equivalent.

Reference: the launcher built an ``mpirun -n N python worker.py`` command
line with per-rank device env (``lib/base.py`` + rule ``init()``;
SURVEY.md §3.1). On TPU pods each HOST already runs one controller
process (started by the pod runtime / GKE / SLURM, picked up via
``TMPI_AUTO_INIT=1``), so a production launcher is usually unnecessary.
This module provides the same capability for the cases that need it:

- **Local simulation**: N controller processes on one machine, each
  owning a slice of virtual CPU devices — the multi-host integration
  test bed (``--xla_force_host_platform_device_count``), usable by any
  developer without a pod.
- **Ad-hoc clusters**: print/spawn the env each host needs.

``spawn_local(n_proc, argv)`` forks this Python interpreter N times with
``TMPI_*`` env set; rank 0's output streams through; returns exit codes.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import Optional, Sequence


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def controller_env(
    process_id: int,
    num_processes: int,
    coordinator: str,
    devices_per_proc: Optional[int] = None,
    platform: Optional[str] = None,
) -> dict:
    """The env one controller process needs to join the world."""
    env = {
        "TMPI_COORDINATOR": coordinator,
        "TMPI_NUM_PROCESSES": str(num_processes),
        "TMPI_PROCESS_ID": str(process_id),
    }
    if devices_per_proc is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={devices_per_proc}"
        ).strip()
    if platform is not None:
        # Plain JAX_PLATFORMS can be clobbered by site hooks that run at
        # interpreter start (seen with the axon TPU plugin); the CLI also
        # applies TMPI_FORCE_PLATFORM via jax.config before backend init.
        env["JAX_PLATFORMS"] = platform
        env["TMPI_FORCE_PLATFORM"] = platform
    return env


def spawn_local(
    n_proc: int,
    argv: Sequence[str],
    devices_per_proc: Optional[int] = None,
    coordinator: Optional[str] = None,
    timeout: Optional[float] = None,
    failure_grace: float = 15.0,
) -> list[int]:
    """Run ``python -m/argv`` as ``n_proc`` cooperating controller
    processes on this machine (CPU simulation of a multi-host pod).
    Streams rank-0 output; captures other ranks to buffers printed on
    failure. Returns the per-rank exit codes.

    Supervision: children are POLLED, not waited-on in rank order — if
    any rank dies non-zero while the others block in a collective, the
    survivors get ``failure_grace`` seconds to exit on their own, then
    are killed, and the failed rank's buffered output is printed.
    ``timeout`` (None = unbounded, the default: training runs are long)
    caps total wall clock and raises ``TimeoutExpired``.
    """
    import threading
    import time as _time

    coordinator = coordinator or f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(n_proc):
        env = dict(os.environ)
        env.update(
            controller_env(
                pid, n_proc, coordinator,
                devices_per_proc=devices_per_proc,
                platform="cpu" if devices_per_proc is not None else None,
            )
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, *argv],
                env=env,
                stdout=None if pid == 0 else subprocess.PIPE,
                stderr=None if pid == 0 else subprocess.STDOUT,
                text=pid != 0,
            )
        )

    # drain non-rank-0 pipes concurrently (a full pipe buffer would
    # deadlock the child)
    outputs: dict[int, str] = {}
    drains = []
    for pid, p in enumerate(procs):
        if p.stdout is not None:
            t = threading.Thread(
                target=lambda pid=pid, p=p: outputs.__setitem__(pid, p.stdout.read()),
                name=f"tmpi-mh-drain-p{pid}", daemon=True,
            )
            t.start()
            drains.append(t)

    deadline = (_time.monotonic() + timeout) if timeout else None

    def _kill_survivors():
        for p in procs:
            if p.poll() is None:
                p.kill()

    while True:
        rcs = [p.poll() for p in procs]
        if all(rc is not None for rc in rcs):
            break
        if any(rc not in (None, 0) for rc in rcs):
            grace_end = _time.monotonic() + failure_grace
            while any(p.poll() is None for p in procs) and _time.monotonic() < grace_end:
                _time.sleep(0.2)
            _kill_survivors()
            break
        if deadline is not None and _time.monotonic() > deadline:
            _kill_survivors()
            for p in procs:  # reap — no zombie children on the timeout path
                p.wait()
            for t in drains:
                t.join(timeout=5)
            raise subprocess.TimeoutExpired([sys.executable, *argv], timeout)
        _time.sleep(0.2)

    for p in procs:
        p.wait()
    for t in drains:
        t.join(timeout=5)
    codes = [p.returncode for p in procs]
    for pid, rc in enumerate(codes):
        if rc != 0 and outputs.get(pid):
            sys.stderr.write(f"--- rank {pid} (exit {rc}) output ---\n{outputs[pid]}\n")
    return codes
