"""Metrics registry: labeled counters / gauges / histograms.

The reference's only metric sink was the recorder's pickled lists
(reference: ``lib/recorder.py``; SURVEY.md §5.1). This registry is the
process-wide home for OPERATIONAL telemetry — step counters, comm-bytes
accounting (obs/comm.py), achieved interconnect GB/s, stall/heartbeat
state — kept separate from the Recorder's training curves (loss/error
history), which remain the Recorder's job. Two expositions:

- **Prometheus text format** to a file (``write_prometheus``): standard
  `# HELP`/`# TYPE` + `name{label="v"} value` lines, scrapeable by a
  node-exporter-style sidecar on a pod host;
- **JSONL snapshots** (``snapshot()``): one self-contained
  ``{"kind": "metrics", "t": ..., "step": ..., "metrics": {...}}``
  object per line, the same machine-readable stream the Recorder
  emits — downstream parsing (bench.py, tools/plot_history.py,
  tools/check_obs_schema.py) reads one format for bench results and
  training telemetry alike.

``REGISTRY`` is the process-wide default; the training driver builds a
fresh ``MetricsRegistry`` per run so tests and stacked runs in one
process never bleed samples into each other.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import tempfile
import threading
import time
from typing import Iterable, Optional, Sequence

_LabelKey = tuple  # sorted ((k, v), ...) pairs — the per-series dict key

# default histogram buckets: seconds-scale latencies (data_wait / step /
# checkpoint brackets span ~100us..minutes)
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _label_key(labels: dict) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: _LabelKey) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


def atomic_write_text(path: str, text: str, suffix: str = ".tmp") -> str:
    """tmp + rename write shared by every obs file that gets REPLACED
    rather than appended (Prometheus exposition, heartbeat, stall
    report): a reader never sees a torn file, and a failed write never
    leaves a stray tmp behind."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=suffix)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return path


class _Metric:
    """One named metric family; per-label-set series live in ``_series``."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._series: dict[_LabelKey, float] = {}
        self._lock = threading.Lock()

    # -- exposition ---------------------------------------------------------
    def samples(self) -> Iterable[tuple[str, float]]:
        """``(suffix_with_labels, value)`` pairs for exposition."""
        with self._lock:
            for key, value in sorted(self._series.items()):
                yield _label_str(key), value

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0.0)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def add(self, amount: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: each ``le``
    bucket counts observations <= its bound, plus ``+Inf``/count/sum)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # per label-set: [bucket counts..., +Inf count], sum
        self._hist: dict[_LabelKey, tuple[list, float]] = {}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            counts, total = self._hist.get(
                key, ([0] * (len(self.buckets) + 1), 0.0)
            )
            counts[bisect.bisect_left(self.buckets, value)] += 1
            self._hist[key] = (counts, total + float(value))

    def samples(self):
        with self._lock:
            for key, (counts, total) in sorted(self._hist.items()):
                cum = 0
                for bound, c in zip(self.buckets, counts):
                    cum += c
                    yield (
                        f"_bucket{_label_str(key + (('le', repr(bound)),))}",
                        float(cum),
                    )
                cum += counts[-1]
                yield f"_bucket{_label_str(key + (('le', '+Inf'),))}", float(cum)
                yield f"_count{_label_str(key)}", float(cum)
                yield f"_sum{_label_str(key)}", total

    def snapshot_samples(self):
        """Compact form for JSONL snapshots: count/sum/mean only (the
        full bucket vector stays in the Prometheus exposition)."""
        with self._lock:
            for key, (counts, total) in sorted(self._hist.items()):
                n = sum(counts)
                yield f"_count{_label_str(key)}", float(n)
                yield f"_sum{_label_str(key)}", total
                if n:
                    yield f"_mean{_label_str(key)}", total / n

    def count(self, **labels) -> int:
        with self._lock:
            entry = self._hist.get(_label_key(labels))
            return sum(entry[0]) if entry else 0

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Estimated q-quantile (0..1) from the cumulative buckets —
        Prometheus ``histogram_quantile`` semantics: linear
        interpolation inside the bucket the target rank falls in, so
        the estimate's resolution is the bucket grid. Observations
        beyond the last finite bound clamp to it (an +Inf bucket has no
        upper edge to interpolate toward). None when nothing was
        observed. Serving reads p50/p99 latency off this
        (serve/engine.py's ``tmpi_serve_*`` histograms)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            entry = self._hist.get(_label_key(labels))
            if entry is None:
                return None
            counts = list(entry[0])
        n = sum(counts)
        if n == 0:
            return None
        target = q * n
        cum = 0
        for i, c in enumerate(counts[:-1]):
            prev = cum
            cum += c
            if cum >= target:
                lo = self.buckets[i - 1] if i else 0.0
                hi = self.buckets[i]
                if c == 0:
                    return hi
                return lo + (hi - lo) * (target - prev) / c
        return self.buckets[-1]  # rank lands in the +Inf bucket


class MetricsRegistry:
    """Get-or-create registry of metric families. Name collisions across
    kinds raise (a counter and a gauge sharing a name would corrupt the
    exposition); re-requesting the same (name, kind) returns the live
    metric, so call sites never coordinate creation."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help: str, **kwargs) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    # -- exposition ---------------------------------------------------------
    def to_prometheus(self) -> str:
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for suffix, value in m.samples():
                lines.append(f"{m.name}{suffix} {value}")
        return "\n".join(lines) + "\n"

    def write_prometheus(self, path: str) -> str:
        """Atomic write (tmp + rename): a scraper never reads a torn
        exposition."""
        return atomic_write_text(path, self.to_prometheus(),
                                 suffix=".prom.tmp")

    def snapshot(self, step: Optional[int] = None,
                 extra: Optional[dict] = None) -> dict:
        """One JSONL-ready snapshot object (schema:
        tools/check_obs_schema.py ``metrics``). Histograms export
        count/sum/mean; non-finite values are dropped (JSON has no
        Inf/NaN and a parser-breaking line defeats the point of a
        machine-readable stream)."""
        flat: dict[str, float] = {}
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            samples = (
                m.snapshot_samples() if isinstance(m, Histogram) else m.samples()
            )
            for suffix, value in samples:
                if isinstance(value, float) and not math.isfinite(value):
                    continue
                flat[m.name + suffix] = value
        rec = {"kind": "metrics", "t": time.time(), "metrics": flat}
        if step is not None:
            rec["step"] = int(step)
        if extra:
            rec.update(extra)
        return rec

    def emit_snapshot(self, fileobj, step: Optional[int] = None,
                      extra: Optional[dict] = None) -> dict:
        rec = self.snapshot(step=step, extra=extra)
        fileobj.write(json.dumps(rec) + "\n")
        fileobj.flush()
        return rec


def result_to_snapshot(result: dict, source: str = "bench") -> dict:
    """Re-express a bench.py-style result dict in the metrics-snapshot
    schema (numeric fields become ``<source>_<key>`` samples; strings
    ride along under ``labels``), so bench output and training telemetry
    share one JSONL format (ISSUE satellite: bench emission)."""
    reg = MetricsRegistry()
    labels = {}
    for k, v in result.items():
        if isinstance(v, bool) or v is None:
            labels[k] = str(v)
        elif isinstance(v, (int, float)) and math.isfinite(float(v)):
            reg.gauge(f"{source}_{k}").set(float(v))
        elif isinstance(v, str):
            labels[k] = v
        # nested dicts/lists (timing, scaling table, dispatch_sweep)
        # stay in the native bench line only — snapshot metrics are a
        # flat numeric map by schema (tools/check_obs_schema.py)
    return reg.snapshot(extra={"source": source, "labels": labels})


# process-wide default registry (the training driver uses a fresh
# per-run instance; this one serves ad-hoc/library callers)
REGISTRY = MetricsRegistry()
