"""Nestable trace spans with a per-rank JSONL span log.

The reference's whole trace story was the recorder's flat calc/comm
wall-clock brackets (reference: ``lib/recorder.py``; SURVEY.md §5.1).
Spans generalize that to a NESTABLE, named tree — ``checkpoint`` inside
``step``-adjacent driver code, ``h2d`` inside the prefetch producer
thread — written one JSON object per line as each span closes, plus a
run-end ``span_summary`` line with per-kind time fractions.

Span kinds used by the training stack (callers may add their own):
``data_wait``, ``h2d``, ``step``, ``grad_sync``, ``eval``,
``checkpoint`` — plus the nested ``checkpoint_gather`` /
``checkpoint_write`` sub-spans utils/checkpoint.py opens inside a save
(named apart so a synchronous save does not count the same wall time
twice under one kind). Schema: tools/check_obs_schema.py.

Fraction semantics: the summary's ``fractions`` divide per-kind
EXCLUSIVE top-level time by the recorder's open→close wall clock, and
count only spans opened on the OWNER thread (the driver). Owner-thread
depth-0 spans are sequential by construction, so the fractions sum to
<= 1.0 — the acceptance invariant a concurrent accounting (e.g. adding
the producer thread's overlapping ``h2d`` spans) could not honor.
Spans from other threads still appear as ``span`` lines and in
``totals_s``/``counts``; they are simply excluded from ``fractions``.

A module-level *current recorder* lets deep layers (utils/checkpoint.py,
data/loader.py) open spans without threading a handle through every
signature: ``with obs_span("checkpoint"): ...`` is a no-op unless the
driver installed a recorder.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

SPAN_KINDS = ("data_wait", "h2d", "step", "grad_sync", "eval", "checkpoint")


class SpanRecorder:
    def __init__(self, path: str, rank: int = 0):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self.rank = rank
        self._f = open(path, "a")
        self._wlock = threading.Lock()
        self._stacks = threading.local()  # per-thread open-span stack
        self._owner = threading.get_ident()
        self._t_open = time.perf_counter()
        self._t_open_wall = time.time()
        # totals over ALL spans / owner-thread depth-0 spans respectively
        self._totals: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._owner_top: dict[str, float] = {}
        self._closed = False

    def _stack(self) -> list:
        if not hasattr(self._stacks, "s"):
            self._stacks.s = []
        return self._stacks.s

    # -- explicit begin/finish (the Recorder bracket bridge) ----------------
    def begin(self, name: str) -> dict:
        stack = self._stack()
        token = {
            "name": str(name),
            "t0": time.perf_counter(),
            "t0_wall": time.time(),
            "depth": len(stack),
            "thread": threading.get_ident(),
        }
        stack.append(token)
        return token

    def finish(self, token: dict) -> float:
        stack = self._stack()
        if any(t is token for t in stack):
            # tolerate out-of-order finishes (a bracket leaked across an
            # exception): drop everything opened above the token too
            while stack[-1] is not token:
                stack.pop()
            stack.pop()
        # a token not on the stack (double finish / cross-thread) still
        # records its span but must not disturb other threads' nesting
        dur = time.perf_counter() - token["t0"]
        name = token["name"]
        rec = {
            "kind": "span",
            "name": name,
            "rank": self.rank,
            "t0": token["t0_wall"],
            "dur": dur,
            "depth": token["depth"],
        }
        with self._wlock:
            if not self._closed:
                self._f.write(json.dumps(rec) + "\n")
            self._totals[name] = self._totals.get(name, 0.0) + dur
            self._counts[name] = self._counts.get(name, 0) + 1
            if token["depth"] == 0 and token["thread"] == self._owner:
                self._owner_top[name] = self._owner_top.get(name, 0.0) + dur
        return dur

    def note(self, name: str, dur: float, t0_wall: Optional[float] = None) -> None:
        """Record a span measured EXTERNALLY (no begin/finish pair) —
        the dispatch pipeline's amortized step windows
        (utils/dispatch.py). Attributed to the calling thread at depth
        0, so when the caller is the driver the duration lands in the
        summary ``fractions``; the caller must therefore pass exclusive
        time (overlapping spans like data waits already subtracted) to
        preserve the fractions-sum<=1 invariant. The emitted line is
        flagged ``amortized`` so trace readers can tell attributed time
        from bracketed time (schema: tools/check_obs_schema.py)."""
        dur = float(dur)
        name = str(name)
        rec = {
            "kind": "span",
            "name": name,
            "rank": self.rank,
            "t0": (time.time() - dur) if t0_wall is None else t0_wall,
            "dur": dur,
            "depth": 0,
            "amortized": True,
        }
        with self._wlock:
            if not self._closed:
                self._f.write(json.dumps(rec) + "\n")
            self._totals[name] = self._totals.get(name, 0.0) + dur
            self._counts[name] = self._counts.get(name, 0) + 1
            if threading.get_ident() == self._owner:
                self._owner_top[name] = self._owner_top.get(name, 0.0) + dur

    @contextmanager
    def span(self, name: str):
        token = self.begin(name)
        try:
            yield token
        finally:
            self.finish(token)

    # -- run-end summary ----------------------------------------------------
    def summary(self) -> dict:
        wall = max(time.perf_counter() - self._t_open, 1e-9)
        with self._wlock:
            fractions = {
                k: min(v / wall, 1.0) for k, v in sorted(self._owner_top.items())
            }
            rec = {
                "kind": "span_summary",
                "rank": self.rank,
                "t0": self._t_open_wall,
                "wall_s": wall,
                "fractions": fractions,
                "totals_s": dict(sorted(self._totals.items())),
                "counts": dict(sorted(self._counts.items())),
            }
        return rec

    def close(self) -> Optional[dict]:
        """Write the summary line and close the file. Idempotent."""
        rec = None
        if not self._closed:
            rec = self.summary()
            with self._wlock:
                self._closed = True
                self._f.write(json.dumps(rec) + "\n")
                self._f.close()
        return rec


# -- module-level current recorder (deep-layer span hook) -------------------

_current: Optional[SpanRecorder] = None


def set_current(rec: Optional[SpanRecorder]) -> None:
    global _current
    _current = rec


def current() -> Optional[SpanRecorder]:
    return _current


@contextmanager
def obs_span(name: str):
    """Open ``name`` on the installed current recorder; no-op (zero
    overhead beyond one global read) when observability is off."""
    rec = _current
    if rec is None:
        yield None
        return
    with rec.span(name) as token:
        yield token
