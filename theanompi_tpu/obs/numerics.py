"""Numerics sentinels + host-side anomaly detection.

The reference's only numerics signal was the printed loss (its recorder
accumulated cost/error lists and nothing else); a NaN burst, a
gradient-norm explosion, or EASGD/GoSGD replicas silently drifting
apart all look identical to a healthy run until the loss curve is
inspected offline. This module supplies both halves of the fix:

- **In-graph sentinels** (device side): pure jnp helpers the engines
  compile INTO their train steps when the driver requests numerics
  (``--numerics-freq``): global grad-norm, update-norm, param-norm and
  a fused non-finite count, plus per-rule divergence gauges (EASGD
  center<->worker L2, GoSGD inter-replica disagreement). The resulting
  scalars ride the step's metrics dict under the ``nm_`` prefix, so
  they drain through the async dispatch pipeline
  (utils/dispatch.py) with ZERO new host syncs — the same D2H fetch
  that already carries the loss carries them
  (tools/check_hot_loop.py enforces the train loops stay sync-free).

- **Host-side detection** (drain side): :class:`AnomalyDetector`
  evaluates each drained row — hard NaN/Inf triggers on every metric,
  a ``> 0`` trigger on the non-finite count, and EWMA spike detectors
  on the norm/divergence gauges — and returns ``anomaly`` records for
  the obs facade to log, gauge, and hand to the flight recorder
  (obs/flight.py) per the ``--on-anomaly {record,dump,halt}`` policy.

Every engine declares a :class:`NumericsModel` via ``numerics_model()``
(mirroring ``traffic_model()`` / obs/comm.py): which sentinels its
step emits, which divergence gauge the rule supports (BSP/ZeRO/ND are
replicated or sharded-consistent by construction — no gauge needed),
and what extra wire the gauge costs (GoSGD's disagreement needs a
param-sized pmean per numerics step; that is exactly what
``--numerics-freq > 1`` amortizes).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Optional

# metric-key namespace for in-graph sentinels: the dispatcher splits
# these out of every drained row so recorder JSONL stays bit-identical
# to a numerics-off run (acceptance invariant, tests/test_numerics.py)
NM_PREFIX = "nm_"

SENTINEL_KEYS = ("nm_grad_norm", "nm_update_norm", "nm_param_norm",
                 "nm_nonfinite")


class NumericsAnomaly(RuntimeError):
    """Raised by the obs facade under ``--on-anomaly halt`` after the
    flight dump landed — stops training at the first detected anomaly
    instead of burning hours on NaN params."""


class RollbackRequested(NumericsAnomaly):
    """Raised by the obs facade under ``--on-anomaly rollback`` (after
    the flight dump landed): the training driver catches it, restores
    the last VERIFIED checkpoint, optionally skips the offending step
    window, decrements the rollback budget, and keeps training
    (``launch/worker.py``). Escapes the driver only when the budget is
    exhausted or there is nothing verified to roll back to — then it
    behaves exactly like ``halt``."""

    def __init__(self, step: int, anomalies: list):
        self.step = int(step)
        self.anomalies = list(anomalies)
        names = sorted({a.get("metric", "?") for a in self.anomalies})
        super().__init__(
            f"numerics anomaly at step {step}: {names} — rollback "
            f"requested ({len(self.anomalies)} trigger(s))"
        )


@dataclass
class NumericsModel:
    """Per-engine numerics declaration (the ``traffic_model()`` peer)."""

    rule: str
    sentinels: tuple = SENTINEL_KEYS
    divergence: Optional[str] = None  # nm_divergence semantics, or None
    detail: dict = field(default_factory=dict)

    def as_metrics(self) -> dict:
        return {
            "numerics_sentinels": float(len(self.sentinels)),
            "numerics_has_divergence": float(self.divergence is not None),
        }


# -- in-graph sentinel helpers (call inside compiled steps only) ------------

def global_norm(tree: Any):
    """Global L2 norm of a pytree, accumulated in float32 (bf16 squares
    overflow at ~3e38 far later than they lose precision)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    )


def nonfinite_count(tree: Any):
    """Fused count of NaN/Inf elements across every leaf (float32 so it
    rides the metrics dict like the other scalars)."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(
        jnp.sum((~jnp.isfinite(l.astype(jnp.float32))).astype(jnp.float32))
        for l in leaves
    )


def sentinel_metrics(grads: Any, updates: Any, params: Any) -> dict:
    """The standard sentinel set over REPLICATED trees (post-sync grads,
    optimizer updates, new params) — BSP/EASGD/GoSGD local steps, where
    every device holds the full tree."""
    return {
        "nm_grad_norm": global_norm(grads),
        "nm_update_norm": global_norm(updates),
        "nm_param_norm": global_norm(params),
        "nm_nonfinite": nonfinite_count(grads),
    }


def _spec_axes(spec) -> tuple:
    """Mesh axis names a PartitionSpec shards over (flattened)."""
    axes = []
    for part in tuple(spec or ()):
        if part is None:
            continue
        for a in part if isinstance(part, tuple) else (part,):
            if a is not None:
                axes.append(a)
    return tuple(axes)


def sharded_global_norm(tree: Any, specs: Any):
    """Global L2 norm when leaves are SHARDED per ``specs`` (the ND
    engine's tp/pipe/expert layouts): each device sums its local shard's
    squares, psums over exactly the axes that leaf is sharded over
    (replicated axes must NOT be summed — they would count each copy),
    then sqrts the total. Scalar collectives only."""
    import jax
    from jax import lax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    specs_l = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: x is None or not isinstance(x, (dict, list))
    )
    total = jnp.zeros((), jnp.float32)
    for leaf, spec in zip(leaves, specs_l):
        s = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        for a in _spec_axes(spec):
            s = lax.psum(s, a)
        total = total + s
    return jnp.sqrt(total)


def sharded_nonfinite_count(tree: Any, specs: Any):
    """Non-finite count over sharded leaves (see sharded_global_norm)."""
    import jax
    from jax import lax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    specs_l = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: x is None or not isinstance(x, (dict, list))
    )
    total = jnp.zeros((), jnp.float32)
    for leaf, spec in zip(leaves, specs_l):
        s = jnp.sum((~jnp.isfinite(leaf.astype(jnp.float32))).astype(jnp.float32))
        for a in _spec_axes(spec):
            s = lax.psum(s, a)
        total = total + s
    return total


def sharded_sentinels(grads: Any, updates: Any, params: Any, specs: Any) -> dict:
    """Sentinel set for spec-sharded trees (grads/updates/params all
    shard like the params under ND engines)."""
    return {
        "nm_grad_norm": sharded_global_norm(grads, specs),
        "nm_update_norm": sharded_global_norm(updates, specs),
        "nm_param_norm": sharded_global_norm(params, specs),
        "nm_nonfinite": sharded_nonfinite_count(grads, specs),
    }


def sentinels_across_workers(metrics: dict, axis) -> dict:
    """Aggregate per-worker sentinel readings across a worker axis with
    per-metric semantics (EASGD/GoSGD, whose metrics otherwise drain as
    a blanket pmean): the non-finite COUNT psums — one worker's NaN
    must read as >= 1, never as 1/n — and the norms combine as RMS over
    workers, comparable in scale to a single worker's reading. Values
    already uniform across ``axis`` (the divergence gauge) pass through
    unchanged (RMS of a uniform value is that value). Call inside the
    engine's shard_map only."""
    from jax import lax
    import jax.numpy as jnp

    out = dict(metrics)
    for k in metrics:
        if not k.startswith(NM_PREFIX):
            continue
        if k == "nm_nonfinite":
            out[k] = lax.psum(metrics[k], axis)
        else:
            out[k] = jnp.sqrt(lax.pmean(jnp.square(metrics[k]), axis))
    return out


def split_numerics(metrics: dict) -> tuple:
    """``(plain, numerics)`` — strip ``nm_``-prefixed keys out of a
    drained metrics dict so recorder rows stay bit-identical to a
    numerics-off run. Cheap key scan; returns the original dict
    untouched when no sentinels rode along."""
    if not any(k.startswith(NM_PREFIX) for k in metrics):
        return metrics, {}
    plain = {k: v for k, v in metrics.items() if not k.startswith(NM_PREFIX)}
    nm = {k: v for k, v in metrics.items() if k.startswith(NM_PREFIX)}
    return plain, nm


# -- host-side detection (drain time) ---------------------------------------

class AnomalyDetector:
    """Per-metric EWMA spike detection + hard non-finite triggers.

    ``observe(step, metrics, numerics)`` returns a (possibly empty) list
    of anomaly dicts. Rules:

    - any non-finite value (loss, lr, any sentinel) fires ``nonfinite``;
    - ``nm_nonfinite > 0`` fires ``nonfinite_grads`` (the fused in-graph
      count caught NaN/Inf before it even reached the loss);
    - norm/divergence gauges fire ``spike`` when the value exceeds
      ``spike_factor`` x their EWMA, after ``warmup`` observations (the
      first steps of a run legitimately swing orders of magnitude).

    Stateful per metric; host-side only (runs in the dispatcher drain,
    a few float compares per row).
    """

    def __init__(self, spike_factor: float = 10.0, ewma_alpha: float = 0.2,
                 warmup: int = 4):
        self.spike_factor = float(spike_factor)
        self.alpha = float(ewma_alpha)
        self.warmup = int(warmup)
        self._ewma: dict[str, float] = {}
        self._seen: dict[str, int] = {}

    def _check_spike(self, key: str, v: float) -> Optional[dict]:
        seen = self._seen.get(key, 0)
        ewma = self._ewma.get(key)
        fired = None
        if (
            seen >= self.warmup
            and ewma is not None
            and v > self.spike_factor * max(ewma, 1e-30)
        ):
            fired = {"metric": key, "reason": "spike", "value": v,
                     "ewma": ewma, "factor": self.spike_factor}
        # the spiked value still updates the EWMA: a legitimate regime
        # change (LR drop boundary) fires once, then re-baselines
        self._ewma[key] = v if ewma is None else (
            (1 - self.alpha) * ewma + self.alpha * v
        )
        self._seen[key] = seen + 1
        return fired

    def observe(self, step: int, metrics: dict, numerics: dict) -> list:
        anomalies = []
        for src in (metrics, numerics):
            for k, v in src.items():
                v = float(v)
                if not math.isfinite(v):
                    anomalies.append({"metric": k, "reason": "nonfinite",
                                      "value_repr": repr(v)})
        nonf = numerics.get("nm_nonfinite")
        if nonf is not None and math.isfinite(float(nonf)) and float(nonf) > 0:
            anomalies.append({"metric": "nm_nonfinite",
                              "reason": "nonfinite_grads",
                              "value": float(nonf)})
        for k in numerics:
            # EWMA spike detection covers the magnitude gauges — every
            # nm_*_norm plus the per-rule divergence; counts use the
            # >0 trigger above
            if (k.startswith(NM_PREFIX) and k.endswith("_norm")) or (
                k == "nm_divergence"
            ):
                v = float(numerics[k])
                if math.isfinite(v):
                    fired = self._check_spike(k, v)
                    if fired:
                        anomalies.append(fired)
        for a in anomalies:
            a["step"] = int(step)
        return anomalies
