"""Chief-side fleet exporter: the FleetTailer behind an HTTP wire.

Reuses the serve frontend's stdlib ``ThreadingHTTPServer`` pattern
(serve/frontend.py) — no new dependencies, handler threads only read —
to expose the merged fleet view (obs/fleet.py) from the chief while a
run (or a whole supervised retry sequence) is in flight:

Routes::

    GET /metrics    -> Prometheus text of the tmpi_fleet_* registry
                       (same exposition shape as obs/metrics.py:
                       # HELP/# TYPE + name{label="v"} value)
    GET /fleet.json -> FleetView.as_dict(): per-rank rows + aggregates
    GET /healthz    -> 200 healthy / 503 on missed heartbeats or
                       persistent stragglers, body naming the rank ids
                       — the pager-facing probe

Lifecycle: ``start()`` builds a live, record-writing FleetTailer,
binds the server (``port=0`` picks an ephemeral port, re-read from
``.port`` — the tests' path), and spawns ``serve_forever`` on a
``tmpi-fleet-exporter`` daemon thread. ``stop()`` shuts the server
down and joins the tailer. Started chief-only by launch/worker.py
(``--fleet-exporter-port``); under the supervisor the exporter is
started ONCE outside the retry loop (launch/supervisor.py), so the
port stays bound and scrapers keep answering across retries.

Concurrency: handler threads are per-request and only call
``tailer.view()`` / ``registry.to_prometheus()`` — both internally
locked; all mutation stays on the tailer's ``tmpi-fleet-tail`` thread.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from theanompi_tpu.obs.fleet import FleetTailer, fleet_topology


def make_fleet_handler(tailer: FleetTailer):
    class _FleetHandler(BaseHTTPRequestHandler):
        # scrape logging off the stderr: Prometheus polls every few
        # seconds for the life of the run
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _reply(self, code: int, body: dict):
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path == "/metrics":
                data = tailer.registry.to_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path == "/fleet.json":
                view = tailer.view()
                self._reply(200, view.as_dict() if view is not None
                            else {"t": 0.0, "ranks": [], "healthy": True,
                                  "warming_up": True})
            elif self.path == "/healthz":
                view = tailer.view()
                if view is None:
                    self._reply(200, {"healthy": True, "warming_up": True})
                    return
                body = {
                    "healthy": view.healthy,
                    "reasons": view.unhealthy_reasons(),
                    "stragglers": view.stragglers,
                    "frozen": view.frozen,
                    "missed": view.missed,
                    "step": view.step,
                }
                self._reply(200 if view.healthy else 503, body)
            else:
                self._reply(404, {"error": f"no route {self.path}"})

    return _FleetHandler


class FleetExporter:
    """Own one live FleetTailer + one bound HTTP server."""

    def __init__(self, obs_dir: str, port: int, *,
                 host: str = "127.0.0.1", ckpt_dir: Optional[str] = None,
                 topology: Optional[dict] = None,
                 poll_interval: float = 2.0):
        if topology is None and ckpt_dir:
            topology = fleet_topology(ckpt_dir)
        self.obs_dir = obs_dir
        self.host = host
        self.port = int(port)
        self.poll_interval = float(poll_interval)
        self.tailer = FleetTailer(obs_dir, topology=topology, live=True,
                                  write_records=True)
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def start(self) -> "FleetExporter":
        """Bind, tail, serve. Raises OSError if the port is taken — the
        caller (worker/supervisor) degrades to no-exporter with a
        warning rather than failing the run."""
        with self._lock:
            if self._server is not None:
                return self
            server = ThreadingHTTPServer(
                (self.host, self.port), make_fleet_handler(self.tailer)
            )
            self._server = server
            self.port = server.server_address[1]  # resolve port=0
            self.tailer.start(self.poll_interval)
            t = threading.Thread(target=self._serve_loop,
                                 name="tmpi-fleet-exporter", daemon=True)
            self._thread = t
        t.start()
        return self

    def _serve_loop(self) -> None:
        with self._lock:
            server = self._server
        if server is not None:  # stop() can win the race to the lock
            server.serve_forever(poll_interval=0.2)

    def stop(self) -> None:
        """Idempotent shutdown: server first (stop answering), then the
        tailer (one final view is left in place for post-mortem)."""
        with self._lock:
            server, t = self._server, self._thread
            self._server = None
            self._thread = None
        if server is not None:
            server.shutdown()
            server.server_close()
        if t is not None:
            t.join(timeout=10.0)
        self.tailer.stop()

    close = stop

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
