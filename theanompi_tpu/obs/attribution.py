"""Step-time attribution: where does the training step go?

The perf trajectory plateaued at MFU ~0.38 (BENCH_r03–r05) and the
evidence was scattered across four tools that did not compose: XLA
cost-analysis math lived only inside ``bench.py --compute``,
``tools/op_profile.py`` needed a manually captured trace, spans measure
host wall only, and ``traffic_model()`` comm bytes were never
reconciled against measured step time. This module is the one place
the pieces meet (GC3, PAPERS.md arXiv:2201.11840: you can't schedule
what you can't measure):

- :func:`attribute_step` reconciles a MEASURED per-step wall time
  against the analytic models — compute (XLA cost-analysis FLOPs + HBM
  bytes vs the chip's roofline, :class:`~theanompi_tpu.utils.flops.
  CostModel`), collective (``traffic_model()`` effective bytes over the
  chip's ICI link bandwidth, per engine and codec), host-blocked (the
  dispatcher's measured drain tax) — and books what none of them
  explain as the ``residual`` fraction. Fractions sum to 1.0 by
  construction (residual may go negative when a model over-explains the
  step — that is itself a finding, flagged in ``detail``).
- :class:`Attribution` carries the fractions, the roofline
  classification (compute-bound / hbm-bound / comm-bound / host-bound),
  and the ``kind=profile`` JSONL record / ``tmpi_*`` gauge views the
  obs facade emits at snapshot time (obs/__init__.py).
- :func:`join_op_table` joins a ``tools/op_profile.py`` per-op table
  against the analytic model, naming the top ops the model does NOT
  explain — the exact input ROADMAP item 2's fusion work needs.
- :func:`traced_wire_bytes` re-prices the engine's traced jaxpr with
  the SPMD analyzer's collective accounting so ``tmpi profile`` can
  cross-check the declared ``traffic_model()`` at runtime (same
  tolerance as lint rule SPMD101).

**Calibrated fallback (CPU test meshes):** devices without spec-sheet
peaks cannot split device time into compute-vs-HBM, so the non-host,
non-comm remainder of the measured step is attributed to compute
(``peak_source="calibrated"``, residual 0 by construction) — honest
about what it is, and it keeps the fraction-sum invariant checkable on
every backend. Spec-peak devices get the real roofline split and a real
residual.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

# Approximate public per-chip aggregate ICI bandwidth (bytes/s, one
# direction) — the collective-time ceiling traffic bytes divide by.
# Same substring-match convention as utils/flops._PEAK_BF16. DCN-
# attached axes are far slower; the ND engine's figure is dp-only
# (obs/comm.py) so this stays a per-chip ICI number.
_LINK_BYTES_PER_SEC = (
    ("v5 lite", 200e9),  # v5e: 1600 Gbps ICI
    ("v5litepod", 200e9),
    ("v5e", 200e9),
    ("v6 lite", 448e9),  # v6e / Trillium: 3584 Gbps
    ("v6e", 448e9),
    ("v5p", 600e9),
    ("v5", 600e9),
    ("v4", 300e9),
    ("v3", 140e9),
    ("v2", 62.5e9),
)

# roofline classification thresholds (README "Profiling & attribution"):
# host-bound needs a material host share even when nothing else
# dominates; comm/host win ties only when they actually dominate
HOST_BOUND_MIN = 0.4

PROFILE_GAUGE_PREFIX = "tmpi_step_"  # + {compute,comm,host,residual}_frac
# the live gauge family the MetricsDispatcher drain path feeds
# (obs/__init__.py note_step_seconds): tmpi_mfu, tmpi_mfu_calibrated,
# tmpi_hbm_gbps, tmpi_step_*_frac — plus the static tmpi_cost_* family
# from CostModel.as_metrics()


# Approximate per-chip DCN share (bytes/s, one direction) for
# cross-slice hops: a multislice pod's data-center network is shared by
# the whole slice, so the per-chip figure is the slice NIC bandwidth
# divided across its chips — public multislice material puts the
# usable per-chip share near 25 GB/s, an order of magnitude under any
# ICI tier above. This single number is deliberately device-agnostic
# (DCN is the facility fabric, not the chip); override per-run with
# ``attribute_step(dcn_bps=...)`` when the deployment's share is known.
_DCN_BYTES_PER_SEC_DEFAULT = 25e9


def link_bytes_per_sec(device=None) -> Optional[float]:
    """Per-chip ICI bytes/s for ``device`` (default: first visible);
    None when unknown (CPU test meshes)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, bw in _LINK_BYTES_PER_SEC:
        if key in kind:
            return bw
    return None


def dcn_bytes_per_sec() -> float:
    """Per-chip cross-slice (DCN) bytes/s — the flat approximate share
    documented on ``_DCN_BYTES_PER_SEC_DEFAULT``."""
    return _DCN_BYTES_PER_SEC_DEFAULT


@dataclass
class Attribution:
    """One reconciled step-time decomposition (see module docstring).

    ``fractions`` always carries the four keys and sums to 1.0 exactly
    (residual is the booked remainder; negative residual = the models
    over-explain the measured step, named in ``detail``)."""

    step_seconds: float
    fractions: dict  # {compute, comm, host, residual}
    seconds: dict  # same keys, absolute model/measured seconds
    classification: str  # compute-bound|hbm-bound|comm-bound|host-bound
    mfu: Optional[float] = None  # vs spec peak (None on unknown devices)
    mfu_calibrated: Optional[float] = None  # vs calibrated peak (= the
    # compute fraction; the CPU-runnable stand-in the perf gate diffs)
    hbm_gbps: Optional[float] = None  # achieved HBM GB/s (any backend)
    peak_source: str = "spec"  # spec | calibrated
    detail: dict = field(default_factory=dict)

    @property
    def fractions_sum(self) -> float:
        return float(sum(self.fractions.values()))

    def as_metrics(self) -> dict:
        """Live gauge map (obs facade prefixes ``tmpi_``): the MFU /
        HBM / step-fraction family the ISSUE's drain-path gauges carry."""
        out = {f"step_{k}_frac": float(v) for k, v in self.fractions.items()}
        if self.mfu is not None:
            out["mfu"] = float(self.mfu)
        if self.mfu_calibrated is not None:
            out["mfu_calibrated"] = float(self.mfu_calibrated)
        if self.hbm_gbps is not None:
            out["hbm_gbps"] = float(self.hbm_gbps)
        return out

    def as_record(self, step: int, rank: int = 0,
                  rule: Optional[str] = None) -> dict:
        """The ``kind=profile`` JSONL record body (schema:
        tools/check_obs_schema.py) — one per metrics snapshot, written
        by ``Observability.snapshot`` next to the kind=metrics line."""
        import time as _time

        rec = {
            "kind": "profile", "rank": int(rank), "t": _time.time(),
            "step": int(step),
            "step_seconds": float(self.step_seconds),
            "fractions": {k: float(v) for k, v in self.fractions.items()},
            "classification": self.classification,
            "peak_source": self.peak_source,
        }
        if rule:
            rec["rule"] = rule
        if self.mfu is not None:
            rec["mfu"] = float(self.mfu)
        if self.mfu_calibrated is not None:
            rec["mfu_calibrated"] = float(self.mfu_calibrated)
        if self.hbm_gbps is not None:
            rec["hbm_gbps"] = float(self.hbm_gbps)
        return rec


def attribute_step(
    step_seconds: float,
    cost=None,  # utils.flops.CostModel (or None)
    traffic=None,  # obs.comm.TrafficModel (or None)
    host_frac: Optional[float] = None,
    link_bps: Optional[float] = None,
    overlap_frac: Optional[float] = None,
    dcn_bps: Optional[float] = None,
) -> Attribution:
    """Reconcile one measured per-step wall time against the analytic
    models (see module docstring for the calibrated-fallback rules).

    ``host_frac``: the measured fraction of the step the host spent
    blocked (dispatcher drain tax) or dispatching. ``link_bps``
    overrides the device-table ICI bandwidth (tests); ``dcn_bps``
    overrides the flat cross-slice share (``dcn_bytes_per_sec``).
    When the traffic model carries a per-link split
    (``dcn_bytes_per_step > 0``), each link class is priced at its own
    bandwidth — the DCN hop is ~10-25x slower per chip than ICI, so a
    byte there books proportionally more comm seconds (this is exactly
    the asymmetry the hierarchical strategy exploits by sending only
    the scattered shard, codec'd, across slices).

    ``overlap_frac``: fraction of the collective that HIDES under
    backward compute (the bucketed allreduce's schedule estimate —
    parallel/strategies.py::bucket_overlap_frac; defaults to the
    traffic model's ``detail["overlap_frac"]``). Before this knob the
    comm model priced the whole exchange as serial post-backward
    traffic, so an overlapped wire double-counted against compute; now
    only the EXPOSED ``(1 - overlap)`` share books as the comm
    fraction, the hidden seconds land in ``detail["comm_hidden_s"]``."""
    if not step_seconds or step_seconds <= 0:
        raise ValueError(f"step_seconds must be > 0, got {step_seconds}")
    detail: dict = {}
    host = min(1.0, max(0.0, float(host_frac or 0.0)))
    if overlap_frac is None and traffic is not None:
        overlap_frac = traffic.detail.get("overlap_frac")
    overlap = min(1.0, max(0.0, float(overlap_frac or 0.0)))

    comm_s = 0.0
    wire = float(traffic.bytes_per_step_amortized) if traffic is not None else 0.0
    dcn_wire = float(traffic.dcn_bytes_per_step) if traffic is not None else 0.0
    if wire > 0:
        if link_bps is None:
            link_bps = link_bytes_per_sec()
        if link_bps:
            if dcn_wire > 0:
                # per-link pricing: in-slice bytes at ICI speed, the
                # cross-slice shard at the (much slower) DCN share
                ici_s = max(0.0, wire - dcn_wire) / link_bps
                dcn_s = dcn_wire / float(dcn_bps or dcn_bytes_per_sec())
                comm_s = ici_s + dcn_s
                detail["comm_ici_s"] = ici_s
                detail["comm_dcn_s"] = dcn_s
            else:
                comm_s = wire / link_bps
            if overlap > 0:
                detail["overlap_frac"] = overlap
                detail["comm_hidden_s"] = comm_s * overlap
                comm_s = comm_s * (1.0 - overlap)
        else:
            detail["comm_note"] = (
                "link bandwidth unknown on this device kind: collective "
                "time folds into compute/residual (bytes still reported)"
            )
    comm = comm_s / step_seconds

    compute_s = cost.compute_seconds() if cost is not None else None
    hbm_gbps = cost.hbm_gbps(step_seconds) if cost is not None else None
    if compute_s is not None:
        # spec roofline: model compute time vs the measured step; the
        # unexplained remainder is the residual the fusion work attacks
        compute = compute_s / step_seconds
        residual = 1.0 - compute - comm - host
        peak_source = "spec"
        mfu_spec = cost.mfu(step_seconds)
        if residual < -0.02:
            detail["model_overrun"] = (
                f"models explain {compute + comm + host:.3f}x the "
                "measured step — check the traffic/cost inputs"
            )
    else:
        # calibrated fallback: no spec peaks (CPU) — attribute the
        # non-host, non-comm remainder to compute, residual 0
        compute = max(0.0, 1.0 - comm - host)
        residual = 1.0 - compute - comm - host  # 0 unless comm+host > 1
        if abs(residual) < 1e-12:
            residual = 0.0  # float noise from the subtraction chain
        peak_source = "calibrated"
        mfu_spec = None
        detail["calibrated_note"] = (
            "no spec-sheet peak for this device kind: compute is the "
            "non-host non-comm remainder of the measured step"
        )

    fractions = {"compute": compute, "comm": comm, "host": host,
                 "residual": residual}
    seconds = {k: v * step_seconds for k, v in fractions.items()}

    # roofline classification: the dominant booked share names the
    # bottleneck; host only wins with a material share (threshold) —
    # when it loses on the threshold, the verdict falls to whichever of
    # compute/comm actually dominates between themselves
    dominant = max(("compute", "comm", "host"), key=lambda k: fractions[k])
    if dominant == "host" and host < HOST_BOUND_MIN:
        dominant = max(("compute", "comm"), key=lambda k: fractions[k])
    if dominant == "host":
        classification = "host-bound"
    elif dominant == "comm":
        classification = "comm-bound"
    else:
        hbm = cost.hbm_bound() if cost is not None else None
        classification = "hbm-bound" if hbm else "compute-bound"

    return Attribution(
        step_seconds=float(step_seconds),
        fractions=fractions,
        seconds=seconds,
        classification=classification,
        mfu=mfu_spec,
        mfu_calibrated=compute if peak_source == "calibrated" else None,
        hbm_gbps=hbm_gbps,
        peak_source=peak_source,
        detail=detail,
    )


# -- op-table join (tools/op_profile.py x the analytic model) ----------------

# XLA op-name patterns that are collective wire time (the analytic comm
# model's measured counterpart); everything else is compute
_COMM_OP = re.compile(
    r"all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all"
    r"|allreduce|psum|ppermute",
    re.IGNORECASE,
)


def join_op_table(rows: list, attribution: Attribution) -> dict:
    """Join a ``tools/op_profile.py`` per-op table against the analytic
    model: classify each op comm/compute by name, compare the measured
    per-class ms against the model's booked seconds, and name the top
    ops in whichever class the model under-explains — the per-op face
    of the ``residual`` fraction.

    ``rows``: ``op_table()`` output (may be empty — CPU captures have
    no device op track; the join then reports only the model side)."""
    measured = {"compute": 0.0, "comm": 0.0}
    tagged = []
    for r in rows:
        cls = "comm" if _COMM_OP.search(r["op"]) else "compute"
        measured[cls] += float(r["ms_per_step"])
        tagged.append({**r, "class": cls})
    model_ms = {
        "compute": attribution.seconds["compute"] * 1e3,
        "comm": attribution.seconds["comm"] * 1e3,
    }
    overshoot = {
        k: max(0.0, measured[k] - model_ms[k]) for k in measured
    }
    # the class the model under-explains the most owns the residual;
    # its biggest ops are the fusion-work candidates
    worst = max(overshoot, key=lambda k: overshoot[k])
    top_unattributed = [
        {"op": r["op"], "ms_per_step": r["ms_per_step"],
         "share": r["share"], "class": r["class"]}
        for r in sorted(tagged, key=lambda r: -r["ms_per_step"])
        if r["class"] == worst
    ][:8] if overshoot[worst] > 0 else []
    return {
        "measured_ms": measured,
        "model_ms": model_ms,
        "unattributed_ms": overshoot,
        "top_unattributed": top_unattributed,
        "rows": tagged,
    }


def format_join(join: dict, top: int = 10) -> str:
    """Text table for the joined op view (``tmpi profile`` stdout)."""
    lines = [
        "measured vs analytic (ms/step): "
        + "  ".join(
            f"{k}: {join['measured_ms'][k]:.3f} measured / "
            f"{join['model_ms'][k]:.3f} model"
            for k in ("compute", "comm")
        )
    ]
    if not join["rows"]:
        lines.append("(no device op track in trace — CPU capture? "
                     "per-op attribution needs a TPU trace)")
        return "\n".join(lines)
    lines.append(f"{'ms/step':>10}  {'share':>6}  {'class':>7}  op")
    for r in sorted(join["rows"], key=lambda r: -r["ms_per_step"])[:top]:
        lines.append(
            f"{r['ms_per_step']:10.3f}  {r['share'] * 100:5.1f}%  "
            f"{r['class']:>7}  {r['op'][:70]}"
        )
    if join["top_unattributed"]:
        names = ", ".join(r["op"] for r in join["top_unattributed"][:5])
        worst = max(join["unattributed_ms"],
                    key=lambda k: join["unattributed_ms"][k])
        lines.append(
            f"top unattributed ({worst}, "
            f"{join['unattributed_ms'][worst]:.3f} ms/step beyond the "
            f"model): {names}"
        )
    return "\n".join(lines)


# -- runtime traffic cross-check (the SPMD101 contract, live) ----------------

def traced_wire_bytes(parts, codec_bytes: Optional[float] = None) -> float:
    """Amortized per-step wire bytes of an engine's traced programs,
    priced with the SPMD analyzer's collective accounting
    (tools/analyze/signature.py) — the measured-side half of the
    ``tmpi profile`` traffic cross-check.

    ``parts``: ``[(fn, args, weight), ...]`` — each traced with
    ``jax.make_jaxpr`` over (abstract) args; ``weight`` amortizes
    periodic programs (EASGD exchange = 1/avg_freq). ``codec_bytes``:
    price quantization-evidenced collectives at this bytes-per-element
    (codec-on runs; None = raw dtype pricing, the SPMD101 convention)."""
    import jax

    from theanompi_tpu.tools.analyze.signature import (
        extract_signature,
        signature_effective_bytes,
        signature_raw_bytes,
    )

    total = 0.0
    for fn, args, weight in parts:
        sig, axis_sizes = extract_signature(jax.make_jaxpr(fn)(*args))
        if codec_bytes is not None:
            total += signature_effective_bytes(sig, axis_sizes,
                                               codec_bytes) * weight
        else:
            total += signature_raw_bytes(sig, axis_sizes) * weight
    return total


def crosscheck_traffic(traced: float, declared: float) -> dict:
    """Compare traced vs declared raw wire bytes under the SPMD101
    tolerance (tools/analyze/rules.py): ok within
    ``max(512 B, 8% of the larger)``."""
    from theanompi_tpu.tools.analyze.rules import (
        TRAFFIC_ABS_TOL,
        TRAFFIC_REL_TOL,
    )

    tol = max(TRAFFIC_ABS_TOL, TRAFFIC_REL_TOL * max(traced, declared))
    return {
        "traced_bytes": float(traced),
        "declared_bytes": float(declared),
        "tolerance_bytes": float(tol),
        "ok": abs(traced - declared) <= tol,
    }
