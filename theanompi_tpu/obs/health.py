"""Per-host heartbeat + multihost stall watchdog.

A multihost run that hangs in a collective today freezes SILENTLY: the
gang-scheduled XLA program blocks every controller, no Python line is
"slow", and the only symptom is a JSONL stream that stops growing. The
reference never faced this (blocking MPI calls fail loudly); the TPU
equivalent needs an out-of-band health layer that distinguishes *slow*
from *stuck*:

- :class:`Heartbeat` — a daemon thread that atomically rewrites
  ``heartbeat_rank{r}.json`` every ``interval`` seconds with the wall
  time, pid, and last completed global step. An external supervisor (or
  another host) reads file mtime + step to tell a live-but-slow rank
  from a dead one.
- :class:`StallWatchdog` — a daemon thread fed ``notify_step(step)``
  after every completed step. When the step stops advancing for
  ``timeout`` seconds it fires ONCE per stall: dumps every Python
  thread's stack (the driver's frame shows WHICH dispatch blocks) to
  ``stall_rank{r}.json`` + a human-readable ``.txt``, then arms a
  ``jax.profiler`` trace into ``postmortem_rank{r}/`` for a short
  window so the device timeline around the hang is preserved for
  tensorboard/xprof. Re-arms automatically when steps resume. The
  clock runs from CONSTRUCTION, not the first step: a run that wedges
  in its very first collective — the canonical multihost hang this
  layer exists to diagnose — reports ``step: -1`` (nothing completed
  yet). The cost of that coverage: a first-epoch compile longer than
  the timeout also reads as a stall, so size the timeout above the
  worst expected compile/eval pause.

Each host watches only its own step counter — a hung collective stalls
every participant, so every rank produces its own post-mortem, and a
SINGLE slow host is identifiable as the one whose heartbeat still
advances while the others' step counters froze.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Optional

from theanompi_tpu.obs.metrics import atomic_write_text


def _atomic_write_json(path: str, obj: dict) -> None:
    atomic_write_text(path, json.dumps(obj))


def arm_profiler_capture(trace_dir: str, capture_s: float = 2.0,
                         rank: int = 0, wait_at_exit: bool = False) -> str:
    """Best-effort ``jax.profiler`` capture of a ``capture_s`` window on
    a daemon thread — armed-and-forgotten, shared by the stall watchdog
    and the flight recorder (obs/flight.py). start/stop can themselves
    BLOCK on a wedged runtime (observed: stop_trace hangs on the CPU
    backend mid-stall), so nothing waits on the thread; any failure
    (already tracing, wedged runtime) is swallowed. Returns the target
    directory immediately.

    ``wait_at_exit``: run the capture on a NON-daemon thread so a
    process that exits right after arming (the ``--on-anomaly halt``
    path) lets the capture finish instead of tearing the interpreter
    down mid-trace (measured: a daemon capture killed at finalization
    segfaults the CPU backend — an atexit join does NOT save it, the
    thread never gets scheduled again once shutdown starts). Callers
    must only set this when the runtime is known-alive (an anomaly dump
    just drained a row from it); stall dumps keep the daemon default —
    their runtime is presumed wedged and a hung stop_trace must never
    block exit."""

    def capture():
        try:
            import jax

            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
            time.sleep(capture_s)
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — an armed Recorder
            # trace (already tracing) or a wedged runtime must not
            # surface as a crash from a diagnostics thread
            print(f"[rank {rank}] post-mortem trace capture "
                  f"failed: {e!r}", file=sys.stderr, flush=True)

    threading.Thread(
        target=capture, name=f"tmpi-postmortem-r{rank}",
        daemon=not wait_at_exit,
    ).start()
    return trace_dir


def thread_stacks() -> dict[str, list[str]]:
    """``{thread_name: [formatted frames...]}`` for every live Python
    thread (the stall report payload). Ordered for triage: the main
    thread first (the driver's frame shows which dispatch blocks),
    then the framework's stable ``tmpi-<role>`` threads sorted by role
    so repeated dumps group attributably, then everything else — the
    same names the thread-model inventory
    (tools/analyze/concurrency.thread_inventory) and the stress
    harness report."""
    names = {t.ident: t.name for t in threading.enumerate()}

    def rank(item):
        name = item[0]
        if name.startswith("MainThread"):
            return (0, name)
        if name.startswith("tmpi-"):
            return (1, name)
        return (2, name)

    stacks = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, f"thread-{ident}")
        stacks[f"{name} ({ident})"] = [
            line.rstrip("\n")
            for line in traceback.format_stack(frame)
        ]
    return dict(sorted(stacks.items(), key=rank))


class Heartbeat:
    def __init__(self, obs_dir: str, rank: int = 0, interval: float = 5.0):
        self.path = os.path.join(obs_dir, f"heartbeat_rank{rank}.json")
        self.rank = rank
        self.interval = max(0.2, float(interval))
        self._step = 0
        self._extra: Optional[Callable[[], dict]] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"tmpi-heartbeat-r{rank}", daemon=True
        )
        self._thread.start()

    def set_step(self, step: int) -> None:
        self._step = int(step)

    def set_extra(self, provider: Optional[Callable[[], dict]]) -> None:
        """Install a provider whose dict merges into every beat — the
        driver wires the dispatch pipeline's ``dispatch_in_flight`` /
        ``last_drained_step`` here, so a stall report reader can tell a
        wedged DEVICE program (step advances, drains stop: in-flight
        pinned at depth) from a stalled HOST driver (dispatches stop:
        in-flight falls to 0 and stays)."""
        self._extra = provider

    def _beat(self) -> None:
        payload = {
            "kind": "heartbeat",
            "rank": self.rank,
            "t": time.time(),
            "step": self._step,
            "pid": os.getpid(),
        }
        provider = self._extra
        if provider is not None:
            try:
                payload.update(provider())
            except Exception:  # noqa: BLE001 — liveness must not die
                pass           # because a telemetry getter raced a close
        _atomic_write_json(self.path, payload)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._beat()
            except OSError:
                pass  # a full disk must not kill the heartbeat thread
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        try:
            self._beat()  # final state on disk: last step before exit
        except OSError:
            pass


class StallWatchdog:
    """Fires ``on_stall`` (default: stack dump + profiler arm) when the
    step counter stops advancing for ``timeout`` seconds."""

    def __init__(
        self,
        timeout: float,
        obs_dir: str,
        rank: int = 0,
        arm_profiler: bool = True,
        capture_s: float = 2.0,
        on_stall: Optional[Callable[[dict], None]] = None,
    ):
        if timeout <= 0:
            raise ValueError(f"stall timeout must be > 0, got {timeout}")
        self.timeout = float(timeout)
        self.obs_dir = obs_dir
        self.rank = rank
        self.arm_profiler = arm_profiler
        self.capture_s = capture_s
        self.report_path = os.path.join(obs_dir, f"stall_rank{rank}.json")
        self._on_stall = on_stall
        self._lock = threading.Lock()
        self._last_step = -1
        self._last_advance = time.monotonic()
        self._fired_at_step: Optional[int] = None
        self.stall_count = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"tmpi-stall-watchdog-r{rank}", daemon=True
        )
        self._thread.start()

    def notify_step(self, step: int) -> None:
        with self._lock:
            if step != self._last_step:
                self._last_step = step
                self._last_advance = time.monotonic()
                self._fired_at_step = None  # re-arm after progress

    def _run(self) -> None:
        poll = min(self.timeout / 4.0, 1.0)
        while not self._stop.wait(poll):
            with self._lock:
                stalled_s = time.monotonic() - self._last_advance
                step = self._last_step
                # step == -1: nothing completed yet — a first-dispatch
                # hang still fires (the clock runs from construction)
                should_fire = (
                    stalled_s > self.timeout
                    and self._fired_at_step != step
                )
                if should_fire:
                    self._fired_at_step = step
            if should_fire:
                try:
                    self._fire(step, stalled_s)
                except Exception as e:  # noqa: BLE001 — diagnostics only:
                    # the watchdog must never take down a live run
                    print(f"[rank {self.rank}] stall watchdog report "
                          f"failed: {e!r}", file=sys.stderr, flush=True)

    def _fire(self, step: int, stalled_s: float) -> None:
        self.stall_count += 1
        report = {
            "kind": "stall",
            "rank": self.rank,
            "t": time.time(),
            "step": step,
            "stall_s": stalled_s,
            "timeout_s": self.timeout,
            "stacks": thread_stacks(),
        }
        print(
            f"[rank {self.rank}] STALL WATCHDOG: global step stuck at "
            f"{step} for {stalled_s:.1f}s (> {self.timeout:.1f}s) — "
            f"dumping thread stacks to {self.report_path}",
            file=sys.stderr, flush=True,
        )
        # report FIRST (the stacks are the critical payload), THEN arm
        # the device capture: profiler start/stop can block indefinitely
        # on a wedged runtime — exactly the situation being diagnosed
        postmortem = self._arm_postmortem()
        if postmortem:
            report["postmortem_trace"] = postmortem
        _atomic_write_json(self.report_path, report)
        txt = self.report_path[:-5] + ".txt"
        with open(txt, "w") as f:
            f.write(
                f"STALL at step {step}: no progress for {stalled_s:.1f}s "
                f"(timeout {self.timeout:.1f}s), rank {self.rank}\n\n"
            )
            for name, frames in report["stacks"].items():
                f.write(f"--- {name} ---\n")
                f.write("\n".join(frames) + "\n\n")
            if postmortem:
                f.write(
                    f"device post-mortem trace: {postmortem}\n"
                    "view: tensorboard --logdir <dir> (xprof trace viewer)\n"
                )
        if self._on_stall is not None:
            self._on_stall(report)

    def _arm_postmortem(self) -> Optional[str]:
        """Capture a ``capture_s`` device-trace window DURING the stall
        (shared :func:`arm_profiler_capture` machinery): if the device
        is actually executing (slow collective, DCN congestion) the
        trace shows it."""
        if not self.arm_profiler:
            return None
        return arm_profiler_capture(
            os.path.join(self.obs_dir, f"postmortem_rank{self.rank}"),
            capture_s=self.capture_s, rank=self.rank,
        )

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
