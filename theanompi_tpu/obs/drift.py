"""Model-drift watchdog: do the static truth sources still match the
hardware? (ISSUE 18 tentpole, the forcing function ROADMAP item 4's
planner requires before it can trust a *predicted* step time.)

The repo holds three analytic models nobody continuously audits:
:class:`~theanompi_tpu.utils.flops.CostModel` (FLOPs/HBM roofline →
predicted step wall), :class:`~theanompi_tpu.obs.comm.TrafficModel`
(per-link wire bytes → predicted comm seconds), and
:class:`~theanompi_tpu.utils.flops.MemoryModel` (per-leaf state bytes →
predicted HBM high-water). At every dispatcher drain sync the obs
facade feeds this watchdog the MEASURED counterparts — step wall from
the dispatcher, comm share as the non-compute non-host remainder,
HBM high-water from ``jax.local_devices()[i].memory_stats()`` where the
backend exposes it — and the watchdog maintains one EWMA relative
error per model, surfaced three ways:

- live gauges ``tmpi_model_err_{cost,traffic,memory}`` (the numbers
  ``perf_gate`` learns to diff, so model honesty regressions fail CI
  exactly like MFU regressions);
- change-gated ``kind=drift`` JSONL records in ``metrics.jsonl`` naming
  the worst-offending component (per-link for traffic, per-leaf-family
  for memory) — schema: tools/check_obs_schema.py;
- a ``drift`` anomaly (flight-recorder bundle ``anomaly_rank{r}-drift/``)
  when an EWMA crosses the configured tolerance band
  (``--drift-tolerance``, default :data:`DRIFT_TOLERANCE_DEFAULT`), so
  the PR-3 triage bundle captures the step where the model lost touch
  with reality.

**Calibrated fallback (CPU test meshes):** like obs/attribution.py,
devices without spec-sheet peaks cannot price a predicted wall, so an
observation calibrates the un-modeled remainder (the LOWEST implied
compute seconds seen for cost — warm-up/compile drains must not pin an
inflated baseline — the first drain's wire bytes for traffic, the
prediction itself for memory when ``memory_stats()`` is absent) and
later errors measure drift AGAINST THAT CALIBRATION — honest about
what it is (``peak_source="calibrated"`` rides the record), and it
keeps the gauges live and the gate non-vacuous on every backend. The
calibrated COST error is gauge-only (exempt from the breach anomaly):
a baseline that is the run's own step wall fed back swings with drain-
window composition, which is signal worth plotting but not worth a
forensic bundle.
"""

from __future__ import annotations

from typing import Optional

DRIFT_TOLERANCE_DEFAULT = 0.25
# EWMA smoothing — one convention across the obs plane (obs/fleet.py
# EWMA_ALPHA): new samples weigh 0.2, so a single noisy drain cannot
# trip the tolerance band on its own
DRIFT_EWMA_ALPHA = 0.2
DRIFT_GAUGE_PREFIX = "model_err_"  # facade prefixes tmpi_ -> tmpi_model_err_*
DRIFT_SOURCES = ("cost", "traffic", "memory")
# change-gate quantum: a record is worth a line when any EWMA moves at
# the third decimal or the breached set changes (mirrors the fleet
# tailer's change-gated kind=fleet records)
_GATE_DECIMALS = 3
# relative-error floor for the measured-comm denominator: a model that
# predicts comm where the measured remainder is ~0 must read as a large
# finite error, not a division blowup
_COMM_MEAS_FLOOR_FRAC = 0.01

# memory_stats() key preference — TPU runtimes report peak_bytes_in_use;
# fall back to the instantaneous figure when the peak is not kept
_MEM_STAT_KEYS = ("peak_bytes_in_use", "bytes_in_use")


def device_peak_bytes() -> Optional[float]:
    """Max measured HBM high-water across local devices via
    ``memory_stats()``; None when the backend keeps no stats (CPU)."""
    try:
        import jax

        peaks = []
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", None)
            stats = stats() if callable(stats) else None
            if not stats:
                continue
            for key in _MEM_STAT_KEYS:
                if stats.get(key):
                    peaks.append(float(stats[key]))
                    break
        return max(peaks) if peaks else None
    except Exception:
        return None


class DriftWatchdog:
    """Per-run EWMA tracker of predicted-vs-measured error for the three
    analytic models. One instance per rank (the facade owns it); feed it
    every drain via :meth:`observe`, which returns ``(record, breaches)``
    — ``record`` a change-gated ``kind=drift`` body (None when nothing
    moved), ``breaches`` the sources that newly crossed the tolerance
    band this drain (each fires at most one anomaly per run until it
    recovers below the band)."""

    def __init__(self, tolerance: float = DRIFT_TOLERANCE_DEFAULT, *,
                 alpha: float = DRIFT_EWMA_ALPHA, rank: int = 0,
                 link_bps: Optional[float] = None,
                 dcn_bps: Optional[float] = None):
        self.tolerance = float(tolerance)
        self.alpha = float(alpha)
        self.rank = int(rank)
        # test injection points; None = device-table lookup like
        # obs/attribution.py
        self._link_bps = link_bps
        self._dcn_bps = dcn_bps
        self.ewma: dict = {k: None for k in DRIFT_SOURCES}
        self.worst: dict = {k: None for k in DRIFT_SOURCES}
        self.breached: set = set()
        self.peak_source = "spec"
        self._calib_compute_s: Optional[float] = None
        self._calib_wire_bytes: Optional[float] = None
        self._calib_mem_bytes: Optional[float] = None
        self._cost_calibrated = False
        self._last_sig = None

    # -- per-model error terms -------------------------------------------

    def _priced_comm(self, traffic, step_seconds: float):
        """(exposed_comm_s, ici_s, dcn_s) for the traffic model at the
        chip's link bandwidths — the attribute_step pricing, reused —
        or None when the bandwidth is unknown (CPU fallback)."""
        wire = float(traffic.bytes_per_step_amortized)
        if wire <= 0:
            return 0.0, 0.0, 0.0
        link_bps = self._link_bps
        if link_bps is None:
            from theanompi_tpu.obs.attribution import link_bytes_per_sec

            link_bps = link_bytes_per_sec()
        if not link_bps:
            return None
        dcn_wire = float(traffic.dcn_bytes_per_step)
        if dcn_wire > 0:
            from theanompi_tpu.obs.attribution import dcn_bytes_per_sec

            ici_s = max(0.0, wire - dcn_wire) / link_bps
            dcn_s = dcn_wire / float(self._dcn_bps or dcn_bytes_per_sec())
        else:
            ici_s, dcn_s = wire / link_bps, 0.0
        overlap = min(1.0, max(0.0, float(
            traffic.detail.get("overlap_frac") or 0.0)))
        exposed = (ici_s + dcn_s) * (1.0 - overlap)
        return exposed, ici_s, dcn_s

    def _observe_cost(self, cost, step_seconds: float, comm_s: float,
                      host_s: float) -> Optional[float]:
        compute_s = cost.compute_seconds()
        if compute_s is not None:
            hbm = cost.hbm_bound()
            self.worst["cost"] = "hbm" if hbm else "flops"
            self._cost_calibrated = False
        else:
            self._cost_calibrated = True
            # calibrated: the LOWEST implied compute seen pins the
            # un-modeled compute seconds — the first drains amortize
            # compile/warm-up, and pricing every later (faster) step
            # against that inflated baseline would read as permanent
            # drift, so a faster step re-pins the floor and only
            # SLOW-DOWNS against it count as drift
            self.peak_source = "calibrated"
            implied = max(0.0, step_seconds - comm_s - host_s)
            if (self._calib_compute_s is None
                    or implied < self._calib_compute_s):
                self._calib_compute_s = implied
            compute_s = self._calib_compute_s
            self.worst["cost"] = "calibrated-compute"
        predicted = compute_s + comm_s + host_s
        return abs(predicted - step_seconds) / step_seconds

    def _observe_traffic(self, traffic, step_seconds: float,
                         compute_s: Optional[float],
                         host_s: float) -> Optional[float]:
        priced = self._priced_comm(traffic, step_seconds)
        if priced is not None:
            exposed, ici_s, dcn_s = priced
            self.worst["traffic"] = "dcn" if dcn_s > ici_s else "ici"
            if compute_s is None:
                compute_s = self._calib_compute_s
            if compute_s is None:
                # first drain on a calibrated device: cost path has not
                # pinned its baseline yet — nothing measured to diff
                return None
            measured = max(0.0, step_seconds - compute_s - host_s)
            floor = _COMM_MEAS_FLOOR_FRAC * step_seconds
            return abs(exposed - measured) / max(measured, floor)
        # unpriceable link (CPU): drift is the model's own wire bytes
        # moving against the first-drain calibration (a reshard or codec
        # change that nobody re-calibrated shows up here)
        wire = float(traffic.bytes_per_step_amortized)
        if wire <= 0:
            return None
        self.peak_source = "calibrated"
        self.worst["traffic"] = (
            "dcn" if float(traffic.dcn_bytes_per_step) > 0 else "ici")
        if self._calib_wire_bytes is None:
            self._calib_wire_bytes = wire
        return abs(wire - self._calib_wire_bytes) / self._calib_wire_bytes

    def _observe_memory(self, memory,
                        measured_bytes: Optional[float]) -> Optional[float]:
        predicted = float(memory.state_bytes_per_device)
        if predicted <= 0:
            return None
        cats = memory.category_bytes_per_device()
        if cats:
            self.worst["memory"] = max(cats, key=lambda k: cats[k])
        if measured_bytes is None:
            measured_bytes = device_peak_bytes()
        if measured_bytes is None:
            # no memory_stats() on this backend: calibrate the measured
            # high-water to the prediction — error stays 0 until the
            # MODEL moves (a reshard that changes state residency)
            self.peak_source = "calibrated"
            if self._calib_mem_bytes is None:
                self._calib_mem_bytes = predicted
            measured_bytes = self._calib_mem_bytes
        return abs(measured_bytes - predicted) / predicted

    # -- the drain-path entry point --------------------------------------

    def observe(self, step_seconds: float, *, step: int = 0,
                cost=None, traffic=None, memory=None,
                host_frac: Optional[float] = None,
                measured_hbm_bytes: Optional[float] = None):
        """Fold one drain's measurements into the EWMAs.

        Returns ``(record, breaches)``: the change-gated ``kind=drift``
        record body (None when the gate holds it back) and the list of
        sources that newly crossed the tolerance band — the facade turns
        those into the ``drift`` anomaly + flight bundle."""
        if not step_seconds or step_seconds <= 0:
            return None, []
        host_s = min(1.0, max(0.0, float(host_frac or 0.0))) * step_seconds
        comm_s, compute_s = 0.0, None
        if traffic is not None:
            priced = self._priced_comm(traffic, step_seconds)
            if priced is not None:
                comm_s = priced[0]
        if cost is not None:
            compute_s = cost.compute_seconds()

        errs = {
            "cost": self._observe_cost(cost, step_seconds, comm_s, host_s)
            if cost is not None else None,
            "traffic": self._observe_traffic(
                traffic, step_seconds, compute_s, host_s)
            if traffic is not None else None,
            "memory": self._observe_memory(memory, measured_hbm_bytes)
            if memory is not None else None,
        }
        for src, err in errs.items():
            if err is None:
                continue
            prev = self.ewma[src]
            self.ewma[src] = err if prev is None else (
                self.alpha * err + (1.0 - self.alpha) * prev)

        now_breached = {src for src in DRIFT_SOURCES
                        if self.ewma[src] is not None
                        and self.ewma[src] > self.tolerance
                        # a calibrated cost "prediction" is the run's own
                        # step wall fed back — drift against it is timing
                        # noise (epoch-boundary drain windows swing it
                        # 100x on micro-steps), a gauge-worthy signal but
                        # never a forensic-bundle anomaly; the spec
                        # roofline path keeps full breach semantics, as
                        # do the calibrated traffic/memory paths, which
                        # diff exact model outputs, not timers
                        and not (src == "cost" and self._cost_calibrated)}
        breaches = sorted(now_breached - self.breached)
        self.breached = now_breached

        sig = tuple(
            None if self.ewma[src] is None
            else round(self.ewma[src], _GATE_DECIMALS)
            for src in DRIFT_SOURCES
        ) + (frozenset(now_breached),)
        record = None
        if sig != self._last_sig and any(
                v is not None for v in self.ewma.values()):
            self._last_sig = sig
            record = self._record(step, step_seconds)
        return record, breaches

    def _record(self, step: int, step_seconds: float) -> dict:
        """``kind=drift`` JSONL body — all-scalar fields so the schema
        checker's extra-field rule holds; caller stamps ``t``."""
        rec = {
            "kind": "drift", "rank": self.rank, "step": int(step),
            "step_seconds": float(step_seconds),
            "tolerance": self.tolerance,
            "peak_source": self.peak_source,
            "breached": ",".join(sorted(self.breached)),
        }
        for src in DRIFT_SOURCES:
            if self.ewma[src] is not None:
                rec[f"model_err_{src}"] = float(self.ewma[src])
            if self.worst[src]:
                rec[f"worst_{src}"] = str(self.worst[src])
        return rec

    def as_metrics(self) -> dict:
        """Live gauge map (facade prefixes ``tmpi_``):
        ``model_err_{cost,traffic,memory}`` for every source that has
        at least one sample — the values ``perf_gate`` diffs."""
        return {f"{DRIFT_GAUGE_PREFIX}{src}": float(self.ewma[src])
                for src in DRIFT_SOURCES if self.ewma[src] is not None}
