"""Observability subsystem: metrics registry, span tracing, analytic
comm accounting, and run health (heartbeat + stall watchdog).

The reference's observability was ``lib/recorder.py``'s host wall-clock
brackets; on TPU the collective is fused inside one XLA program, so
this package supplies what host brackets cannot (SURVEY.md §5.1,
ISSUE 1):

- :mod:`~theanompi_tpu.obs.metrics` — labeled counters/gauges/
  histograms, Prometheus text exposition + JSONL snapshots;
- :mod:`~theanompi_tpu.obs.spans` — nestable trace spans with a
  per-rank JSONL log and a run-end time-fraction summary;
- :mod:`~theanompi_tpu.obs.comm` — closed-form bytes-on-the-wire per
  step for every sync rule (the comm-side peer of utils/flops.py MFU);
- :mod:`~theanompi_tpu.obs.health` — heartbeat files + a stall
  watchdog that dumps thread stacks and arms a post-mortem device
  trace when the global step stops advancing.

:class:`Observability` is the driver-facing facade
(``launch/worker.py``): one object that owns the per-run registry, the
span recorder, the health threads, and the snapshot cadence — and that
collapses to near-zero-cost no-ops when ``obs_dir`` is None, so the
training loop carries no conditionals.

On-disk layout under ``obs_dir`` (schemas:
``theanompi_tpu/tools/check_obs_schema.py``)::

    metrics.jsonl           rank-0 metric snapshots (kind=metrics)
    metrics.prom            rank-0 Prometheus text exposition (atomic)
    spans_rank{r}.jsonl     per-rank span + span_summary lines
    heartbeat_rank{r}.json  per-rank liveness (atomic rewrite)
    stall_rank{r}.json/.txt stall watchdog reports (thread stacks)
    postmortem_rank{r}/     jax.profiler trace armed at stall time
"""

from __future__ import annotations

import os
import sys
from typing import Optional

from theanompi_tpu.obs import spans as _spans_mod
from theanompi_tpu.obs.comm import (  # noqa: F401
    TrafficModel,
    bsp_traffic,
    easgd_traffic,
    gosgd_traffic,
    nd_traffic,
    pytree_num_elements,
    zero1_traffic,
)
from theanompi_tpu.obs.health import Heartbeat, StallWatchdog  # noqa: F401
from theanompi_tpu.obs.metrics import (  # noqa: F401
    REGISTRY,
    MetricsRegistry,
    result_to_snapshot,
)
from theanompi_tpu.obs.spans import SpanRecorder, obs_span  # noqa: F401


class Observability:
    """Per-run facade over the obs modules (see module docstring).

    ``snapshot_freq``: write a metrics snapshot (JSONL + prom rewrite)
    every N completed steps; 0 = only at epoch boundaries/close (the
    driver calls :meth:`snapshot` at epoch end regardless).
    ``stall_timeout``: seconds without step progress before the
    watchdog fires; 0 disables it. Set it ABOVE the worst expected
    compile/eval pause — the watchdog only learns of progress through
    :meth:`on_step`, so a first-epoch XLA compile longer than the
    timeout reads as a stall.
    """

    def __init__(
        self,
        obs_dir: Optional[str],
        *,
        rank: int = 0,
        stall_timeout: float = 0.0,
        snapshot_freq: int = 0,
        heartbeat_interval: float = 5.0,
        arm_profiler: bool = True,
    ):
        self.obs_dir = obs_dir
        self.rank = rank
        self.enabled = obs_dir is not None
        self.snapshot_freq = max(0, int(snapshot_freq))
        self.registry = MetricsRegistry()
        self.spans: Optional[SpanRecorder] = None
        self.heartbeat: Optional[Heartbeat] = None
        self.watchdog: Optional[StallWatchdog] = None
        self.traffic: Optional[TrafficModel] = None
        self._metrics_f = None
        self._prom_path = None
        self._last_snapshot_step = 0
        self._closed = False
        if not self.enabled:
            return
        os.makedirs(obs_dir, exist_ok=True)
        self.spans = SpanRecorder(
            os.path.join(obs_dir, f"spans_rank{rank}.jsonl"), rank=rank
        )
        # install as the process-current recorder so deep layers
        # (utils/checkpoint.py, data/loader.py) can open spans without
        # plumbing a handle through every signature
        _spans_mod.set_current(self.spans)
        if rank == 0:
            # one metrics sink per run (reference: rank-0 recorder save)
            self._metrics_f = open(os.path.join(obs_dir, "metrics.jsonl"), "a")
            self._prom_path = os.path.join(obs_dir, "metrics.prom")
        self.heartbeat = Heartbeat(obs_dir, rank=rank,
                                   interval=heartbeat_interval)
        if stall_timeout and stall_timeout > 0:
            self.watchdog = StallWatchdog(
                stall_timeout, obs_dir, rank=rank, arm_profiler=arm_profiler
            )

    # -- driver hooks --------------------------------------------------------
    def set_traffic_model(self, tm: Optional[TrafficModel]) -> None:
        """Record the active sync rule's analytic wire model (engine-
        declared; see each engine's ``traffic_model``) as gauges, so
        every snapshot carries the per-step comm bytes next to the
        measured throughput."""
        self.traffic = tm
        if tm is None or not self.enabled:
            return
        for key, value in tm.as_metrics().items():
            self.registry.gauge(
                f"tmpi_{key}",
                help=f"analytic {tm.rule} wire model (obs/comm.py)",
            ).set(value)
        self.registry.gauge(
            "tmpi_comm_n_workers", help="sync-rule worker count"
        ).set(tm.n_workers)

    def on_step(self, step: int, substeps: int = 1,
                step_seconds: Optional[float] = None) -> None:
        """Per completed dispatch: advance health + comm accounting.
        ``substeps`` > 1 for fused dispatches (one call per group)."""
        if self.heartbeat is not None:
            self.heartbeat.set_step(step)
        if self.watchdog is not None:
            self.watchdog.notify_step(step)
        if not self.enabled:
            return
        self.registry.counter(
            "tmpi_steps_total", help="completed training steps"
        ).inc(substeps)
        if self.traffic is not None:
            per_step = self.traffic.bytes_per_step_amortized
            self.registry.counter(
                "tmpi_comm_bytes_total",
                help="cumulative analytic per-device wire bytes",
            ).inc(per_step * substeps)
            if step_seconds:
                gbps = self.traffic.achieved_gbps(step_seconds / substeps)
                if gbps is not None:
                    self.registry.gauge(
                        "tmpi_comm_gbps",
                        help="achieved per-device interconnect GB/s "
                             "(analytic bytes / measured step time)",
                    ).set(gbps)
        if (
            self.snapshot_freq
            and step - self._last_snapshot_step >= self.snapshot_freq
        ):
            self.snapshot(step=step)

    def note_step_seconds(self, per_step_seconds: Optional[float]) -> None:
        """Refresh the achieved-GB/s gauge from an amortized per-step
        time (utils/dispatch.py's spaced syncs). Under deferred dispatch
        :meth:`on_step` no longer knows the step time at push time —
        the dispatcher calls this at each sync point instead, so the
        gauge carries the same analytic-bytes / measured-time reading
        sync mode produced, just on the sync cadence."""
        if not self.enabled or self.traffic is None or not per_step_seconds:
            return
        gbps = self.traffic.achieved_gbps(per_step_seconds)
        if gbps is not None:
            self.registry.gauge(
                "tmpi_comm_gbps",
                help="achieved per-device interconnect GB/s "
                     "(analytic bytes / measured step time)",
            ).set(gbps)

    def snapshot(self, step: Optional[int] = None) -> Optional[dict]:
        """Write one metrics snapshot line + refresh the Prometheus
        exposition (rank 0 only; other ranks no-op)."""
        if not self.enabled or self._metrics_f is None or self._closed:
            return None
        if step is not None:
            self._last_snapshot_step = step
        rec = self.registry.emit_snapshot(self._metrics_f, step=step)
        try:
            self.registry.write_prometheus(self._prom_path)
        except OSError as e:
            print(f"[rank {self.rank}] metrics.prom write failed: {e!r}",
                  file=sys.stderr, flush=True)
        return rec

    def close(self) -> None:
        """Final snapshot, span summary, health-thread shutdown.
        Idempotent; must run even when training raises (the driver's
        ``finally``)."""
        if self._closed:
            return
        self.snapshot(step=None)
        self._closed = True
        if self.spans is not None:
            if _spans_mod.current() is self.spans:
                _spans_mod.set_current(None)
            self.spans.close()
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.heartbeat is not None:
            self.heartbeat.stop()
        if self._metrics_f is not None:
            self._metrics_f.close()
            self._metrics_f = None
