"""Observability subsystem: metrics registry, span tracing, analytic
comm accounting, and run health (heartbeat + stall watchdog).

The reference's observability was ``lib/recorder.py``'s host wall-clock
brackets; on TPU the collective is fused inside one XLA program, so
this package supplies what host brackets cannot (SURVEY.md §5.1,
ISSUE 1):

- :mod:`~theanompi_tpu.obs.metrics` — labeled counters/gauges/
  histograms, Prometheus text exposition + JSONL snapshots;
- :mod:`~theanompi_tpu.obs.spans` — nestable trace spans with a
  per-rank JSONL log and a run-end time-fraction summary;
- :mod:`~theanompi_tpu.obs.comm` — closed-form bytes-on-the-wire per
  step for every sync rule (the comm-side peer of utils/flops.py MFU);
- :mod:`~theanompi_tpu.obs.health` — heartbeat files + a stall
  watchdog that dumps thread stacks and arms a post-mortem device
  trace when the global step stops advancing;
- :mod:`~theanompi_tpu.obs.numerics` — in-graph numerics sentinels
  (grad/update/param norms, fused non-finite count, per-rule
  divergence gauges) + host-side EWMA/NaN anomaly detection evaluated
  at dispatch-drain time;
- :mod:`~theanompi_tpu.obs.flight` — flight recorder: bounded ring of
  the last N drained step records, dumped as an ``anomaly_rank{r}/``
  triage bundle when a sentinel fires or the stall watchdog trips.

:class:`Observability` is the driver-facing facade
(``launch/worker.py``): one object that owns the per-run registry, the
span recorder, the health threads, and the snapshot cadence — and that
collapses to near-zero-cost no-ops when ``obs_dir`` is None, so the
training loop carries no conditionals.

On-disk layout under ``obs_dir`` (schemas:
``theanompi_tpu/tools/check_obs_schema.py``)::

    metrics.jsonl           rank-0 metric snapshots (kind=metrics) +
                            one kind=comm record per run: the engine's
                            declared wire model — rule, wire codec,
                            raw_bytes vs wire_bytes (sustained
                            per-step, fp32 vs post-codec) and their
                            compression_ratio; on a multislice mesh the
                            comm record also splits the raw AND
                            effective bytes by link class — ici_bytes /
                            dcn_bytes (effective, post-codec on the DCN
                            hop) and raw_ici_bytes / raw_dcn_bytes —
                            matching the tmpi_comm_ici_bytes_per_step /
                            tmpi_comm_dcn_bytes_per_step (+ raw_*)
                            gauges and the achieved tmpi_comm_ici_gbps /
                            tmpi_comm_dcn_gbps pair the step cadence
                            refreshes; snapshots also carry
                            the tmpi_comm_raw_bytes_per_step /
                            tmpi_comm_compression_ratio /
                            tmpi_comm_gbps_raw gauges next to the
                            effective tmpi_comm_* family; an elastic
                            resume that resharded a checkpoint onto a
                            changed mesh adds one kind=reshard record
                            (from_world/to_world, wall seconds, leaf
                            count, per-replica batch) next to the
                            tmpi_reshard_seconds / tmpi_reshards_total
                            gauges; runs whose engine declared a cost
                            model (obs/attribution.py) add one
                            kind=profile record per snapshot — the
                            step-time attribution: measured
                            step_seconds, the compute/comm/host/
                            residual fractions (sum 1.0 by
                            construction), roofline classification
                            (compute/hbm/comm/host-bound), mfu (or
                            mfu_calibrated on spec-less devices) and
                            achieved hbm_gbps — next to the live
                            tmpi_mfu / tmpi_hbm_gbps /
                            tmpi_step_*_frac gauges the dispatcher's
                            drain cadence refreshes; a `tmpi preflight`
                            run with --obs-dir appends one
                            kind=preflight record (model/engine/codec/
                            fused config, PREDICTED per-device
                            peak_bytes from the lowered-not-executed
                            step, budget + fit verdict when a budget
                            exists) next to a snapshot carrying the
                            tmpi_preflight_peak_bytes /
                            tmpi_preflight_fit gauges — the memory
                            trajectory tools/perf_gate.py gates via
                            its preflight_peak_bytes invariant; runs
                            with a checkpoint scrubber active
                            (--scrub-interval, or the supervisor's
                            retry-time pass) add one kind=scrub record
                            per pass that ran — members checked,
                            corrupt count, the quarantined filenames
                            (comma-joined), pass seconds — next to the
                            tmpi_scrub_checked / tmpi_scrub_runs_total
                            / tmpi_scrub_quarantined_total gauges; a
                            `tmpi lint --obs-dir` run appends one
                            kind=shard record per analyzed engine x
                            codec x fused config (tools/analyze/
                            sharding.py): leaf counts, declared-vs-
                            compiled mismatches, and the GSPMD-inserted
                            hidden-collective bytes next to the
                            compiled/traced/declared wire totals —
                            the sharding analyzer's lint-report line;
                            the model-drift watchdog (obs/drift.py)
                            appends change-gated kind=drift records —
                            per-model EWMA relative error of predicted
                            vs measured (model_err_cost / model_err_
                            traffic / model_err_memory, matching the
                            tmpi_model_err_* gauges perf_gate diffs),
                            the worst-offending component per model
                            (per-link for traffic, per-leaf-family for
                            memory), the tolerance band, and the
                            breached sources comma-joined — one line
                            whenever an EWMA moves at the third
                            decimal or the breached set changes
    chaos.jsonl             chaos campaign log (tools/chaos.py, written
                            under the campaign's --out dir): one
                            kind=chaos record per fuzzed fault
                            schedule — seed, config, the schedule
                            itself, ok/violations verdict from the
                            invariant oracle, run count, and (for a
                            failing schedule) the shrunken minimal
                            repro as a ready-to-paste --inject-fault
                            line
    metrics.prom            rank-0 Prometheus text exposition (atomic)
    spans_rank{r}.jsonl     per-rank span + span_summary lines
    heartbeat_rank{r}.json  per-rank liveness (atomic rewrite; carries
                            dispatch_in_flight + last_drained_step so a
                            wedged device program — drains stop, ring
                            full — reads apart from a stalled host
                            driver, whose dispatches stop too)
    stall_rank{r}.json/.txt stall watchdog reports (thread stacks)
    postmortem_rank{r}/     jax.profiler trace armed at stall time
    numerics_rank{r}.jsonl  kind=numerics sentinel rows (one per
                            drained numerics step: tmpi gauge values
                            under ``metrics``, non-finite keys named in
                            ``nonfinite_keys``) + kind=anomaly records
                            + kind=rollback records (one per
                            ``--on-anomaly rollback`` restore: the
                            anomalous step, the verified checkpoint
                            step restored, budget left, batches
                            skipped)
    supervisor.jsonl        kind=retry records from the run supervisor
                            (launch/supervisor.py): one per failed or
                            preempted attempt — attempt index, the
                            verified resume-from step, the error, the
                            backoff applied, and the attempt's device
                            world size; elastic supervision adds one
                            kind=topology record per attempt (world +
                            prev_world: the probed device count each
                            attempt ran in, so the file alone shows
                            topology across retries); the supervisor
                            also appends a final kind=metrics snapshot
                            (source="supervisor") carrying
                            tmpi_retries_total to metrics.jsonl
    fleet.jsonl             fleet telemetry plane (obs/fleet.py): one
                            kind=fleet record per CHANGED merged view
                            (fleet step advance, or the straggler/
                            frozen/missed/skewed rank sets changing) —
                            fleet max step + spread, the step-time
                            distribution over ranks (min/p50/p99/max
                            of each rank's EWMA), slowest rank,
                            rank-id flag lists comma-joined, MFU
                            min/median, comm GB/s with its link class
                            (ici, or dcn on a multislice mesh).
                            Written only by a record-writing
                            FleetTailer — in practice the chief's
                            fleet exporter (obs/exporter.py), started
                            chief-only by --fleet-exporter-port (or
                            once per supervised run, outside the
                            retry loop) and stopped in the worker/
                            supervisor shutdown path after obs.close();
                            its tmpi-fleet-tail thread tails every
                            per-rank stream above byte-offset-
                            incrementally and its tmpi-fleet-exporter
                            thread serves /metrics (tmpi_fleet_*
                            Prometheus), /fleet.json and /healthz.
                            `tmpi top` reads the same streams but
                            NEVER writes this file (viewers must not
                            grow the dir they watch)
    serve.jsonl             serving engine telemetry (serve/engine.py,
                            written when ``tmpi serve`` runs with
                            --obs-dir): periodic + drain-time
                            kind=serve stats records (params step,
                            tmpi_serve_* latency p50/p99, queue depth,
                            batch fill, request totals) + one
                            kind=reload record per checkpoint
                            hot-reload the engine applied
    serve_r{N}.jsonl        per-replica member telemetry when ``tmpi
                            serve --replicas N`` runs a fleet
                            (serve/router.py): the same kind=serve
                            records as serve.jsonl, each stamped with
                            its ``replica_id`` — one file per member,
                            restarted members append to their
                            predecessor's file
    router.jsonl            replica-group router stream
                            (serve/router.py): kind=router health
                            transitions (healthy→down→restarting→
                            healthy), failover records (the in-flight
                            request's from/to replica), restart /
                            restart_failed records with the
                            decorrelated-jitter backoff drawn, drop
                            records (terminal failover failures — the
                            chaos oracle's zero-drop invariant watches
                            these), the drain-time kind=router
                            snapshot carrying the tmpi_router_* gauge
                            family, and one kind=reload record per
                            CENTRAL hot-reload fanned out to the
                            fleet; ``tmpi report`` adopts these into
                            its causal timeline (a replica restart
                            adopts the crash/failover chain that
                            preceded it)
    anomaly_rank{r}/        flight-recorder triage bundle (ring.jsonl,
                            report.json, stacks.txt, span_summary.json,
                            optional state/ checkpoint + postmortem/
                            trace) — written once per run at the FIRST
                            anomaly; a stall-watchdog trip writes its
                            own anomaly_rank{r}-stall/ bundle, and a
                            model-drift tolerance breach (obs/drift.py)
                            its own anomaly_rank{r}-drift/ bundle, so
                            neither consumes the anomaly's forensic
                            budget

``tmpi report OBS_DIR`` (tools/report.py) is the read-only post-mortem
over everything above: it merges every per-rank stream into one
monotonic event timeline, causally groups incidents (a retry adopts the
crash/anomaly/reshard evidence that precedes it), and renders the run
summary + drift trajectory + final verdict — like ``tmpi top``, it
never writes the dir it reads.

Every file above is schema-linted by ``tmpi lint`` (tools/lint.py),
whose ``--json`` report carries one SCHEMA001 finding per invalid
record — the same pass that statically cross-checks the declared
``kind=comm`` wire models against each engine's traced collective
schedule (rules SPMD101/SPMD102), so the telemetry this layout
promises cannot silently drift from the programs that emit it.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

from theanompi_tpu.obs import spans as _spans_mod
from theanompi_tpu.obs.comm import (  # noqa: F401
    TrafficModel,
    bsp_traffic,
    easgd_traffic,
    gosgd_traffic,
    nd_traffic,
    pytree_num_elements,
    zero1_traffic,
)
from theanompi_tpu.obs.drift import DriftWatchdog  # noqa: F401
from theanompi_tpu.obs.flight import FlightRecorder, sanitize_record  # noqa: F401
from theanompi_tpu.obs.health import Heartbeat, StallWatchdog  # noqa: F401
from theanompi_tpu.obs.metrics import (  # noqa: F401
    REGISTRY,
    MetricsRegistry,
    result_to_snapshot,
)
from theanompi_tpu.obs.numerics import (  # noqa: F401
    AnomalyDetector,
    NumericsAnomaly,
    NumericsModel,
    RollbackRequested,
)
from theanompi_tpu.obs.spans import SpanRecorder, obs_span  # noqa: F401

ANOMALY_POLICIES = ("record", "dump", "halt", "rollback")


class Observability:
    """Per-run facade over the obs modules (see module docstring).

    ``snapshot_freq``: write a metrics snapshot (JSONL + prom rewrite)
    every N completed steps; 0 = only at epoch boundaries/close (the
    driver calls :meth:`snapshot` at epoch end regardless).
    ``stall_timeout``: seconds without step progress before the
    watchdog fires; 0 disables it. Set it ABOVE the worst expected
    compile/eval pause — the watchdog only learns of progress through
    :meth:`on_step`, so a first-epoch XLA compile longer than the
    timeout reads as a stall.
    """

    def __init__(
        self,
        obs_dir: Optional[str],
        *,
        rank: int = 0,
        stall_timeout: float = 0.0,
        snapshot_freq: int = 0,
        heartbeat_interval: float = 5.0,
        arm_profiler: bool = True,
        numerics_freq: int = 0,
        flight_window: int = 64,
        on_anomaly: str = "dump",
        drift_tolerance: float = 0.25,
    ):
        if on_anomaly not in ANOMALY_POLICIES:
            raise ValueError(
                f"on_anomaly must be one of {ANOMALY_POLICIES}, "
                f"got {on_anomaly!r}"
            )
        self.obs_dir = obs_dir
        self.rank = rank
        self.enabled = obs_dir is not None
        self.snapshot_freq = max(0, int(snapshot_freq))
        self.numerics_freq = max(0, int(numerics_freq))
        self.on_anomaly = on_anomaly
        self.registry = MetricsRegistry()
        self.spans: Optional[SpanRecorder] = None
        self.heartbeat: Optional[Heartbeat] = None
        self.watchdog: Optional[StallWatchdog] = None
        self.traffic: Optional[TrafficModel] = None
        self.numerics: Optional[NumericsModel] = None
        self.flight: Optional[FlightRecorder] = None
        # step-time attribution (obs/attribution.py): the engine's
        # compiled-step cost model, the dispatcher handle the live
        # host-blocked fraction reads off, and the newest attribution
        # (refreshed at each drain sync, emitted at snapshot time)
        self.cost = None
        self._disp = None
        self._host_mark: Optional[tuple] = None  # (blocked_s, wall_t)
        self._last_attr = None
        # model-drift watchdog (obs/drift.py): per-model EWMA relative
        # error of predicted vs measured, refreshed at the same drain
        # cadence as attribution; fed the memory_model() declaration via
        # set_memory_model. _last_step gives its records a step number
        # (note_step_seconds arrives from the dispatcher without one).
        self.memory = None
        self.drift = DriftWatchdog(tolerance=drift_tolerance, rank=rank)
        self._last_step = 0
        # detection is a host-side float check per drained row — active
        # whenever sentinels are requested, even with no obs_dir (the
        # halt policy must work without telemetry output)
        self.detector = (
            AnomalyDetector() if self.numerics_freq > 0 else None
        )
        self.anomaly_count = 0
        self._anomaly_lines = 0
        self._anomaly_lines_max = 200  # NaN persists once params poison:
        # cap the per-rank anomaly log rather than writing one line per
        # step for the rest of the run
        self._metrics_f = None
        # serializes metrics.jsonl writes: the checkpoint scrubber's
        # kind=scrub records arrive from its background thread while
        # the driver thread snapshots
        self._metrics_lock = threading.Lock()
        self._numerics_f = None
        self._prom_path = None
        self._last_snapshot_step = 0
        self._closed = False
        if not self.enabled:
            return
        os.makedirs(obs_dir, exist_ok=True)
        self.spans = SpanRecorder(
            os.path.join(obs_dir, f"spans_rank{rank}.jsonl"), rank=rank
        )
        # install as the process-current recorder so deep layers
        # (utils/checkpoint.py, data/loader.py) can open spans without
        # plumbing a handle through every signature
        _spans_mod.set_current(self.spans)
        if rank == 0:
            # one metrics sink per run (reference: rank-0 recorder save)
            self._metrics_f = open(os.path.join(obs_dir, "metrics.jsonl"), "a")
            self._prom_path = os.path.join(obs_dir, "metrics.prom")
        if flight_window and flight_window > 0:
            self.flight = FlightRecorder(
                obs_dir, rank=rank, window=flight_window,
                arm_profiler=arm_profiler,
            )
            self.flight.spans = self.spans
        self.heartbeat = Heartbeat(obs_dir, rank=rank,
                                   interval=heartbeat_interval)
        if stall_timeout and stall_timeout > 0:
            flight = self.flight

            def on_stall(report: dict) -> None:
                # a tripped watchdog is a flight-dump trigger too: the
                # ring holds the last healthy steps before the hang.
                # No state save (a wedged device cannot be fetched) and
                # no second profiler arm (the watchdog armed one).
                if flight is not None:
                    flight.dump("stall", step=report.get("step"),
                                include_state=False, arm_profiler=False)

            self.watchdog = StallWatchdog(
                stall_timeout, obs_dir, rank=rank, arm_profiler=arm_profiler,
                on_stall=on_stall,
            )

    # -- driver hooks --------------------------------------------------------
    def set_traffic_model(self, tm: Optional[TrafficModel]) -> None:
        """Record the active sync rule's analytic wire model (engine-
        declared; see each engine's ``traffic_model``) as gauges, so
        every snapshot carries the per-step comm bytes next to the
        measured throughput — raw AND effective (post-codec), plus one
        ``kind=comm`` JSONL record naming the codec (strings cannot
        ride the numeric gauge map)."""
        self.traffic = tm
        if tm is None or not self.enabled:
            return
        for key, value in tm.as_metrics().items():
            self.registry.gauge(
                f"tmpi_{key}",
                help=f"analytic {tm.rule} wire model (obs/comm.py)",
            ).set(value)
        self.registry.gauge(
            "tmpi_comm_n_workers", help="sync-rule worker count"
        ).set(tm.n_workers)
        if self._metrics_f is not None:
            # one comm record per declaration (schema:
            # tools/check_obs_schema.py kind=comm): the codec proof line
            # bench --codec-sweep and plot_history read back
            import json as _json
            import time as _time

            line = _json.dumps({"t": _time.time(), **tm.as_record()})
            # under the sink lock: the scrubber thread's kind=scrub
            # records share this file (RACE002 — a lock only some
            # writers take protects nothing)
            with self._metrics_lock:
                if not self._closed and self._metrics_f is not None:
                    self._metrics_f.write(line + "\n")
                    self._metrics_f.flush()

    def set_numerics_model(self, nm: Optional["NumericsModel"]) -> None:
        """Record the active rule's numerics declaration (engine-
        declared ``numerics_model()``, the ``traffic_model`` peer) as
        gauges, so snapshots say which sentinels ride the steps and
        whether a divergence gauge exists for this rule."""
        self.numerics = nm
        if nm is None or not self.enabled:
            return
        for key, value in nm.as_metrics().items():
            self.registry.gauge(
                f"tmpi_{key}",
                help=f"{nm.rule} numerics declaration (obs/numerics.py)",
            ).set(value)
        self.registry.gauge(
            "tmpi_numerics_freq",
            help="sentinel cadence (steps; 0 = numerics off)",
        ).set(self.numerics_freq)

    def set_cost_model(self, cm) -> None:
        """Record the engine's compiled-step cost model (utils/flops.py
        ``CostModel``, engine-declared via ``cost_model()``) as static
        ``tmpi_cost_*`` gauges and arm the live attribution path: every
        dispatcher drain sync then refreshes ``tmpi_mfu`` /
        ``tmpi_hbm_gbps`` / ``tmpi_step_*_frac`` (obs/attribution.py)
        from values the drain already fetched — zero new host syncs."""
        self.cost = cm
        if cm is None or not self.enabled:
            return
        for key, value in cm.as_metrics().items():
            self.registry.gauge(
                f"tmpi_{key}",
                help="compiled-step cost model (utils/flops.py)",
            ).set(value)

    def set_memory_model(self, mm) -> None:
        """Record the engine's declared state residency (utils/flops.py
        ``MemoryModel``, engine-declared via ``memory_model()``) as
        static ``tmpi_memory_*`` gauges, and hand it to the drift
        watchdog as the predicted HBM high-water its measured
        counterpart (``device.memory_stats()``) is diffed against."""
        self.memory = mm
        if mm is None or not self.enabled:
            return
        self.registry.gauge(
            "tmpi_memory_state_bytes_per_device",
            help="declared per-device persistent state bytes "
                 "(utils/flops.py MemoryModel)",
        ).set(int(mm.state_bytes_per_device))
        self.registry.gauge(
            "tmpi_memory_n_devices", help="memory-model device count",
        ).set(int(mm.n_devices))

    def set_flight_state_saver(self, saver) -> None:
        """Install the driver's ``saver(dump_dir)`` that checkpoints the
        current train state into an anomaly bundle (skipped for
        stall-triggered dumps — a wedged device cannot be fetched)."""
        if self.flight is not None:
            self.flight.state_saver = saver

    def attach_dispatcher(self, disp) -> None:
        """Expose the dispatch pipeline's live counters through the
        heartbeat: ``dispatch_in_flight`` + ``last_drained_step`` let a
        stall-report reader tell a wedged DEVICE program (dispatches
        advance then stop with the ring pinned full) from a stalled
        HOST driver (dispatches stop, in-flight falls to zero)."""
        # also the live host-blocked source for step attribution: the
        # drain-window delta of host_blocked_s is the measured per-step
        # host tax (obs/attribution.py books it as the host fraction)
        self._disp = disp
        if self.heartbeat is not None:
            self.heartbeat.set_extra(
                lambda: {"dispatch_in_flight": int(disp.in_flight),
                         "last_drained_step": int(disp.last_drained_step)}
            )

    def on_row(self, step: int, metrics: dict, numerics: dict) -> None:
        """Per drained row (utils/dispatch.py ``on_row``): feed the
        flight ring, refresh the sentinel gauges, and run anomaly
        detection — all on host floats the drain already fetched, so
        the hot loop gains zero syncs. Raises :class:`NumericsAnomaly`
        under ``--on-anomaly halt`` (after the dump landed)."""
        rec = sanitize_record(self.rank, step, {**metrics, **numerics})
        if self.flight is not None:
            self.flight.record(rec)
        if numerics and self.enabled:
            for k, v in numerics.items():
                self.registry.gauge(
                    f"tmpi_{k}", help="in-graph numerics sentinel "
                                      "(obs/numerics.py)"
                ).set(v)
        if numerics:
            self._write_numerics_line(rec)
        if self.detector is None:
            return
        anomalies = self.detector.observe(step, metrics, numerics)
        if anomalies:
            self._handle_anomalies(step, anomalies)

    def check_val_metrics(self, epoch: int, step: int, metrics: dict) -> None:
        """Epoch-end hook: a non-finite validation metric is an anomaly
        too (a train-side NaN can slip between sentinel steps when
        ``--numerics-freq > 1``; the val epoch always sees it)."""
        if self.detector is None:
            return
        import math as _math

        bad = {k: v for k, v in metrics.items()
               if not _math.isfinite(float(v))}
        if bad:
            self._handle_anomalies(step, [
                {"metric": f"val_{k}", "reason": "nonfinite",
                 "value_repr": repr(float(v)), "step": int(step),
                 "epoch": int(epoch)}
                for k, v in bad.items()
            ])

    def _numerics_sink(self):
        """Lazy-opened per-rank numerics/anomaly JSONL (shared by the
        sentinel-row and anomaly-record writers so the two streams can
        never diverge into different files)."""
        if self._numerics_f is None:
            self._numerics_f = open(
                os.path.join(self.obs_dir,
                             f"numerics_rank{self.rank}.jsonl"), "a"
            )
        return self._numerics_f

    def _write_numerics_line(self, rec: dict) -> None:
        if not self.enabled or self._closed:
            return
        import json as _json

        f = self._numerics_sink()
        f.write(_json.dumps(rec) + "\n")
        f.flush()

    def _handle_anomalies(self, step: int, anomalies: list) -> None:
        self.anomaly_count += len(anomalies)
        if self.enabled:
            self.registry.counter(
                "tmpi_anomalies_total",
                help="numerics anomalies detected at drain time",
            ).inc(len(anomalies))
        import json as _json
        import time as _time

        for a in anomalies:
            if self._anomaly_lines >= self._anomaly_lines_max:
                break
            self._anomaly_lines += 1
            line = {"kind": "anomaly", "rank": self.rank, "t": _time.time(),
                    "policy": self.on_anomaly, **a}
            if self.enabled and not self._closed:
                f = self._numerics_sink()
                f.write(_json.dumps(line) + "\n")
                f.flush()
            else:
                print(f"[rank {self.rank}] numerics anomaly: {line}",
                      file=sys.stderr, flush=True)
        if self.on_anomaly in ("dump", "halt", "rollback") and self.flight is not None:
            self.flight.dump("anomaly", step=step, anomalies=anomalies)
        if self.on_anomaly == "rollback":
            # the driver catches this, restores the last verified
            # checkpoint, and keeps training within its rollback budget
            # (launch/worker.py); escaping it degrades to halt semantics
            raise RollbackRequested(step, anomalies)
        if self.on_anomaly == "halt":
            names = sorted({a["metric"] for a in anomalies})
            raise NumericsAnomaly(
                f"numerics anomaly at step {step}: {names} "
                f"({len(anomalies)} trigger(s); triage bundle: "
                f"{self.flight.dir if self.flight else 'no obs_dir'})"
            )

    def note_reshard(self, step: int, from_world: int, to_world: int,
                     seconds: float, leaves: int,
                     per_replica_batch: Optional[int] = None) -> None:
        """Driver hook (elastic resume, launch/worker.py): one
        checkpoint was resharded onto a different mesh. Sets the
        ``tmpi_reshard_seconds`` gauge, counts ``tmpi_reshards_total``,
        and writes a ``kind=reshard`` JSONL record into metrics.jsonl
        (rank 0) — the per-run proof line the elastic acceptance test
        reads back."""
        if self.enabled:
            self.registry.gauge(
                "tmpi_reshard_seconds",
                help="wall seconds of the last checkpoint reshard "
                     "(elastic resume, utils/checkpoint.load_resharded)",
            ).set(float(seconds))
            self.registry.gauge(
                "tmpi_reshard_world",
                help="device world size after the last elastic reshard",
            ).set(int(to_world))
            self.registry.counter(
                "tmpi_reshards_total",
                help="checkpoints resharded onto a changed mesh "
                     "(elastic resume)",
            ).inc()
        import json as _json
        import time as _time

        line = {"kind": "reshard", "rank": self.rank, "t": _time.time(),
                "step": int(step), "from_world": int(from_world),
                "to_world": int(to_world), "seconds": float(seconds),
                "leaves": int(leaves)}
        if per_replica_batch is not None:
            line["per_replica_batch"] = int(per_replica_batch)
        if self._metrics_f is not None and not self._closed:
            # same sink lock as note_scrub/snapshot: the background
            # scrubber writes this file concurrently
            with self._metrics_lock:
                if not self._closed and self._metrics_f is not None:
                    self._metrics_f.write(_json.dumps(line) + "\n")
                    self._metrics_f.flush()
        else:
            print(f"[rank {self.rank}] elastic reshard: {line}",
                  file=sys.stderr, flush=True)

    def note_scrub(self, result: dict) -> None:
        """Scrubber hook (utils/checkpoint.CheckpointScrubber
        ``on_result``): one keep-chain scrub pass finished. Refreshes
        the ``tmpi_scrub_*`` gauges/counters and writes a ``kind=scrub``
        JSONL record into metrics.jsonl (rank 0) — called from the
        scrubber's background thread, so the metrics sink write is
        lock-serialized against driver-thread snapshots."""
        if self.enabled:
            self.registry.gauge(
                "tmpi_scrub_checked",
                help="keep-chain members verified by the last scrub "
                     "pass (utils/checkpoint.scrub_checkpoint_dir)",
            ).set(int(result["checked"]))
            self.registry.gauge(
                "tmpi_scrub_last_seconds",
                help="wall seconds of the last scrub pass",
            ).set(float(result["seconds"]))
            self.registry.counter(
                "tmpi_scrub_runs_total", help="scrub passes completed",
            ).inc()
            if result["corrupt"]:
                self.registry.counter(
                    "tmpi_scrub_quarantined_total",
                    help="corrupt checkpoint members moved to "
                         "quarantine/ by the scrubber",
                ).inc(int(result["corrupt"]))
        import json as _json
        import time as _time

        line = {"kind": "scrub", "rank": self.rank, "t": _time.time(),
                "checked": int(result["checked"]),
                "corrupt": int(result["corrupt"]),
                "quarantined": ",".join(result["quarantined"]),
                "seconds": float(result["seconds"])}
        if self._metrics_f is not None and not self._closed:
            with self._metrics_lock:
                if not self._closed:
                    self._metrics_f.write(_json.dumps(line) + "\n")
                    self._metrics_f.flush()
        elif result["corrupt"]:
            print(f"[rank {self.rank}] checkpoint scrub: {line}",
                  file=sys.stderr, flush=True)

    def note_rollback(self, anomaly_step: int, restore_step: int,
                      budget_left: int, skipped: int = 0) -> None:
        """Driver hook (``--on-anomaly rollback``, launch/worker.py):
        one restore happened. Counts ``tmpi_rollbacks_total``, writes a
        ``rollback`` JSONL record next to the anomaly records, and
        RESETS the anomaly detector — its EWMA baselines were fed by
        the poisoned steps the restore just erased, and the replayed
        steps must re-warm from clean values."""
        if self.enabled:
            self.registry.counter(
                "tmpi_rollbacks_total",
                help="anomaly rollbacks: restores of the last verified "
                     "checkpoint (--on-anomaly rollback)",
            ).inc()
        if self.detector is not None:
            self.detector = AnomalyDetector()
        import time as _time

        line = {"kind": "rollback", "rank": self.rank, "t": _time.time(),
                "step": int(anomaly_step), "restore_step": int(restore_step),
                "budget_left": int(budget_left), "skipped": int(skipped)}
        if self.enabled and not self._closed:
            self._write_numerics_line(line)
        else:
            print(f"[rank {self.rank}] anomaly rollback: {line}",
                  file=sys.stderr, flush=True)

    def on_step(self, step: int, substeps: int = 1,
                step_seconds: Optional[float] = None) -> None:
        """Per completed dispatch: advance health + comm accounting.
        ``substeps`` > 1 for fused dispatches (one call per group)."""
        self._last_step = int(step)
        if self.heartbeat is not None:
            self.heartbeat.set_step(step)
        if self.watchdog is not None:
            self.watchdog.notify_step(step)
        if not self.enabled:
            return
        self.registry.counter(
            "tmpi_steps_total", help="completed training steps"
        ).inc(substeps)
        if self.traffic is not None:
            per_step = self.traffic.bytes_per_step_amortized
            self.registry.counter(
                "tmpi_comm_bytes_total",
                help="cumulative analytic per-device wire bytes",
            ).inc(per_step * substeps)
            if step_seconds:
                gbps = self.traffic.achieved_gbps(step_seconds / substeps)
                if gbps is not None:
                    self._set_gbps_gauges(gbps, step_seconds / substeps)
        if (
            self.snapshot_freq
            and step - self._last_snapshot_step >= self.snapshot_freq
        ):
            self.snapshot(step=step)

    def note_step_seconds(self, per_step_seconds: Optional[float]) -> None:
        """Refresh the achieved-GB/s gauge — and, when the engine
        declared a cost model, the live MFU / HBM / step-fraction
        attribution gauges — from an amortized per-step time
        (utils/dispatch.py's spaced syncs). Under deferred dispatch
        :meth:`on_step` no longer knows the step time at push time —
        the dispatcher calls this at each sync point instead, so the
        gauges carry the same analytic-models / measured-time reading
        sync mode produced, just on the sync cadence (no new host
        syncs: every input is already host-side)."""
        if not self.enabled or not per_step_seconds:
            return
        if self.traffic is not None:
            gbps = self.traffic.achieved_gbps(per_step_seconds)
            if gbps is not None:
                self._set_gbps_gauges(gbps, per_step_seconds)
        # one host-frac read per drain: _live_host_frac CONSUMES the
        # dispatcher mark, so attribution and the drift watchdog must
        # share the same measured window
        host_frac = self._live_host_frac()
        if self.cost is not None:
            self._note_attribution(per_step_seconds, host_frac)
        self._note_drift(per_step_seconds, host_frac)

    def _live_host_frac(self) -> Optional[float]:
        """Host-blocked fraction of the wall since the previous drain
        sync (dispatcher cumulative counter deltas — measured, free)."""
        import time as _time

        if self._disp is None:
            return None
        now = _time.perf_counter()
        blocked = float(self._disp.host_blocked_s)
        mark, self._host_mark = self._host_mark, (blocked, now)
        if mark is None or now <= mark[1]:
            return None
        return max(0.0, min(1.0, (blocked - mark[0]) / (now - mark[1])))

    def _note_attribution(self, per_step_seconds: float,
                          host_frac: Optional[float]) -> None:
        """Refresh the live attribution gauges (obs/attribution.py) and
        keep the newest decomposition for the snapshot-time
        ``kind=profile`` record. Pure host-side float math per drain."""
        from theanompi_tpu.obs.attribution import attribute_step

        try:
            attr = attribute_step(
                per_step_seconds, cost=self.cost, traffic=self.traffic,
                host_frac=host_frac,
            )
        except Exception:  # noqa: BLE001 — gauges must never kill a drain
            return
        self._last_attr = attr
        for key, value in attr.as_metrics().items():
            self.registry.gauge(
                f"tmpi_{key}",
                help="step-time attribution (obs/attribution.py)",
            ).set(value)

    def _note_drift(self, per_step_seconds: float,
                    host_frac: Optional[float]) -> None:
        """Feed the model-drift watchdog (obs/drift.py) one drain's
        measurements: refresh the ``tmpi_model_err_*`` gauges, append
        the change-gated ``kind=drift`` record (rank 0), and on a
        tolerance breach write a ``drift`` anomaly line + flight bundle
        (``anomaly_rank{r}-drift/``). Runs with ANY subset of the three
        models declared — drift needs no cost model to watch traffic."""
        if (self.cost is None and self.traffic is None
                and self.memory is None):
            return
        try:
            record, breaches = self.drift.observe(
                per_step_seconds, step=self._last_step,
                cost=self.cost, traffic=self.traffic, memory=self.memory,
                host_frac=host_frac,
            )
            for key, value in self.drift.as_metrics().items():
                self.registry.gauge(
                    f"tmpi_{key}",
                    help="EWMA |predicted-measured|/measured of the "
                         "analytic model (obs/drift.py)",
                ).set(value)
        except Exception:  # noqa: BLE001 — gauges must never kill a drain
            return
        import json as _json
        import time as _time

        if record is not None and self._metrics_f is not None \
                and not self._closed:
            line = _json.dumps({**record, "t": _time.time()})
            with self._metrics_lock:
                if not self._closed and self._metrics_f is not None:
                    self._metrics_f.write(line + "\n")
                    self._metrics_f.flush()
        if not breaches:
            return
        anomalies = [
            {"metric": f"model_err_{src}", "reason": "drift",
             "value_repr": repr(float(self.drift.ewma[src])),
             "tolerance": self.drift.tolerance,
             "worst": str(self.drift.worst[src] or ""),
             "step": self._last_step}
            for src in breaches
        ]
        for a in anomalies:
            line = {"kind": "anomaly", "rank": self.rank,
                    "t": _time.time(), "policy": "record", **a}
            if not self._closed:
                f = self._numerics_sink()
                f.write(_json.dumps(line) + "\n")
                f.flush()
        self.registry.counter(
            "tmpi_drift_breaches_total",
            help="model-drift tolerance crossings (obs/drift.py)",
        ).inc(len(anomalies))
        if self.flight is not None:
            # own bundle dir (anomaly_rank{r}-drift/): a drifted model
            # is a finding, not a numerics failure — it must not spend
            # the anomaly path's once-per-run forensic budget
            self.flight.dump("drift", step=self._last_step,
                             anomalies=anomalies, include_state=False)

    def _set_gbps_gauges(self, gbps: float,
                         step_seconds: Optional[float] = None) -> None:
        """Effective GB/s gauge, plus the raw (uncompressed-equivalent)
        companion whenever a codec shrinks the wire — the pair is what
        makes codec runs visually distinguishable in plot_history's
        comm panel. On a multislice model the per-link-class pair
        (``tmpi_comm_ici_gbps`` / ``tmpi_comm_dcn_gbps``) splits the
        achieved rate by the link each byte rides — DCN is the
        oversubscribed hop, so its gauge is the one that saturates
        first."""
        self.registry.gauge(
            "tmpi_comm_gbps",
            help="achieved per-device interconnect GB/s "
                 "(analytic bytes / measured step time)",
        ).set(gbps)
        ratio = self.traffic.compression_ratio
        if ratio != 1.0:
            self.registry.gauge(
                "tmpi_comm_gbps_raw",
                help="GB/s an UNCOMPRESSED (fp32) wire would need for "
                     "the same step time — effective * compression "
                     "ratio (obs/comm.py)",
            ).set(gbps * ratio)
        if step_seconds and self.traffic.dcn_bytes_per_step > 0:
            ici = self.traffic.ici_gbps(step_seconds)
            dcn = self.traffic.dcn_gbps(step_seconds)
            if ici is not None:
                self.registry.gauge(
                    "tmpi_comm_ici_gbps",
                    help="achieved GB/s on in-slice (ICI) hops "
                         "(analytic per-link bytes / measured step time)",
                ).set(ici)
            if dcn is not None:
                self.registry.gauge(
                    "tmpi_comm_dcn_gbps",
                    help="achieved GB/s on cross-slice (DCN) hops "
                         "(analytic per-link bytes / measured step time)",
                ).set(dcn)

    def snapshot(self, step: Optional[int] = None) -> Optional[dict]:
        """Write one metrics snapshot line + refresh the Prometheus
        exposition (rank 0 only; other ranks no-op)."""
        if not self.enabled or self._metrics_f is None or self._closed:
            return None
        if step is not None:
            self._last_snapshot_step = step
        with self._metrics_lock:
            if self._last_attr is not None:
                # one kind=profile record per snapshot: the newest
                # step-time attribution (schema:
                # tools/check_obs_schema.py) — the machine-readable
                # trail tools/perf_gate.py diffs. Written BEFORE the
                # snapshot line: downstream readers (and tests) may
                # treat the file's last record as the metrics snapshot.
                import json as _json

                self._metrics_f.write(_json.dumps(self._last_attr.as_record(
                    step=step if step is not None else self._last_snapshot_step,
                    rank=self.rank,
                    rule=self.traffic.rule if self.traffic is not None else None,
                )) + "\n")
            rec = self.registry.emit_snapshot(self._metrics_f, step=step)
        try:
            self.registry.write_prometheus(self._prom_path)
        except OSError as e:
            print(f"[rank {self.rank}] metrics.prom write failed: {e!r}",
                  file=sys.stderr, flush=True)
        return rec

    def close(self) -> None:
        """Final snapshot, span summary, health-thread shutdown.
        Idempotent; must run even when training raises (the driver's
        ``finally``)."""
        if self._closed:
            return
        self.snapshot(step=None)
        self._closed = True
        if self.spans is not None:
            if _spans_mod.current() is self.spans:
                _spans_mod.set_current(None)
            self.spans.close()
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.heartbeat is not None:
            self.heartbeat.stop()
        if self._metrics_f is not None:
            # under the lock: the scrubber thread may be mid-write
            with self._metrics_lock:
                self._metrics_f.close()
                self._metrics_f = None
        if self._numerics_f is not None:
            self._numerics_f.close()
            self._numerics_f = None
