"""Fleet telemetry plane: cross-rank aggregation over obs dirs.

Every obs artifact this framework writes is per-rank — metrics.jsonl
(chief), ``spans_rank{r}.jsonl``, ``numerics_rank{r}.jsonl``,
``heartbeat_rank{r}.json`` — and the reference inherited Theano-MPI's
shape of per-process logs with no cross-rank view. The north-star
workloads are fleet-sized (serving SLOs, 256-chip multislice with
slice-granularity failure), and their defining pathologies — a
straggler rank stretching every synchronous step, a silently frozen
rank, cross-rank numerics divergence — are *fleet* properties that no
single rank's stream can show. This module is the merge point:

- :class:`FleetTailer` incrementally tails every rank's JSONL streams
  (byte-offset resumable: each refresh reads only the bytes appended
  since the last one, and a truncated/rotated file resets to 0), plus
  the atomic-replace heartbeat files, and folds them into a live
  :class:`FleetView` keyed by step — per-rank step progress, the
  step-time distribution over ranks (p50/p99/max), per-slice rollups
  derived from the checkpoint ``__topology__`` mesh (the ShardingRecipe
  axes: a ``dcn`` axis partitions ranks into slices), and comm GB/s
  tagged with the link class the bytes ride (``dcn`` when the mesh is
  multislice, else ``ici``);
- a straggler/skew detector: each rank's step time keeps an EWMA
  (alpha matching obs/numerics.py's AnomalyDetector) compared against
  the fleet median; a rank whose last ``straggler_windows`` step
  durations ALL exceed ``straggler_factor`` x the fleet median is a
  *persistent* straggler (trailing-window form, so one post-mortem
  refresh over a finished dir reaches the same verdict as a live
  tailer). Numerics skew reuses the ``numerics_model()`` ``nm_*``
  gauges: a rank whose latest gauge sits more than ``skew_factor`` x
  away from the cross-rank median (either side) is flagged;
- the silent-rank detector (the bug this PR fixes): heartbeat files
  are written per rank but nothing ever compared them — a rank whose
  heartbeat went stale (``frozen_after`` seconds behind "now") is
  ``missed``, and stale *while the rest of the fleet advanced past it*
  is ``frozen``. "now" comes from ONE helper (``_now``): wall clock for
  a live tailer; for post-mortem reads, the newest timestamp observed
  anywhere in the dir with forward clock-skew outliers excluded
  (``AHEAD_SKEW_TOL_S`` past the cross-rank median — a rank whose host
  clock ran ahead must not make every healthy peer read as frozen), so
  a finished healthy run does not read as universally frozen;
- ``kind=fleet`` JSONL records (schema: tools/check_obs_schema.py)
  appended to ``<obs_dir>/fleet.jsonl`` on change (step advanced or a
  flag set changed), plus ``tmpi_fleet_*`` gauges in a private
  :class:`~theanompi_tpu.obs.metrics.MetricsRegistry` — the exporter
  (obs/exporter.py) serves that registry as ``/metrics``.

Consumers: ``obs/exporter.py`` (chief HTTP exporter, live),
``tools/top.py`` (``tmpi top``, live or post-mortem), and anything
reading ``fleet.jsonl`` (tools/plot_history.py's fleet panel).

Concurrency: one ``tmpi-fleet-tail`` daemon thread runs the refresh
loop; ``self._lock`` serializes every refresh against viewers, so the
exporter's handler threads and ``stop()`` never observe a half-merged
view. Viewers are read-only (``write_records`` stays False in ``tmpi
top``) — a viewer must never grow the obs dir it is watching.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
import statistics
import threading
import time
from collections import deque
from typing import Optional

from theanompi_tpu.obs.metrics import MetricsRegistry

# EWMA smoothing for per-rank step time — matches the numerics
# AnomalyDetector's default so "persistent" means the same thing in
# both detectors' documentation
EWMA_ALPHA = 0.2
# a rank is straggling when its step time exceeds factor x fleet median
STRAGGLER_FACTOR = 1.5
# ... and PERSISTENTLY so when its last K step durations all do
STRAGGLER_WINDOWS = 3
# heartbeat staleness (seconds behind "now") before a rank is missed
FROZEN_AFTER_S = 30.0
# numerics skew: |gauge| outside [median/factor, median*factor]
SKEW_FACTOR = 10.0
# post-mortem clock-skew guard: a rank whose host clock ran AHEAD of
# its peers (DST shift, unsynced NTP) stamps records from the future;
# taking a plain max over newest-timestamps would adopt that future as
# "now" and read every healthy peer as frozen. Timestamps more than
# this far ahead of the cross-rank median are excluded from the max —
# comfortably above real finish-order spread (seconds to minutes),
# comfortably below any DST/timezone jump (>= 1 h).
AHEAD_SKEW_TOL_S = 600.0

_RANK_FILE_RE = re.compile(r"_rank(\d+)\.jsonl?$")


def _rank_of(path: str) -> Optional[int]:
    m = _RANK_FILE_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def _percentile(values, q: float) -> float:
    """Linear-interpolated q-quantile (0..1) of a small sample."""
    s = sorted(values)
    if not s:
        return 0.0
    k = (len(s) - 1) * q
    lo, hi = math.floor(k), math.ceil(k)
    if lo == hi:
        return float(s[lo])
    return float(s[lo] + (s[hi] - s[lo]) * (k - lo))


def fleet_topology(ckpt_dir: Optional[str]) -> Optional[dict]:
    """The ``__topology__`` manifest off the newest checkpoint in
    ``ckpt_dir``, or None (no dir / no checkpoint / pre-elastic file).
    Best-effort by design: the fleet view degrades to a single-slice
    interpretation, it never blocks on checkpoint state."""
    if not ckpt_dir:
        return None
    try:
        from theanompi_tpu.utils.checkpoint import (
            latest_checkpoint,
            read_topology_manifest,
        )

        path = latest_checkpoint(ckpt_dir)
        return read_topology_manifest(path) if path else None
    except Exception:  # noqa: BLE001 — viewer must survive any ckpt state
        return None


def _n_slices(topology: Optional[dict]) -> int:
    """Slice count from a ``__topology__`` manifest: the size of the
    mesh's ``dcn`` axis when one exists (multislice), else 1."""
    try:
        mesh = (topology or {}).get("mesh") or {}
        axes = list(mesh.get("axes") or [])
        shape = list(mesh.get("shape") or [])
        if "dcn" in axes:
            return max(1, int(shape[axes.index("dcn")]))
    except (TypeError, ValueError, AttributeError):
        pass
    return 1


class _RankState:
    """Mutable per-rank accumulator (plain data; every mutation happens
    under the owning tailer's lock)."""

    def __init__(self, rank: int):
        self._lock = threading.Lock()  # guards the span accumulators
        self.rank = rank
        self.step = -1               # best known absolute step
        self.spanned_steps = 0       # count of name=="step" spans seen
        self.durations = deque(maxlen=64)  # recent step-span durations
        self.ewma: Optional[float] = None  # smoothed step seconds
        self.hb_t: Optional[float] = None  # last heartbeat wall time
        self.hb_step: Optional[int] = None
        self.pid: Optional[int] = None
        self.mfu: Optional[float] = None
        self.anomalies = 0
        self.nm: dict[str, float] = {}     # latest nm_* gauge values
        self.last_t = 0.0            # newest timestamp from this rank

    def note_step_span(self, t0: float, dur: float) -> None:
        with self._lock:
            self.spanned_steps += 1
            self.durations.append(float(dur))
            self.ewma = (
                float(dur) if self.ewma is None
                else (1 - EWMA_ALPHA) * self.ewma + EWMA_ALPHA * float(dur)
            )
            self.step = max(self.step, self.spanned_steps)
            self.last_t = max(self.last_t, t0 + dur)


class FleetView:
    """One merged snapshot of the fleet. ``ranks`` holds one plain-dict
    row per rank (sorted by rank id); aggregate fields mirror the
    ``tmpi_fleet_*`` gauges. Immutable by convention — the tailer
    builds a fresh view per refresh and swaps the reference."""

    def __init__(self, *, t: float, rows: list, step: int,
                 step_spread: int, step_s_min: float, step_s_p50: float,
                 step_s_p99: float,
                 step_s_max: float, slowest_rank: int, stragglers: list,
                 frozen: list, missed: list, skewed: list,
                 mfu_min: Optional[float], mfu_median: Optional[float],
                 comm_gbps: Optional[float], link_class: str,
                 slices: list, retries: int,
                 comm_ici_gbps: Optional[float] = None,
                 comm_dcn_gbps: Optional[float] = None):
        self.t = t
        self.rows = rows
        self.step = step
        self.step_spread = step_spread
        self.step_s_min = step_s_min
        self.step_s_p50 = step_s_p50
        self.step_s_p99 = step_s_p99
        self.step_s_max = step_s_max
        self.slowest_rank = slowest_rank
        self.stragglers = stragglers
        self.frozen = frozen
        self.missed = missed
        self.skewed = skewed
        self.mfu_min = mfu_min
        self.mfu_median = mfu_median
        self.comm_gbps = comm_gbps
        self.link_class = link_class
        self.slices = slices
        self.retries = retries
        self.comm_ici_gbps = comm_ici_gbps
        self.comm_dcn_gbps = comm_dcn_gbps

    @property
    def healthy(self) -> bool:
        """False on missed heartbeats or persistent stragglers — the
        exporter's ``/healthz`` verdict."""
        return not self.missed and not self.stragglers

    def unhealthy_reasons(self) -> list[str]:
        out = []
        if self.missed:
            out.append("missed heartbeat: rank "
                       + ",".join(str(r) for r in self.missed))
        if self.frozen:
            out.append("frozen: rank "
                       + ",".join(str(r) for r in self.frozen))
        if self.stragglers:
            out.append("persistent straggler: rank "
                        + ",".join(str(r) for r in self.stragglers))
        return out

    def as_dict(self) -> dict:
        """JSON-safe form — the exporter's ``/fleet.json`` body."""
        return {
            "t": self.t,
            "step": self.step,
            "n_ranks": len(self.rows),
            "healthy": self.healthy,
            "unhealthy_reasons": self.unhealthy_reasons(),
            "step_spread": self.step_spread,
            "step_seconds": {"min": self.step_s_min,
                             "p50": self.step_s_p50,
                             "p99": self.step_s_p99,
                             "max": self.step_s_max},
            "slowest_rank": self.slowest_rank,
            "stragglers": self.stragglers,
            "frozen": self.frozen,
            "missed": self.missed,
            "skewed": self.skewed,
            "mfu_min": self.mfu_min,
            "mfu_median": self.mfu_median,
            "comm_gbps": self.comm_gbps,
            "comm_ici_gbps": self.comm_ici_gbps,
            "comm_dcn_gbps": self.comm_dcn_gbps,
            "link_class": self.link_class,
            "slices": self.slices,
            "retries": self.retries,
            "ranks": self.rows,
        }

    def record(self) -> dict:
        """The ``kind=fleet`` JSONL record (scalar fields only; rank
        lists comma-joined like scrub's ``quarantined``)."""
        rec = {
            "kind": "fleet",
            "t": self.t,
            "step": int(self.step),
            "ranks": len(self.rows),
            "step_spread": int(self.step_spread),
            "step_seconds_min": self.step_s_min,
            "step_seconds_p50": self.step_s_p50,
            "step_seconds_p99": self.step_s_p99,
            "step_seconds_max": self.step_s_max,
            "slowest_rank": int(self.slowest_rank),
            "straggler_count": len(self.stragglers),
            "stragglers": ",".join(str(r) for r in self.stragglers),
            "frozen": ",".join(str(r) for r in self.frozen),
            "missed": ",".join(str(r) for r in self.missed),
            "skewed": ",".join(str(r) for r in self.skewed),
            "link_class": self.link_class,
            "slices": len(self.slices) or 1,
            "retries": int(self.retries),
        }
        if self.mfu_min is not None:
            rec["mfu_min"] = self.mfu_min
        if self.mfu_median is not None:
            rec["mfu_median"] = self.mfu_median
        if self.comm_gbps is not None:
            rec["comm_gbps"] = self.comm_gbps
        if self.comm_ici_gbps is not None:
            rec["comm_ici_gbps"] = self.comm_ici_gbps
        if self.comm_dcn_gbps is not None:
            rec["comm_dcn_gbps"] = self.comm_dcn_gbps
        return rec


class FleetTailer:
    """Incremental multi-rank telemetry tailer over one obs dir.

    ``live=True`` (the exporter) measures heartbeat staleness against
    wall clock; ``live=False`` (post-mortem ``tmpi top --once``)
    measures it against the newest timestamp in the dir, so a finished
    run keeps its in-run verdicts. ``write_records=True`` additionally
    appends ``kind=fleet`` records to ``<obs_dir>/fleet.jsonl`` — keep
    it False in viewers (``tmpi top`` must not grow the dir it reads).
    """

    def __init__(self, obs_dir: str, *, topology: Optional[dict] = None,
                 live: bool = False, write_records: bool = False,
                 straggler_factor: float = STRAGGLER_FACTOR,
                 straggler_windows: int = STRAGGLER_WINDOWS,
                 frozen_after: float = FROZEN_AFTER_S,
                 skew_factor: float = SKEW_FACTOR):
        self.obs_dir = obs_dir
        self.topology = topology
        self.live = bool(live)
        self.write_records = bool(write_records)
        self.straggler_factor = float(straggler_factor)
        self.straggler_windows = max(1, int(straggler_windows))
        self.frozen_after = float(frozen_after)
        self.skew_factor = float(skew_factor)
        self.registry = MetricsRegistry()
        self._fleet_path = os.path.join(obs_dir, "fleet.jsonl")
        # RLock: refresh() holds it across the whole scan+detect pass
        # while the helpers it calls re-acquire at their write sites
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._offsets: dict[str, int] = {}   # byte offset per tailed file
        self._ranks: dict[int, _RankState] = {}
        self._comm_gbps: Optional[float] = None
        self._comm_ici_gbps: Optional[float] = None
        self._comm_dcn_gbps: Optional[float] = None
        self._retries = 0
        self._refresh_errors = 0
        self._emitted_sig: Optional[tuple] = None
        self._view: Optional[FleetView] = None
        self._closed = False

    # -- lifecycle ----------------------------------------------------------
    def start(self, interval: float = 2.0) -> "FleetTailer":
        """Spawn the ``tmpi-fleet-tail`` daemon refresh loop."""
        with self._lock:
            if self._thread is not None or self._closed:
                return self
            self._interval = max(0.2, float(interval))
            t = threading.Thread(target=self._tail_loop,
                                 name="tmpi-fleet-tail", daemon=True)
            self._thread = t
        t.start()
        return self

    def _tail_loop(self) -> None:
        # immediate first refresh: the exporter's endpoints answer with
        # real data as soon as the server binds, not an interval later
        while True:
            try:
                self.refresh()
            except Exception:  # noqa: BLE001 — the loop must outlive
                # any malformed telemetry line or racing writer; the
                # error count is surfaced as a gauge, not a crash
                with self._lock:
                    self._refresh_errors += 1
            if self._stop.wait(self._interval):
                return

    def stop(self) -> None:
        """Idempotent: signal the loop, join it, mark closed."""
        self._stop.set()
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        with self._lock:
            self._closed = True

    close = stop

    # -- tailing ------------------------------------------------------------
    def _read_new_lines(self, path: str) -> list:
        """Parsed rows appended to ``path`` since the last read.
        Byte-offset resumable; a file that shrank (truncate/rotate)
        re-reads from 0; a partial trailing line (a writer mid-append)
        stays unconsumed until its newline lands."""
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return []
        try:
            size = os.fstat(fd).st_size
            off = self._offsets.get(path, 0)
            if size < off:
                off = 0
            data = os.pread(fd, size - off, off) if size > off else b""
        except OSError:
            return []
        finally:
            os.close(fd)
        cut = data.rfind(b"\n")
        if cut < 0:
            return []
        with self._lock:
            self._offsets[path] = off + cut + 1
        rows = []
        for line in data[:cut + 1].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                continue
        return rows

    def _rank(self, r: int) -> _RankState:
        st = self._ranks.get(r)
        if st is None:
            with self._lock:
                st = self._ranks.setdefault(r, _RankState(r))
        return st

    def _scan(self) -> None:
        """One incremental pass over every telemetry stream in the dir."""
        base = self.obs_dir
        for path in sorted(glob.glob(os.path.join(base, "spans_rank*.jsonl"))):
            rank = _rank_of(path)
            for row in self._read_new_lines(path):
                if row.get("kind") == "span" and row.get("name") == "step" \
                        and not row.get("amortized"):
                    r = row.get("rank", rank)
                    if isinstance(r, int):
                        try:
                            self._rank(r).note_step_span(
                                float(row["t0"]), float(row["dur"]))
                        except (KeyError, TypeError, ValueError):
                            continue
        for path in sorted(glob.glob(os.path.join(base,
                                                  "numerics_rank*.jsonl"))):
            rank = _rank_of(path)
            for row in self._read_new_lines(path):
                self._ingest_numerics(row, rank)
        for row in self._read_new_lines(os.path.join(base, "metrics.jsonl")):
            self._ingest_metrics(row)
        for row in self._read_new_lines(os.path.join(base,
                                                     "supervisor.jsonl")):
            if row.get("kind") == "retry":
                with self._lock:
                    self._retries += 1
        for path in sorted(glob.glob(os.path.join(base,
                                                  "heartbeat_rank*.json"))):
            self._ingest_heartbeat(path)

    def _ingest_numerics(self, row: dict, rank_hint: Optional[int]) -> None:
        kind = row.get("kind")
        r = row.get("rank", rank_hint)
        if not isinstance(r, int):
            return
        st = self._rank(r)
        t = row.get("t")
        if isinstance(t, (int, float)):
            st.last_t = max(st.last_t, float(t))
        if kind == "numerics":
            step = row.get("step")
            if isinstance(step, int):
                st.step = max(st.step, step)
            metrics = row.get("metrics")
            if isinstance(metrics, dict):
                for k, v in metrics.items():
                    if isinstance(k, str) and k.startswith("nm_") \
                            and isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        st.nm[k] = float(v)
        elif kind == "anomaly":
            st.anomalies += 1

    def _ingest_metrics(self, row: dict) -> None:
        kind = row.get("kind")
        if kind == "metrics":
            metrics = row.get("metrics")
            if isinstance(metrics, dict):
                for key, attr in (("tmpi_comm_gbps", "_comm_gbps"),
                                  ("tmpi_comm_ici_gbps", "_comm_ici_gbps"),
                                  ("tmpi_comm_dcn_gbps", "_comm_dcn_gbps")):
                    v = metrics.get(key)
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        with self._lock:
                            setattr(self, attr, float(v))
        elif kind == "profile":
            r = row.get("rank")
            if not isinstance(r, int):
                return
            st = self._rank(r)
            step = row.get("step")
            if isinstance(step, int):
                st.step = max(st.step, step)
            mfu = row.get("mfu", row.get("mfu_calibrated"))
            if isinstance(mfu, (int, float)) and not isinstance(mfu, bool):
                st.mfu = float(mfu)
            t = row.get("t")
            if isinstance(t, (int, float)):
                st.last_t = max(st.last_t, float(t))

    def _ingest_heartbeat(self, path: str) -> None:
        # atomic-replace file: re-read whole each refresh (no offsets)
        try:
            with open(path) as f:
                row = json.load(f)
        except (OSError, ValueError):
            return
        r = row.get("rank")
        if not isinstance(r, int):
            return
        st = self._rank(r)
        t, step, pid = row.get("t"), row.get("step"), row.get("pid")
        if isinstance(t, (int, float)):
            st.hb_t = float(t)
            st.last_t = max(st.last_t, float(t))
        if isinstance(step, int):
            st.hb_step = step
            st.step = max(st.step, step)
        if isinstance(pid, int):
            st.pid = pid

    # -- merge + detect -----------------------------------------------------
    def refresh(self) -> FleetView:
        """One scan + detect pass; returns (and retains) the new view."""
        with self._lock:
            self._scan()
            view = self._detect()
            self._view = view
            self._export(view)
            if self.write_records:
                self._maybe_emit(view)
            return view

    def view(self) -> Optional[FleetView]:
        with self._lock:
            return self._view

    def _now(self) -> float:
        """THE clock staleness is judged against — one helper for both
        the silent-rank detector and the per-rank heartbeat-age rows
        (before this helper the two compared against different clocks).
        Live: wall clock. Post-mortem: the newest timestamp observed in
        the dir, with forward outliers excluded (> AHEAD_SKEW_TOL_S
        ahead of the cross-rank median), so one rank whose host clock
        ran ahead cannot freeze every healthy peer."""
        if self.live:
            return time.time()
        newest = [st.last_t for st in self._ranks.values() if st.last_t]
        if not newest:
            return 0.0
        med = statistics.median(newest)
        within = [t for t in newest if t - med <= AHEAD_SKEW_TOL_S]
        return max(within) if within else med

    @staticmethod
    def _heartbeat_age(st: "_RankState", now: float) -> Optional[float]:
        """Seconds since ``st``'s last heartbeat against the _now()
        clock; None when the rank never wrote one. Clamped >= 0: the
        skewed-ahead rank itself reads fresh, never negative."""
        if st.hb_t is None or not now:
            return None
        return max(0.0, now - st.hb_t)

    def _detect(self) -> FleetView:
        now = self._now()
        states = [self._ranks[r] for r in sorted(self._ranks)]
        steps = [st.step for st in states if st.step >= 0]
        fleet_step = max(steps) if steps else -1
        spread = (max(steps) - min(steps)) if steps else 0

        ewmas = {st.rank: st.ewma for st in states if st.ewma is not None}
        med = statistics.median(ewmas.values()) if ewmas else 0.0
        step_samples = list(ewmas.values())
        slowest = max(ewmas, key=ewmas.get) if ewmas else -1

        stragglers, frozen, missed, skewed = [], [], [], []
        for st in states:
            # straggling NOW: smoothed step time vs the fleet median
            st_straggling = bool(
                len(ewmas) >= 2 and med > 0.0 and st.ewma is not None
                and st.ewma >= self.straggler_factor * med
            )
            # PERSISTENT: the last K raw durations all exceeded the
            # threshold — trailing-window form, so a single post-mortem
            # refresh reaches the same verdict as K live windows
            tail = list(st.durations)[-self.straggler_windows:]
            persistent = bool(
                st_straggling and len(tail) >= self.straggler_windows
                and all(d >= self.straggler_factor * med for d in tail)
            )
            if persistent:
                stragglers.append(st.rank)
            # silent-rank detection: heartbeat stale vs the shared
            # _now() clock (same helper the row view renders)
            hb_age = self._heartbeat_age(st, now)
            stale = hb_age is not None and hb_age > self.frozen_after
            if stale:
                missed.append(st.rank)
                if st.step < fleet_step:
                    frozen.append(st.rank)
            st._straggling_now = st_straggling
            st._persistent = persistent
            st._stale = stale

        # numerics skew: per nm_* key with >= 2 reporting ranks,
        # |value| more than skew_factor from the cross-rank median
        keys = set()
        for st in states:
            keys.update(st.nm)
        skewed_set = set()
        for k in keys:
            vals = {st.rank: abs(st.nm[k]) for st in states if k in st.nm}
            if len(vals) < 2:
                continue
            m = statistics.median(vals.values())
            if m <= 0.0:
                continue
            for r, v in vals.items():
                if v > self.skew_factor * m or v * self.skew_factor < m:
                    skewed_set.add(r)
        skewed = sorted(skewed_set)

        mfus = [st.mfu for st in states if st.mfu is not None]
        n_slices = _n_slices(self.topology)
        n_ranks = max(1, len(states))
        link = "dcn" if n_slices > 1 else "ici"
        slices = []
        if states:
            per_slice: dict[int, list] = {}
            for st in states:
                s = st.rank * n_slices // n_ranks if n_slices > 1 else 0
                per_slice.setdefault(s, []).append(st)
            for s in sorted(per_slice):
                members = per_slice[s]
                s_steps = [m.step for m in members if m.step >= 0]
                s_ewmas = [m.ewma for m in members if m.ewma is not None]
                entry = {
                    "slice": s,
                    "ranks": [m.rank for m in members],
                    "step": max(s_steps) if s_steps else -1,
                    "step_seconds_max": max(s_ewmas) if s_ewmas else 0.0,
                    "stragglers": [m.rank for m in members
                                   if m.rank in stragglers],
                    "frozen": [m.rank for m in members if m.rank in frozen],
                }
                if n_slices > 1:
                    # the slice's cross-slice exchange rate: every slice
                    # participates in the same DCN allreduce, so the
                    # chief-reported per-link gauges apply to each
                    if self._comm_dcn_gbps is not None:
                        entry["dcn_gbps"] = self._comm_dcn_gbps
                    if self._comm_ici_gbps is not None:
                        entry["ici_gbps"] = self._comm_ici_gbps
                slices.append(entry)

        rows = []
        for st in states:
            rows.append({
                "rank": st.rank,
                "step": st.step,
                "step_seconds": st.ewma,
                "mfu": st.mfu,
                "anomalies": st.anomalies,
                "heartbeat_t": st.hb_t,
                "heartbeat_age_s": self._heartbeat_age(st, now),
                "pid": st.pid,
                "slice": (st.rank * n_slices // n_ranks
                          if n_slices > 1 else 0),
                "straggling": st._straggling_now,
                "straggler": st._persistent,
                "missed": st._stale,
                "frozen": st.rank in frozen,
                "skewed": st.rank in skewed_set,
            })

        return FleetView(
            t=now, rows=rows, step=fleet_step, step_spread=spread,
            step_s_min=_percentile(step_samples, 0.0),
            step_s_p50=_percentile(step_samples, 0.50),
            step_s_p99=_percentile(step_samples, 0.99),
            step_s_max=_percentile(step_samples, 1.0),
            slowest_rank=slowest, stragglers=stragglers, frozen=frozen,
            missed=missed, skewed=skewed,
            mfu_min=min(mfus) if mfus else None,
            mfu_median=statistics.median(mfus) if mfus else None,
            comm_gbps=self._comm_gbps, link_class=link, slices=slices,
            retries=self._retries,
            comm_ici_gbps=self._comm_ici_gbps,
            comm_dcn_gbps=self._comm_dcn_gbps,
        )

    def _export(self, view: FleetView) -> None:
        """Refresh the ``tmpi_fleet_*`` gauge family from one view."""
        g = self.registry.gauge
        g("tmpi_fleet_ranks", "ranks reporting telemetry").set(len(view.rows))
        g("tmpi_fleet_step", "fleet max step").set(view.step)
        g("tmpi_fleet_step_spread",
          "max-min step over ranks").set(view.step_spread)
        g("tmpi_fleet_slowest_rank",
          "rank with the highest smoothed step time").set(view.slowest_rank)
        g("tmpi_fleet_stragglers",
          "persistent stragglers").set(len(view.stragglers))
        g("tmpi_fleet_frozen",
          "silent ranks behind the fleet").set(len(view.frozen))
        g("tmpi_fleet_missed_heartbeats",
          "ranks with stale heartbeats").set(len(view.missed))
        g("tmpi_fleet_skewed",
          "numerics-skewed ranks").set(len(view.skewed))
        g("tmpi_fleet_healthy", "1 healthy / 0 unhealthy").set(
            1.0 if view.healthy else 0.0)
        g("tmpi_fleet_refresh_errors",
          "suppressed refresh exceptions").set(self._refresh_errors)
        g("tmpi_fleet_retries",
          "supervisor retry records observed").set(view.retries)
        sg = g("tmpi_fleet_step_seconds",
               "step-time distribution over ranks")
        sg.set(view.step_s_min, q="min")
        sg.set(view.step_s_p50, q="p50")
        sg.set(view.step_s_p99, q="p99")
        sg.set(view.step_s_max, q="max")
        if view.mfu_min is not None:
            g("tmpi_fleet_mfu_min", "min MFU over ranks").set(view.mfu_min)
        if view.mfu_median is not None:
            g("tmpi_fleet_mfu_median",
              "median MFU over ranks").set(view.mfu_median)
        if view.comm_gbps is not None:
            g("tmpi_fleet_comm_gbps",
              "achieved collective GB/s by link class").set(
                view.comm_gbps, link=view.link_class)
        if view.comm_ici_gbps is not None:
            g("tmpi_fleet_comm_gbps",
              "achieved collective GB/s by link class").set(
                view.comm_ici_gbps, link="ici")
        if view.comm_dcn_gbps is not None:
            g("tmpi_fleet_comm_gbps",
              "achieved collective GB/s by link class").set(
                view.comm_dcn_gbps, link="dcn")
        rg = g("tmpi_fleet_rank_step", "per-rank step progress")
        for row in view.rows:
            rg.set(row["step"], rank=row["rank"])
        if len(view.slices) > 1:
            slg = g("tmpi_fleet_slice_step", "per-slice max step")
            for s in view.slices:
                slg.set(s["step"], slice=s["slice"])

    def _maybe_emit(self, view: FleetView) -> None:
        """Append one ``kind=fleet`` record on change (first view, step
        advance, or any flag set changing) — a quiet fleet stays quiet
        on disk."""
        sig = (view.step, tuple(view.stragglers), tuple(view.frozen),
               tuple(view.missed), tuple(view.skewed), len(view.rows))
        if sig == self._emitted_sig:
            return
        with self._lock:
            self._emitted_sig = sig
        try:
            with open(self._fleet_path, "a") as f:
                f.write(json.dumps(view.record()) + "\n")
        except OSError:
            return
