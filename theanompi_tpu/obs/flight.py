"""Flight recorder: bounded ring of drained step records + triage dumps.

A numerics anomaly three hours into a pod run is useless as a stack
trace: by the time a human looks, the interesting state — what the last
N steps' losses and grad norms looked like, which step went non-finite,
what every thread was doing — is gone. :class:`FlightRecorder` keeps a
bounded ring of the last ``window`` drained step records (training
metrics + numerics sentinels + divergence gauges, exactly what the
dispatch pipeline drains anyway) and, when a sentinel fires or the
stall watchdog trips, writes a self-contained triage bundle::

    <obs_dir>/anomaly_rank{r}/
        ring.jsonl          the ring contents (kind=numerics records)
        report.json         reason, anomalous step, anomaly list,
                            thread stacks, ring span
        stacks.txt          human-readable thread stacks
        span_summary.json   the span recorder's fractions at dump time
        state/              optional param-state checkpoint (the
                            driver's saver callback; skipped for
                            stall dumps — saving needs a live device)
        postmortem/         armed jax.profiler capture (anomaly dumps
                            only; stall dumps already armed one)

One dump per run PER REASON: the first anomaly is the forensic moment
(later anomalies in the same run are almost always the first one's
fallout), but a benign stall trip — a watchdog timeout sized under a
long compile pause — must not consume the budget a later genuine
numerics anomaly needs. Stall-triggered bundles therefore land in
``anomaly_rank{r}-stall/`` and anomaly bundles keep the pristine
``anomaly_rank{r}/``; subsequent fires of an already-dumped reason
still count and log through the obs facade.

Ring records are schema-valid ``numerics`` lines
(tools/check_obs_schema.py): non-finite values cannot ride a JSON
numeric map, so they are dropped from ``metrics`` and named in the
``nonfinite_keys`` scalar field — the non-finite COUNT sentinel stays
numeric, so the anomalous step remains machine-findable.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from theanompi_tpu.obs.health import arm_profiler_capture, thread_stacks
from theanompi_tpu.obs.metrics import atomic_write_text


def sanitize_record(rank: int, step: int, metrics: dict,
                    t: Optional[float] = None) -> dict:
    """One JSONL-ready ``numerics`` record: finite values only in the
    numeric map, non-finite keys listed in ``nonfinite_keys``."""
    finite: dict[str, float] = {}
    bad: list[str] = []
    for k, v in metrics.items():
        v = float(v)
        if math.isfinite(v):
            finite[k] = v
        else:
            bad.append(k)
    rec = {
        "kind": "numerics",
        "rank": int(rank),
        "t": time.time() if t is None else t,
        "step": int(step),
        "metrics": finite,
    }
    if bad:
        rec["nonfinite_keys"] = ",".join(sorted(bad))
    return rec


class FlightRecorder:
    def __init__(
        self,
        obs_dir: str,
        rank: int = 0,
        window: int = 64,
        arm_profiler: bool = True,
        capture_s: float = 2.0,
        state_saver: Optional[Callable[[str], None]] = None,
    ):
        self.dir = os.path.join(obs_dir, f"anomaly_rank{rank}")
        self.rank = rank
        self.window = max(1, int(window))
        self.arm_profiler = arm_profiler
        self.capture_s = capture_s
        # driver-installed: state_saver(dump_dir) persists the current
        # train state into the bundle (worker.py wires a checkpoint save)
        self.state_saver = state_saver
        self.spans = None  # obs facade installs its SpanRecorder
        self._ring: deque = deque(maxlen=self.window)
        self._lock = threading.Lock()
        self.dump_count = 0
        self._dumped_reasons: set = set()

    def record(self, rec: dict) -> None:
        """Append one drained step record (already sanitized — see
        :func:`sanitize_record`). Called from the dispatcher drain on
        the driver thread; the lock only guards against a concurrent
        watchdog-triggered dump."""
        with self._lock:
            self._ring.append(rec)

    def dump(
        self,
        reason: str,
        step: Optional[int] = None,
        anomalies: Optional[list] = None,
        include_state: bool = True,
        arm_profiler: Optional[bool] = None,
    ) -> Optional[str]:
        """Write the triage bundle; returns its path, or None when this
        run already dumped for this ``reason`` (first fire wins — and a
        benign stall cannot consume a later anomaly's budget: each
        reason owns its own bundle dir). Never raises — forensics must
        not take down the run they describe."""
        with self._lock:
            self.dump_count += 1
            if reason in self._dumped_reasons:
                return None
            # claimed inside the lock (a concurrent watchdog fire must
            # not double-write), RELEASED on failure below — a transient
            # write error (ENOSPC) must not consume the run's only
            # budget for this reason
            self._dumped_reasons.add(reason)
            entries = list(self._ring)
        try:
            return self._write(reason, step, anomalies or [], entries,
                               include_state, arm_profiler)
        except Exception as e:  # noqa: BLE001 — diagnostics only
            import sys

            print(f"[rank {self.rank}] flight dump failed: {e!r}",
                  file=sys.stderr, flush=True)
            with self._lock:
                self._dumped_reasons.discard(reason)
            return None

    def _write(self, reason, step, anomalies, entries, include_state,
               arm_profiler) -> str:
        # each reason owns its bundle: anomalies keep the canonical
        # anomaly_rank{r}/, other triggers (stall) get a -{reason} dir
        out_dir = self.dir if reason == "anomaly" else f"{self.dir}-{reason}"
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "ring.jsonl"), "w") as f:
            for rec in entries:
                f.write(json.dumps(rec) + "\n")
        stacks = thread_stacks()
        report = {
            "reason": reason,
            "rank": self.rank,
            "t": time.time(),
            "step": None if step is None else int(step),
            "anomalies": anomalies,
            "ring_len": len(entries),
            "ring_steps": [r.get("step") for r in entries[:1]]
            + ([r.get("step") for r in entries[-1:]] if len(entries) > 1 else []),
            "stacks": stacks,
        }
        txt = [
            f"FLIGHT DUMP ({reason}) at step {step}, rank {self.rank}",
            "",
            "anomalies:",
        ] + [f"  {a}" for a in anomalies] + [""]
        for name, frames in stacks.items():
            txt.append(f"--- {name} ---")
            txt += frames + [""]
        atomic_write_text(os.path.join(out_dir, "stacks.txt"),
                          "\n".join(txt) + "\n")
        if self.spans is not None:
            try:
                atomic_write_text(
                    os.path.join(out_dir, "span_summary.json"),
                    json.dumps(self.spans.summary()),
                )
            except Exception:  # noqa: BLE001 — spans may already be closed
                pass
        if include_state and self.state_saver is not None:
            state_dir = os.path.join(out_dir, "state")
            try:
                self.state_saver(state_dir)
                report["state_dir"] = state_dir
            except Exception as e:  # noqa: BLE001 — a poisoned device
                # value can make the save itself raise; the ring and
                # stacks are the critical payload
                report["state_error"] = repr(e)
        if (self.arm_profiler if arm_profiler is None else arm_profiler):
            # wait_at_exit: an anomaly dump's runtime is alive (a row
            # just drained from it), and halt exits the process right
            # after — a bounded atexit join lets the capture complete
            # instead of segfaulting mid-trace at interpreter teardown
            report["postmortem_trace"] = arm_profiler_capture(
                os.path.join(out_dir, "postmortem"),
                capture_s=self.capture_s, rank=self.rank, wait_at_exit=True,
            )
        atomic_write_text(os.path.join(out_dir, "report.json"),
                          json.dumps(report))
        import sys

        print(
            f"[rank {self.rank}] FLIGHT RECORDER: {reason} at step {step} — "
            f"triage bundle ({len(entries)} ring records) in {out_dir}",
            file=sys.stderr, flush=True,
        )
        return out_dir
