"""Analytic collective-traffic accounting per sync rule.

On GPU+MPI the reference could WATCH communication (host wall-clock
around ``exchanger.exchange()`` — ``lib/recorder.py``'s 'comm'
bracket); on TPU the collective is fused inside one XLA program, so the
wire volume must be computed, not bracketed. This module holds the
closed-form per-step bytes-on-the-wire for every sync rule, given the
grad/param pytree size and the rule's cadence — the comm-side peer of
``utils/flops.py``'s MFU (EQuARX, PAPERS.md, shows allreduce cost is a
first-order scaling term worth measuring per strategy).

Accounting convention: **bytes sent per device per training step** —
the quantity that divides by step time to give the achieved per-link
interconnect GB/s a chip must sustain (multiply by ``n_workers`` for
pod-total traffic). Formulas (N = elements on the wire, b = bytes per
element after wire compression):

- BSP ring/psum allreduce:   ``2 (n-1)/n · N·b``  (reduce-scatter +
  all-gather halves; XLA's psum lowers to the same ring on ICI)
- ZeRO-1:                    identical — psum_scatter ``(n-1)/n`` +
  all_gather ``(n-1)/n`` over the padded flat buffer (the update
  between the halves is free on the wire)
- EASGD center<->worker:     one psum of the elastic differences every
  ``avg_freq`` steps: ``2 (n-1)/n · N·b`` per exchange, amortized
- GoSGD gossip:              ONE ppermute of the packed
  ``(share·w, share)`` buffer per gossip round: ``(N+1)·b``, amortized
  by ``gossip_every``

**Codec accounting** (parallel/codec.py): every model reports BOTH the
raw (uncompressed fp32) and the effective (post-codec) wire bytes —
``bytes_per_step``/``bytes_per_exchange`` are the EFFECTIVE numbers
(what the gauges divide by step time), ``raw_*`` the fp32 equivalents,
and ``compression_ratio`` their quotient. int8 wire bytes INCLUDE the
per-128-block f32 scale rows (1/32 B per element), so the claimed
>= 3.8x ratio is the honest on-the-wire number.

Known under-counts, flagged in ``detail`` rather than silently wrong:
ring variants pad N up to a segment multiple (accounted), and the ND
engine's activation collectives (tp psum, sp ring/all-to-all, pp
ppermute, MoE all-to-all) are NOT modeled — its figure covers the
dp-axis grad sync only and is marked ``approx``.

**Statically cross-checked** (ISSUE 7): the SPMD analyzer
(tools/analyze/) sums wire bytes from each engine's traced jaxpr and
fails ``tmpi lint`` if these closed forms drift from the program —
raw bytes within tolerance (SPMD101) and, codec-on, the claimed
``compression_ratio`` realized in-graph (SPMD102). Edit a formula here
or an exchange in ``parallel/`` and the other side must follow.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Optional

from theanompi_tpu.parallel.codec import CODEC_WIRE_BYTES, get_codec

# wire bytes per element after each strategy's compression
# (parallel/strategies.py: packed ring variants cast/quantize the wire;
# psum runs in the operand dtype — grads are fp32 here). ring_int8's
# figure includes the packed per-block scale rows (codec layer format).
STRATEGY_WIRE_BYTES = {
    "psum": 4, "ring": 4,
    "psum_bf16": 2, "ring_bf16": 2,
    "ring_int8": CODEC_WIRE_BYTES["int8"],
    # hier's dominant (ICI) wire is fp32; its DCN hop prices separately
    # in bsp_traffic's two-hop model
    "hier": 4,
    # reference aliases (strategies._ALIASES)
    "ar": 4, "cudaaware": 4, "copper": 4, "nccl32": 4,
    "nccl16": 2, "asa32": 4, "asa16": 2,
}


def dcn_fraction(n: int, n_slices: int) -> float:
    """Cross-slice (DCN) share of one hierarchically-lowered n-way
    reduction collective on a slice-major mesh of ``n_slices`` rows x
    ``s = n/n_slices`` chips: the allreduce ``2(n-1)/n·N·b`` lowers to
    ICI ``2(s-1)/s·N·b`` + DCN ``2(r-1)/r·(N/s)·b``, and the one-sided
    RS/AG ``(n-1)/n·N·b`` forms split identically — both give the DCN
    fraction ``(r-1)/(n-1)``. Used to decompose every flat (XLA-lowered)
    collective's declared bytes into link classes; the explicit 'hier'
    strategy prices its two hops directly instead."""
    r = max(1, int(n_slices))
    if n <= 1 or r <= 1:
        return 0.0
    return (r - 1) / (n - 1)


@dataclass
class TrafficModel:
    """Per-device wire volume for one sync rule instance.

    ``bytes_per_step``/``bytes_per_exchange`` are EFFECTIVE (post-
    codec) bytes; ``raw_bytes_per_step``/``raw_bytes_per_exchange``
    the uncompressed fp32 equivalents (default: equal — no codec)."""

    rule: str
    n_workers: int
    bytes_per_step: float  # every-step collectives (in-step grad sync)
    bytes_per_exchange: float = 0.0  # periodic exchange collectives
    exchange_every: int = 0  # steps between exchanges (0 = none)
    codec: str = "none"  # wire codec spec (parallel/codec.py)
    raw_bytes_per_step: Optional[float] = None
    raw_bytes_per_exchange: Optional[float] = None
    # per-link-class accounting (AMORTIZED basis): the cross-slice DCN
    # share of the sustained per-step wire; ICI is the remainder —
    # derived, so the two classes always sum to the totals SPMD101
    # reconciles. 0 on single-slice meshes.
    dcn_bytes_per_step: float = 0.0
    raw_dcn_bytes_per_step: Optional[float] = None
    detail: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.raw_bytes_per_step is None:
            self.raw_bytes_per_step = self.bytes_per_step
        if self.raw_bytes_per_exchange is None:
            self.raw_bytes_per_exchange = self.bytes_per_exchange
        if self.raw_dcn_bytes_per_step is None:
            self.raw_dcn_bytes_per_step = self.dcn_bytes_per_step

    @property
    def bytes_per_step_amortized(self) -> float:
        """Every-step bytes plus the periodic exchange amortized over
        its cadence — the honest sustained per-step wire load."""
        amort = (
            self.bytes_per_exchange / self.exchange_every
            if self.exchange_every else 0.0
        )
        return self.bytes_per_step + amort

    @property
    def raw_bytes_per_step_amortized(self) -> float:
        amort = (
            self.raw_bytes_per_exchange / self.exchange_every
            if self.exchange_every else 0.0
        )
        return self.raw_bytes_per_step + amort

    @property
    def compression_ratio(self) -> float:
        """raw / effective sustained bytes (1.0 = uncompressed; a
        zero-wire rule — single device — reports 1.0 too)."""
        eff = self.bytes_per_step_amortized
        raw = self.raw_bytes_per_step_amortized
        return raw / eff if eff > 0 else 1.0

    @property
    def ici_bytes_per_step(self) -> float:
        """In-slice (ICI) share of the sustained effective wire —
        the amortized total minus the DCN share."""
        return max(0.0,
                   self.bytes_per_step_amortized - self.dcn_bytes_per_step)

    @property
    def raw_ici_bytes_per_step(self) -> float:
        return max(0.0, self.raw_bytes_per_step_amortized
                   - self.raw_dcn_bytes_per_step)

    def achieved_gbps(self, step_seconds: float) -> Optional[float]:
        """Sustained per-device interconnect GB/s implied by a measured
        step time (None when unmeasurable)."""
        if not step_seconds or step_seconds <= 0:
            return None
        return self.bytes_per_step_amortized / step_seconds / 1e9

    def ici_gbps(self, step_seconds: float) -> Optional[float]:
        if not step_seconds or step_seconds <= 0:
            return None
        return self.ici_bytes_per_step / step_seconds / 1e9

    def dcn_gbps(self, step_seconds: float) -> Optional[float]:
        if not step_seconds or step_seconds <= 0:
            return None
        return self.dcn_bytes_per_step / step_seconds / 1e9

    def as_metrics(self) -> dict:
        return {
            "comm_bytes_per_step": self.bytes_per_step,
            "comm_bytes_per_exchange": self.bytes_per_exchange,
            "comm_exchange_every": float(self.exchange_every),
            "comm_bytes_per_step_amortized": self.bytes_per_step_amortized,
            # codec accounting: raw (fp32) wire next to the effective
            # bytes above, plus their quotient — the compression proof
            "comm_raw_bytes_per_step": self.raw_bytes_per_step,
            "comm_raw_bytes_per_step_amortized":
                self.raw_bytes_per_step_amortized,
            "comm_compression_ratio": self.compression_ratio,
            # per-link-class accounting (amortized): ICI + DCN sum to
            # the *_amortized totals above by construction
            "comm_ici_bytes_per_step": self.ici_bytes_per_step,
            "comm_dcn_bytes_per_step": self.dcn_bytes_per_step,
            "comm_raw_ici_bytes_per_step": self.raw_ici_bytes_per_step,
            "comm_raw_dcn_bytes_per_step": self.raw_dcn_bytes_per_step,
        }

    def as_record(self) -> dict:
        """The ``kind=comm`` JSONL record body (schema:
        tools/check_obs_schema.py) — one per run, written when the
        engine declares its wire model."""
        return {
            "kind": "comm",
            "rule": self.rule,
            "codec": self.codec,
            "n_workers": self.n_workers,
            "raw_bytes": self.raw_bytes_per_step_amortized,
            "wire_bytes": self.bytes_per_step_amortized,
            "compression_ratio": self.compression_ratio,
            "ici_bytes": self.ici_bytes_per_step,
            "dcn_bytes": self.dcn_bytes_per_step,
            "raw_ici_bytes": self.raw_ici_bytes_per_step,
            "raw_dcn_bytes": self.raw_dcn_bytes_per_step,
        }


def pytree_num_elements(tree: Any) -> int:
    import jax

    return sum(
        int(math.prod(getattr(l, "shape", ()) or ()))
        for l in jax.tree_util.tree_leaves(tree)
    )


def wire_bytes_per_element(strategy: str) -> int:
    try:
        return STRATEGY_WIRE_BYTES[strategy]
    except KeyError:
        raise ValueError(
            f"unknown strategy {strategy!r} for traffic accounting; "
            f"known: {sorted(STRATEGY_WIRE_BYTES)}"
        ) from None


def allreduce_bytes(n_elements: int, n: int, wire_bytes: int = 4) -> float:
    """Ring allreduce per-device bytes: ``2 (n-1)/n * N * b``."""
    if n <= 1:
        return 0.0
    return 2.0 * (n - 1) / n * n_elements * wire_bytes


def reduce_scatter_bytes(n_elements: int, n: int, wire_bytes: int = 4) -> float:
    if n <= 1:
        return 0.0
    return (n - 1) / n * n_elements * wire_bytes


all_gather_bytes = reduce_scatter_bytes  # same wire volume, other half


def hier_traffic(n_elements: int, n: int, n_slices: int, codec=None,
                 segments: Optional[list] = None,
                 n_buckets: Optional[int] = None,
                 overlap_frac: Optional[float] = None) -> TrafficModel:
    """The explicit two-hop hierarchical exchange ('hier',
    parallel/strategies.py::hierarchical_sync): per flat buffer of
    ``L`` elements on an ``r x s`` mesh (``r = n_slices``,
    ``s = n/n_slices``), ICI moves the reduce-scatter + all-gather
    halves ``2(s-1)/s · s·ceil(L/s) · 4`` B and DCN moves the shard
    allreduce ``2(r-1)/r · ceil(L/s) · b`` B — the codec compresses
    ONLY the DCN hop (the fp32 figure is the raw side). ``segments``:
    the per-bucket flat lengths of a bucketed schedule (each bucket
    pads and scatters independently); default one buffer of
    ``n_elements``."""
    codec = get_codec(codec)
    r = max(1, int(n_slices))
    s = max(1, n // r)
    segs = [-(-int(L) // s) for L in (segments or [n_elements])]
    padded = sum(s * g for g in segs)
    shard = sum(segs)
    ici = (reduce_scatter_bytes(padded, s) + all_gather_bytes(padded, s))
    dcn_raw = allreduce_bytes(shard, r)
    b = codec.wire_bytes_per_element if codec.active else 4.0
    dcn_eff = dcn_raw * b / 4.0
    detail = {"strategy": "hier", "elements": padded,
              "wire_bytes_per_element": b, "n_slices": r,
              "per_slice": s, "dcn_shard_elements": shard}
    if n_buckets is not None:
        detail["n_buckets"] = int(n_buckets)
        detail["overlap_frac"] = float(overlap_frac or 0.0)
    return TrafficModel(
        rule="bsp", n_workers=n,
        bytes_per_step=ici + dcn_eff,
        codec=codec.spec,
        raw_bytes_per_step=ici + dcn_raw,
        dcn_bytes_per_step=dcn_eff,
        raw_dcn_bytes_per_step=dcn_raw,
        detail=detail,
    )


def bsp_traffic(n_elements: int, n: int, strategy: str = "psum",
                codec=None, n_buckets: Optional[int] = None,
                overlap_frac: Optional[float] = None,
                n_slices: int = 1,
                segments: Optional[list] = None) -> TrafficModel:
    """BSP in-step gradient allreduce. Ring variants pad the flat buffer
    to ``n`` equal segments (128-multiples for int8) — accounted, since
    the padding rides the wire. ``codec``: the wire codec the exchange
    runs through (parallel/codec.py) — its bytes-per-element replaces
    the strategy's own when active (psum + codec, or ring whose wire
    the codec selects).

    ``n_buckets``/``overlap_frac`` (``--allreduce-buckets``,
    parallel/strategies.py): the bucketed schedule moves the SAME bytes
    (chunked), so the volume figures are untouched; the geometry lands
    in ``detail`` and ``overlap_frac`` tells the attribution model
    (obs/attribution.py) what fraction of the collective hides under
    backward — so the comm fraction stays honest once comm overlaps.

    ``n_slices``: slice count of a multi-slice mesh. The 'hier'
    strategy routes to the explicit two-hop model (hier_traffic,
    ``segments`` carrying a bucketed schedule's per-bucket lengths);
    flat strategies keep their totals and split them into link classes
    with ``dcn_fraction`` (XLA's hierarchical lowering moves the same
    bytes, re-routed)."""
    if strategy == "hier":
        return hier_traffic(n_elements, n, n_slices, codec=codec,
                            segments=segments, n_buckets=n_buckets,
                            overlap_frac=overlap_frac)
    codec = get_codec(codec)
    b = wire_bytes_per_element(strategy)
    canonical = {"ar": "psum", "cudaaware": "psum", "copper": "psum",
                 "nccl32": "psum", "nccl16": "psum_bf16", "asa32": "ring",
                 "asa16": "ring_bf16"}.get(strategy, strategy)
    if codec.active:
        b = codec.wire_bytes_per_element
        if canonical == "ring":
            canonical = {"bf16": "ring_bf16", "int8": "ring_int8"}[codec.name]
    elems = n_elements
    if n > 1 and (canonical.startswith("ring")
                  or (codec.active and codec.name == "int8")):
        # ring variants pad to n segments; the int8 codec's block layout
        # pads each leaf to 128-lane rows — approximate both with the
        # segment rule (exact for the ring, <=1 row per leaf off for
        # the psum path)
        seg = -(-n_elements // n)
        if canonical == "ring_int8" or codec.name == "int8":
            seg = -(-seg // 128) * 128
        elems = n * seg
    detail = {"strategy": strategy, "elements": elems,
              "wire_bytes_per_element": b}
    if n_buckets is not None:
        detail["n_buckets"] = int(n_buckets)
        detail["overlap_frac"] = float(overlap_frac or 0.0)
    if n_slices > 1:
        detail["n_slices"] = int(n_slices)
    frac = dcn_fraction(n, n_slices)
    return TrafficModel(
        rule="bsp", n_workers=n,
        bytes_per_step=allreduce_bytes(elems, n, b),
        codec=codec.spec,
        raw_bytes_per_step=allreduce_bytes(elems, n),
        dcn_bytes_per_step=allreduce_bytes(elems, n, b) * frac,
        raw_dcn_bytes_per_step=allreduce_bytes(elems, n) * frac,
        detail=detail,
    )


def zero1_traffic(n_elements: int, n: int, codec=None,
                  n_slices: int = 1) -> TrafficModel:
    """ZeRO-1: psum_scatter + all_gather over the flat fp32 buffer
    padded to ``n`` equal segments (parallel/zero.py pads to
    ``n * ceil(P/n)``) — same total wire as the plain allreduce. The
    codec compresses BOTH halves (grad scatter and param gather —
    parallel/zero.py quantizes each with its own error-feedback
    residual), so the full volume shrinks. On a multi-slice mesh the
    scatter/gather halves split into link classes by ``dcn_fraction``
    (same hierarchical lowering as the flat allreduce)."""
    codec = get_codec(codec)
    b = codec.wire_bytes_per_element
    seg = -(-n_elements // n) if n > 1 else n_elements
    padded = n * seg if n > 1 else n_elements
    raw = reduce_scatter_bytes(padded, n) + all_gather_bytes(padded, n)
    frac = dcn_fraction(n, n_slices)
    return TrafficModel(
        rule="zero1", n_workers=n,
        bytes_per_step=raw * b / 4.0,
        codec=codec.spec,
        raw_bytes_per_step=raw,
        dcn_bytes_per_step=raw * b / 4.0 * frac,
        raw_dcn_bytes_per_step=raw * frac,
        detail={"elements": padded, "wire_bytes_per_element": b,
                "padded_from": n_elements},
    )


def easgd_traffic(
    n_elements: int, n_workers: int, avg_freq: int, group_size: int = 1,
    codec=None, n_slices: int = 1,
) -> TrafficModel:
    """EASGD: zero comm on local steps (the selling point) unless the
    worker is a chip GROUP (in-step grad psum over the group's data
    axis); every ``avg_freq`` steps one psum of the param-sized elastic
    differences over the worker axis. The codec compresses the ELASTIC
    EXCHANGE only — the group-internal grad psum rides dense ICI and
    stays fp32 (parallel/easgd.py). On a multi-slice mesh the group
    psum stays ICI by construction (make_worker_group_mesh pins each
    group inside one slice); the worker-axis exchange spans slices and
    splits by ``dcn_fraction`` over the worker count."""
    codec = get_codec(codec)
    per_step = (
        allreduce_bytes(n_elements, group_size) if group_size > 1 else 0.0
    )
    raw_exchange = allreduce_bytes(n_elements, n_workers)
    eff_exchange = raw_exchange * codec.wire_bytes_per_element / 4.0
    every = max(1, int(avg_freq))
    frac = dcn_fraction(n_workers, n_slices)
    return TrafficModel(
        rule="easgd", n_workers=n_workers,
        bytes_per_step=per_step,
        bytes_per_exchange=eff_exchange,
        exchange_every=every,
        codec=codec.spec,
        raw_bytes_per_step=per_step,
        raw_bytes_per_exchange=raw_exchange,
        dcn_bytes_per_step=eff_exchange * frac / every,
        raw_dcn_bytes_per_step=raw_exchange * frac / every,
        detail={"elements": n_elements,
                "wire_bytes_per_element": codec.wire_bytes_per_element,
                "group_size": group_size},
    )


def gosgd_traffic(
    n_elements: int, n_workers: int, gossip_every: int = 1,
    group_size: int = 1, codec=None, n_slices: int = 1,
) -> TrafficModel:
    """GoSGD: every gossip round is ONE ppermute of the packed
    ``(share*w, share)`` buffer — ``(N+1)*4`` bytes per device per
    round regardless of n (parallel/gosgd.py), zero between rounds
    (plus the group grad psum when workers are chip groups). The
    Bernoulli push DECISION gates merging, not the wire: the ppermute
    ships every round it runs. With a codec the round message is the
    ACTUAL packed layout (codec.gossip_encode: quantized values +
    scale rows + the exact-fp32 share tail)."""
    from theanompi_tpu.parallel.codec import gossip_wire_bytes

    codec = get_codec(codec)
    per_step = (
        allreduce_bytes(n_elements, group_size) if group_size > 1 else 0.0
    )
    raw_round = float((n_elements + 1) * 4) if n_workers > 1 else 0.0
    round_bytes = (
        gossip_wire_bytes(codec, n_elements) if n_workers > 1 else 0.0
    )
    every = max(1, int(gossip_every))
    # the gossip partner is uniform-random over workers: on a multi-
    # slice mesh the ppermute hop is charged entirely to DCN
    # (conservative — a same-slice draw is the exception, not the rule,
    # once r > 1 and workers spread slice-major)
    dcn = 1.0 if n_slices > 1 and n_workers > 1 else 0.0
    return TrafficModel(
        rule="gosgd", n_workers=n_workers,
        bytes_per_step=per_step,
        bytes_per_exchange=round_bytes,
        exchange_every=every,
        codec=codec.spec,
        raw_bytes_per_step=per_step,
        raw_bytes_per_exchange=raw_round,
        dcn_bytes_per_step=round_bytes * dcn / every,
        raw_dcn_bytes_per_step=raw_round * dcn / every,
        detail={"elements": n_elements,
                "wire_bytes_per_element": codec.wire_bytes_per_element,
                "group_size": group_size},
    )


def nd_traffic(
    n_elements: int, dp: int, shard_ways: int = 1, codec=None,
    n_slices: int = 1,
) -> TrafficModel:
    """ND engine, dp-axis grad sync only: each device allreduces its
    LOCAL (1/shard_ways) slice of the params over the dp axis; the
    codec compresses exactly those sharded-axis grad psums
    (parallel/nd.py). Activation collectives (tp psum, sp ring, pp
    ppermute, MoE all-to-all) are NOT modeled — marked ``approx`` so
    downstream readers can't mistake this for a full wire audit."""
    codec = get_codec(codec)
    b = codec.wire_bytes_per_element
    local = n_elements / max(1, shard_ways)
    raw = allreduce_bytes(local, dp)
    frac = dcn_fraction(dp, n_slices)
    return TrafficModel(
        rule="nd", n_workers=dp,
        bytes_per_step=raw * b / 4.0,
        codec=codec.spec,
        raw_bytes_per_step=raw,
        dcn_bytes_per_step=raw * b / 4.0 * frac,
        raw_dcn_bytes_per_step=raw * frac,
        detail={"elements": local, "wire_bytes_per_element": b,
                "approx": True, "shard_ways": shard_ways,
                "note": "dp grad sync only; activation collectives "
                        "(tp/sp/pp/moe) not modeled"},
    )
