"""Replica-group serving: health-checked least-loaded routing with
bounded failover, plus the supervisor that restarts dead replicas.

PR 5's engine is one process, one replica — a death drops everything it
holds. This module turns N :class:`~theanompi_tpu.serve.engine.
ServeEngine` replicas into one serving fleet behind one endpoint:

- **Least-loaded routing.** :meth:`Router.submit` scores every healthy
  replica by ``(queue_depth + 1) x EWMA batch seconds`` — the expected
  wait a new request would see — and admits to the cheapest. A replica
  that rejects (its own bounded-queue admission control) falls to the
  next candidate; only when EVERY healthy replica rejects does the
  router itself raise :class:`RouterOverloaded`, whose
  ``retry_after_ms`` comes from the fleet's SURVIVING-capacity EWMA
  (total backlog / aggregate service rate), not any single engine's
  view — graceful degradation means overload semantics engage exactly
  when the surviving capacity is truly exceeded.

- **Bounded per-request failover.** A request in flight on a dying
  replica (its engine rejects the future with
  :class:`~theanompi_tpu.serve.engine.EngineDead` or any other
  engine-side error) is RE-ADMITTED to a healthy replica within its
  original deadline — never silently dropped. Failover is bounded
  (``max_failovers``) and deadline-honoring: a deadline that expires
  mid-failover surfaces as ``DeadlineExceeded`` exactly like one that
  expires in a queue. Every terminal drop is counted
  (``tmpi_router_requests_total{status=dropped}``) and recorded — the
  chaos oracle (tools/chaos.py ``--serve``) asserts the counter stays
  at zero while surviving capacity suffices.

- **Served-step monotonicity by construction.** The router keeps a
  fleet-wide step floor, ratcheted under a lock on every result. A
  result served from params OLDER than the floor (one replica lagging
  the central hot-reload by a batch) is not returned — the request is
  re-admitted until a current replica serves it. Clients can never
  observe the served step move backward across failover or reload.

- **Supervisor.** A single ``tmpi-router-supervisor`` thread health-
  checks replicas (an aborted/dead batcher demotes the replica out of
  rotation) and restarts down replicas through the replica factory
  with the PR-4 decorrelated-jitter backoff
  (``min(cap, U(base, 3*prev))``, seeded RNG) while survivors absorb
  the traffic.

The Router duck-types enough of the engine surface that the existing
pieces compose unchanged: ``serve/reload.py``'s
:class:`CheckpointReloader` points at the Router and hot-reload becomes
CENTRAL (one load, one ``set_params`` fan-out, every replica swaps to
the same step), and ``serve/frontend.py`` fronts a Router exactly like
an engine (``submit``/``params_step``/``draining``/``registry``).

Telemetry: ``tmpi_router_*`` metrics in the router's registry and
``kind=router`` JSONL records (events ``health`` / ``failover`` /
``restart`` / ``restart_failed`` / ``drop`` / ``snapshot``) in
``<obs_dir>/router.jsonl`` — schema in tools/check_obs_schema.py.
Replica members write their own ``serve_r<id>.jsonl``.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable, Optional

from theanompi_tpu.serve.engine import (
    DeadlineExceeded,
    EngineDead,
    EngineDraining,
    Rejected,
    ServeEngine,
)

# a replica with no batch timing yet is assumed this fast (seconds per
# micro-batch) for scoring — matches the engine's own overload fallback
_DEFAULT_BATCH_S = 0.05
# sleep between re-admission attempts when no replica is healthy yet
# (the supervisor is restarting one); deadline-bounded overall
_REROUTE_WAIT_S = 0.02
# a result older than the fleet's step floor is retried at most this
# many times (the central reload fan-out window is sub-millisecond;
# this bound exists so a wedged fleet cannot spin forever)
_MAX_STALE_RETRIES = 8


class RouterOverloaded(Rejected):
    """Every healthy replica rejected admission: the FLEET is out of
    capacity. ``retry_after_ms`` is the aggregate estimate — total
    backlog over the surviving replicas' combined service rate."""

    def __init__(self, healthy: int, depth: int, retry_after_ms: float):
        self.retry_after_ms = float(retry_after_ms)
        super().__init__(
            f"all {healthy} healthy replicas overloaded ({depth} "
            f"waiting fleet-wide); retry in ~{retry_after_ms:.0f} ms"
        )


class RouterUnavailable(Rejected):
    """Zero healthy replicas right now (all crashed, supervisor mid-
    restart). ``retry_after_ms`` estimates the restart backoff."""

    def __init__(self, retry_after_ms: float):
        self.retry_after_ms = float(retry_after_ms)
        super().__init__(
            "no healthy replica available; retry in "
            f"~{retry_after_ms:.0f} ms"
        )


class RequestDropped(RuntimeError):
    """Terminal failover failure: the request exhausted its failover
    budget (or the drop_inflight mutation fired). The router counts
    every one — the chaos oracle's zero-drop invariant watches it."""


class RouterFuture:
    """Completion handle for a routed request. ``result()`` runs the
    failover loop in the WAITING thread: it blocks on the current
    replica's future and, when that replica dies under the request,
    asks the router to re-admit it on a healthy one — bounded by the
    failover budget and the request's original deadline."""

    __slots__ = ("_router", "_x", "_deadline", "_rep", "_fut",
                 "_failovers", "_stales", "t_submit")

    def __init__(self, router: "Router", x, deadline_ms: Optional[float]):
        self._router = router
        self._x = x
        self._deadline = (
            time.monotonic() + float(deadline_ms) / 1000.0
            if deadline_ms else None
        )
        self._rep = None
        self._fut = None
        self._failovers = 0
        self._stales = 0
        self.t_submit = time.monotonic()

    def remaining_ms(self) -> Optional[float]:
        """Milliseconds left on the ORIGINAL deadline (None = none)."""
        if self._deadline is None:
            return None
        return 1000.0 * (self._deadline - time.monotonic())

    def done(self) -> bool:
        f = self._fut
        return f is not None and f.done()

    def result(self, timeout: Optional[float] = None):
        t_end = None if timeout is None else time.monotonic() + timeout
        while True:
            budget = None if t_end is None else t_end - time.monotonic()
            try:
                res = self._fut.result(budget)
            except TimeoutError:
                raise
            except DeadlineExceeded:
                self._router._count_expired()
                raise
            except BaseException as e:  # noqa: BLE001 — every engine-
                # side failure (EngineDead, a post-admission drain, a
                # poisoned batch) is a failover candidate: another
                # replica may still serve this request in time
                self._router._failover(self, e)
                continue
            if self._router._settle(res):
                return res
            # stale params: this replica lagged the central reload
            self._stales += 1
            if self._stales > _MAX_STALE_RETRIES:
                # wedged fleet — surface the stale result rather than
                # spin; counted so the oracle can see it ever happened
                self._router._count_stale_served()
                return res
            self._router._reroute_stale(self)


class Replica:
    """One fleet member: an engine slot plus its health state machine
    (``new -> healthy <-> down -> restarting -> healthy``). All state
    transitions are serialized by the replica's own lock; the Router
    writes the ``kind=router`` health records around them."""

    def __init__(self, replica_id: int,
                 factory: Callable[[int], ServeEngine]):
        self.replica_id = int(replica_id)
        self._factory = factory
        self._lock = threading.Lock()
        self._engine: Optional[ServeEngine] = None
        self._state = "new"
        self._last_error: Optional[str] = None
        self._restarts = 0
        self._next_restart_t: Optional[float] = None
        self._backoff_s: Optional[float] = None

    # -- views (racy reads are fine: every write is serialized) -------------
    @property
    def engine(self) -> Optional[ServeEngine]:
        return self._engine

    @property
    def state(self) -> str:
        return self._state

    @property
    def restarts(self) -> int:
        return self._restarts

    @property
    def healthy(self) -> bool:
        eng = self._engine
        return self._state == "healthy" and eng is not None and eng.alive

    @property
    def next_restart_t(self) -> Optional[float]:
        return self._next_restart_t

    @property
    def backoff_s(self) -> Optional[float]:
        return self._backoff_s

    # -- transitions --------------------------------------------------------
    def start(self) -> ServeEngine:
        """Build this member's engine through the factory (started,
        warmed, params set — the factory contract) and enter rotation."""
        eng = self._factory(self.replica_id)
        with self._lock:
            self._engine = eng
            self._state = "healthy"
        return eng

    def mark_down(self, error: str) -> bool:
        """healthy/new -> down; returns whether THIS call made the
        transition (the caller writes the health record exactly once)."""
        with self._lock:
            if self._state in ("down", "restarting"):
                return False
            self._state = "down"
            self._last_error = str(error)[:300]
            self._next_restart_t = None
            return True

    def schedule_restart(self, at_t: float, backoff_s: float) -> None:
        with self._lock:
            self._next_restart_t = float(at_t)
            self._backoff_s = float(backoff_s)

    def begin_restart(self) -> bool:
        with self._lock:
            if self._state != "down":
                return False
            self._state = "restarting"
            return True

    def adopt(self, engine: ServeEngine) -> None:
        """Restart succeeded: publish the fresh engine and re-enter
        rotation; the jitter backoff resets on success."""
        with self._lock:
            self._engine = engine
            self._state = "healthy"
            self._restarts += 1
            self._next_restart_t = None
            self._backoff_s = None

    def restart_failed(self, error: str) -> None:
        with self._lock:
            self._state = "down"
            self._last_error = str(error)[:300]
            self._next_restart_t = None  # supervisor re-draws backoff

    def kill(self, error: Optional[BaseException] = None) -> None:
        """Chaos hook: hard-abort the engine (queued AND in-flight
        requests reject with :class:`EngineDead` and fail over)."""
        eng = self._engine
        if eng is not None:
            eng.abort(error or EngineDead(
                f"replica {self.replica_id} killed"))


class Router:
    """N-replica serving fleet behind one submit(): health-checked
    least-loaded routing, bounded failover, supervised restarts.

    ``factory(replica_id) -> ServeEngine`` must return a STARTED,
    warmed engine with params set (each member owns its registry and
    writes ``serve_r<id>.jsonl``); the supervisor uses the same factory
    to restart crashed members. Lifecycle: construct -> ``start()`` ->
    ``submit``/``infer`` ... -> ``drain()``.

    ``mutate="drop_inflight"`` plants the seeded bug the chaos
    mutation self-test must catch: the failover path DROPS a request
    held by a dying replica instead of re-admitting it.
    """

    def __init__(
        self,
        factory: Callable[[int], ServeEngine],
        n_replicas: int,
        *,
        obs_dir: Optional[str] = None,
        registry=None,
        default_deadline_ms: Optional[float] = None,
        max_failovers: int = 4,
        health_interval: float = 0.25,
        restart_base_s: float = 0.2,
        restart_cap_s: float = 2.0,
        seed: int = 0,
        mutate: Optional[str] = None,
    ):
        from theanompi_tpu.obs.metrics import MetricsRegistry

        if int(n_replicas) < 1:
            raise ValueError("n_replicas must be >= 1")
        self._factory = factory
        self._replicas = tuple(
            Replica(i, factory) for i in range(int(n_replicas))
        )
        self.obs_dir = obs_dir
        self.default_deadline_ms = default_deadline_ms
        self.max_failovers = int(max_failovers)
        self.health_interval = float(health_interval)
        self.restart_base_s = float(restart_base_s)
        self.restart_cap_s = float(restart_cap_s)
        self.mutate = mutate
        # seeded: restart backoff jitter is reproducible per chaos seed
        self._rng = random.Random(seed)

        self._lock = threading.Lock()
        self._step_floor = -1
        self._capacity_rps = 0.0
        self._draining = False
        self._stop = threading.Event()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sink_lock = threading.Lock()
        self._sink_f = None
        self._sink_retired = False

        self.registry = registry or MetricsRegistry()
        self._c_requests = self.registry.counter(
            "tmpi_router_requests_total",
            help="routed requests by outcome (status=served|dropped|"
                 "rejected|expired|stale_retry|stale_served)",
        )
        self._c_failovers = self.registry.counter(
            "tmpi_router_failovers_total",
            help="in-flight requests re-admitted off a dying replica",
        )
        self._c_restarts = self.registry.counter(
            "tmpi_router_restarts_total",
            help="supervisor replica restarts (status=failed for "
                 "factory failures)",
        )
        self._c_reloads = self.registry.counter(
            "tmpi_router_reloads_total",
            help="central hot-reloads fanned out to the fleet",
        )
        self._g_healthy = self.registry.gauge(
            "tmpi_router_healthy", help="replicas currently in rotation"
        )
        self._g_replicas = self.registry.gauge(
            "tmpi_router_replicas", help="fleet size"
        )
        self._g_queue = self.registry.gauge(
            "tmpi_router_queue_depth", help="fleet-wide queued requests"
        )
        self._g_capacity = self.registry.gauge(
            "tmpi_router_capacity_rps",
            help="surviving-capacity EWMA (requests/s the healthy "
                 "replicas can serve)",
        )
        self._g_floor = self.registry.gauge(
            "tmpi_router_step_floor",
            help="fleet-wide served-step floor (monotone ratchet)",
        )
        self._g_replicas.set(float(len(self._replicas)))

    # -- lifecycle ----------------------------------------------------------
    def start(self, supervise: bool = True) -> None:
        """Build every replica through the factory, then start the
        supervisor thread (health checks + jitter-backoff restarts)."""
        for rep in self._replicas:
            rep.start()
            self._write_record(self._event(
                "health", rep, from_state="new", to_state="healthy"))
        self._g_healthy.set(float(self.healthy_count))
        if supervise:
            self._thread = threading.Thread(
                target=self._supervise_loop, name="tmpi-router-supervisor",
                daemon=True,
            )
            self._thread.start()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful fleet shutdown: stop admission, stop the
        supervisor, drain every live replica, flush the final
        ``snapshot`` record. Idempotent."""
        with self._lock:
            self._draining = True
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        deadline = None if timeout is None else time.monotonic() + timeout
        drained = True
        for rep in self._replicas:
            eng = rep.engine
            if eng is None:
                continue
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            try:
                drained = eng.drain(timeout=left) and drained
            except Exception:  # noqa: BLE001 — a dead member must not
                # block the survivors' drain
                drained = False
        with self._sink_lock:
            first = not self._stopped.is_set()
            self._stopped.set()
        if first and self.obs_dir is not None:
            rec = self.router_record()
            with self._sink_lock:
                if not self._sink_retired:
                    if self._sink_f is None:
                        os.makedirs(self.obs_dir, exist_ok=True)
                        self._sink_f = open(
                            os.path.join(self.obs_dir, "router.jsonl"), "a"
                        )
                    self._sink_f.write(json.dumps(rec) + "\n")
                    self._sink_retired = True
                    self._sink_f.close()
                    self._sink_f = None
        return drained

    close = drain

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def replicas(self) -> tuple:
        return self._replicas

    @property
    def healthy_count(self) -> int:
        return sum(1 for rep in self._replicas if rep.healthy)

    @property
    def model(self):
        """The served model (any live member's — they are identical);
        the central reloader builds its load template from this."""
        for rep in self._replicas:
            eng = rep.engine
            if eng is not None:
                return eng.model
        raise RuntimeError("no replica has an engine yet (start() first)")

    # -- reloader adapter (CheckpointReloader duck-type) --------------------
    @property
    def params_step(self) -> int:
        """MIN served step over healthy replicas: the reloader polls
        for anything newer than the laggiest member, so a member that
        missed a swap catches up on the next poll."""
        steps = [rep.engine.params_step for rep in self._replicas
                 if rep.healthy and rep.engine is not None]
        if not steps:
            return self._step_floor
        return min(steps)

    def set_params(self, params, model_state, step: int) -> bool:
        """Central hot-reload fan-out: one loaded checkpoint, every
        live replica swaps (each refuses backward steps on its own).
        Returns True when at least one member swapped."""
        any_swapped = False
        for rep in self._replicas:
            eng = rep.engine
            if eng is None:
                continue
            try:
                any_swapped = (
                    eng.set_params(params, model_state, step) or any_swapped
                )
            except Exception:  # noqa: BLE001 — a dying member must not
                # fail the fleet's reload; it restarts from the newest
                # checkpoint anyway
                continue
        return any_swapped

    def note_reload(self, from_step: int, to_step: int, ms: float) -> None:
        self._c_reloads.inc()
        self._write_record({
            "kind": "reload", "t": time.time(),
            "from_step": int(from_step), "to_step": int(to_step),
            "ms": round(float(ms), 3),
        })

    def note_reload_failed(self, from_step: int, error: str) -> None:
        self._c_reloads.inc(status="failed")
        self._write_record({
            "kind": "reload", "t": time.time(),
            "from_step": int(from_step), "to_step": -1,
            "ok": False, "error": str(error)[:500],
        })

    # -- request path -------------------------------------------------------
    def submit(self, x, deadline_ms: Optional[float] = None) -> RouterFuture:
        """Admit one request to the least-loaded healthy replica.
        Raises :class:`RouterOverloaded` (every healthy replica's own
        admission control rejected) or :class:`RouterUnavailable`
        (zero healthy replicas) synchronously; engine-side failures
        after admission fail over inside ``result()``."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if self._draining:
            self._c_requests.inc(status="rejected")
            raise EngineDraining()
        fut = RouterFuture(self, x, deadline_ms)
        self._admit(fut, deadline_ms, exclude=None)
        return fut

    def infer(self, x, deadline_ms: Optional[float] = None,
              timeout: Optional[float] = 30.0):
        """Blocking convenience: submit + failover-aware wait."""
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    def _admit(self, fut: RouterFuture, deadline_ms: Optional[float],
               exclude: Optional[Replica]) -> None:
        """One admission pass over the healthy replicas, least-loaded
        first. Raises RouterOverloaded / RouterUnavailable when no
        member admits (ValueError from a shape mismatch propagates —
        that is a caller bug, not a capacity problem)."""
        tried = set()
        while True:
            rep = self._pick(tried, prefer_not=exclude)
            if rep is None:
                break
            eng = rep.engine
            if eng is None:
                tried.add(rep.replica_id)
                continue
            try:
                sfut = eng.submit(fut._x, deadline_ms=deadline_ms)
            except Rejected:
                tried.add(rep.replica_id)
                continue
            except RuntimeError:
                # engine died between pick and submit — not a reject
                tried.add(rep.replica_id)
                continue
            fut._rep, fut._fut = rep, sfut
            return
        healthy = self.healthy_count
        self._c_requests.inc(status="rejected")
        if healthy == 0:
            raise RouterUnavailable(
                retry_after_ms=1000.0 * max(self.restart_base_s, 0.05))
        raise RouterOverloaded(
            healthy, self.total_queue_depth,
            retry_after_ms=self.retry_after_ms())

    def _pick(self, tried: set, prefer_not: Optional[Replica]) -> \
            Optional[Replica]:
        """Least-loaded healthy replica not yet tried; the replica the
        request just died on is only chosen when it is the sole
        survivor (it may have restarted already)."""
        best = None
        best_score = None
        for pass_excluding_prev in (True, False):
            for rep in self._replicas:
                if rep.replica_id in tried or not rep.healthy:
                    continue
                if pass_excluding_prev and rep is prefer_not:
                    continue
                eng = rep.engine
                if eng is None:
                    continue
                ewma = eng.batch_s_ewma or _DEFAULT_BATCH_S
                score = (eng.queue_depth + 1) * ewma
                if best_score is None or score < best_score:
                    best, best_score = rep, score
            if best is not None:
                return best
        return None

    # -- failover (runs on the waiting request's thread) --------------------
    def _failover(self, fut: RouterFuture, error: BaseException) -> None:
        """The dying replica rejected an in-flight request: demote the
        replica, then re-admit the request on a healthy one within its
        original deadline. Raises when the request is terminally lost
        (budget exhausted / deadline passed / mutation)."""
        rep = fut._rep
        if rep is not None and rep.mark_down(repr(error)):
            self._write_record(self._event(
                "health", rep, from_state="healthy", to_state="down",
                error=repr(error)))
            self._g_healthy.set(float(self.healthy_count))
        if self.mutate == "drop_inflight":
            # the planted bug the chaos mutation self-test must catch:
            # the in-flight request is dropped instead of re-admitted
            self._drop(fut, rep, error)
        fut._failovers += 1
        if fut._failovers > self.max_failovers:
            self._drop(fut, rep, error)
        remaining = fut.remaining_ms()
        if remaining is not None and remaining <= 0.0:
            self._count_expired()
            raise DeadlineExceeded(
                "deadline expired during failover "
                f"(after {fut._failovers} attempts)") from error
        # re-admit, waiting out a no-healthy-replica window (the
        # supervisor is restarting) up to the deadline; deadline-less
        # requests get a bounded wait instead of spinning forever on a
        # fleet whose restarts keep failing
        waited = 0.0
        max_wait_s = 4.0 * max(self.restart_cap_s, self.restart_base_s)
        while True:
            try:
                self._admit(fut, fut.remaining_ms(), exclude=rep)
            except Rejected as rej:
                remaining = fut.remaining_ms()
                if remaining is not None and remaining <= 0.0:
                    self._count_expired()
                    raise DeadlineExceeded(
                        "deadline expired during failover "
                        f"(after {fut._failovers} attempts)") from error
                if remaining is None and (
                        self._draining or waited >= max_wait_s):
                    self._drop(fut, rep, rej)
                time.sleep(_REROUTE_WAIT_S)
                waited += _REROUTE_WAIT_S
                continue
            break
        self._c_failovers.inc()
        self._write_record(self._event(
            "failover", rep if rep is not None else fut._rep,
            to_replica=fut._rep.replica_id, error=repr(error)))

    def _drop(self, fut: RouterFuture, rep: Optional[Replica],
              error: BaseException) -> None:
        self._c_requests.inc(status="dropped")
        self._write_record(self._event("drop", rep, error=repr(error)))
        raise RequestDropped(
            f"request dropped after {fut._failovers} failovers: "
            f"{error!r}") from error

    def _reroute_stale(self, fut: RouterFuture) -> None:
        """The result came from params older than the fleet floor (a
        member lagging the central reload by one batch): re-admit,
        preferring a different replica — by the time the new submit
        batches, the swap fan-out has landed."""
        self._c_requests.inc(status="stale_retry")
        time.sleep(_REROUTE_WAIT_S / 4.0)
        waited = 0.0
        while True:
            try:
                self._admit(fut, fut.remaining_ms(), exclude=fut._rep)
            except Rejected:
                remaining = fut.remaining_ms()
                if remaining is not None and remaining <= 0.0:
                    self._count_expired()
                    raise DeadlineExceeded(
                        "deadline expired while retrying a stale-params "
                        "result")
                if remaining is None and (
                        self._draining or waited >= 2.0):
                    raise  # surface the fleet-level reject as-is
                time.sleep(_REROUTE_WAIT_S)
                waited += _REROUTE_WAIT_S
                continue
            break

    # -- result settlement --------------------------------------------------
    def _settle(self, res) -> bool:
        """Ratchet the fleet step floor; False = the result is from
        params older than what the fleet already served (stale)."""
        with self._lock:
            if res.step < self._step_floor:
                return False
            if res.step > self._step_floor:
                self._step_floor = res.step
                self._g_floor.set(float(res.step))
        self._c_requests.inc(status="served")
        return True

    def _count_expired(self) -> None:
        self._c_requests.inc(status="expired")

    def _count_stale_served(self) -> None:
        self._c_requests.inc(status="stale_served")

    # -- supervisor ---------------------------------------------------------
    def _supervise_loop(self) -> None:
        while not self._stop.wait(self.health_interval):
            try:
                self._health_pass(time.monotonic())
            except Exception as e:  # noqa: BLE001 — the supervisor
                # must outlive any single bad pass
                print(f"[serve.router] health pass failed ({e!r}); "
                      "retrying", flush=True)

    def _health_pass(self, now: float) -> None:
        """One supervisor tick: demote dead members, restart due ones
        (decorrelated-jitter backoff), refresh the capacity EWMA."""
        healthy = 0
        queue_depth = 0
        rate = 0.0
        for rep in self._replicas:
            eng = rep.engine
            if rep.state == "healthy" and (eng is None or not eng.alive):
                if rep.mark_down("engine not alive (health check)"):
                    self._write_record(self._event(
                        "health", rep, from_state="healthy",
                        to_state="down",
                        error="engine not alive (health check)"))
            if rep.state == "down":
                nxt = rep.next_restart_t
                if nxt is None:
                    prev = rep.backoff_s or self.restart_base_s
                    backoff = min(
                        self.restart_cap_s,
                        self._rng.uniform(self.restart_base_s, 3.0 * prev),
                    )
                    rep.schedule_restart(now + backoff, backoff)
                elif now >= nxt:
                    self._restart(rep)
            if rep.healthy:
                eng = rep.engine
                healthy += 1
                queue_depth += eng.queue_depth
                ewma = eng.batch_s_ewma or _DEFAULT_BATCH_S
                rate += eng.buckets[-1] / max(ewma, 1e-4)
        with self._lock:
            prev = self._capacity_rps
            self._capacity_rps = (
                rate if prev == 0.0 else 0.7 * prev + 0.3 * rate
            )
        self._g_healthy.set(float(healthy))
        self._g_queue.set(float(queue_depth))
        self._g_capacity.set(self._capacity_rps)

    def _restart(self, rep: Replica) -> None:
        backoff = rep.backoff_s
        if not rep.begin_restart():
            return
        self._write_record(self._event(
            "health", rep, from_state="down", to_state="restarting"))
        try:
            eng = self._factory(rep.replica_id)
        except Exception as e:  # noqa: BLE001 — a failed restart re-
            # enters backoff with the jitter grown from the last draw
            self._c_restarts.inc(status="failed")
            rep.restart_failed(repr(e))
            self._write_record(self._event(
                "restart_failed", rep, error=repr(e),
                backoff_s=backoff))
            return
        rep.adopt(eng)
        self._c_restarts.inc()
        self._g_healthy.set(float(self.healthy_count))
        self._write_record(self._event(
            "restart", rep, from_state="restarting", to_state="healthy",
            backoff_s=backoff))

    # -- chaos hooks --------------------------------------------------------
    def kill_replica(self, replica_id: int,
                     error: Optional[BaseException] = None) -> None:
        """Hard-kill one member (chaos ``replica_crash``): demote it
        out of rotation FIRST (no new admissions), then abort its
        engine so queued and in-flight requests fail over."""
        rep = self._replicas[int(replica_id)]
        if rep.mark_down("killed (chaos replica_crash)"):
            self._write_record(self._event(
                "health", rep, from_state="healthy", to_state="down",
                error="killed (chaos replica_crash)"))
            self._g_healthy.set(float(self.healthy_count))
        rep.kill(error)

    # -- telemetry ----------------------------------------------------------
    @property
    def total_queue_depth(self) -> int:
        total = 0
        for rep in self._replicas:
            eng = rep.engine
            if rep.healthy and eng is not None:
                total += eng.queue_depth
        return total

    def surviving_capacity_rps(self) -> float:
        """The router's surviving-capacity EWMA (requests/s across the
        healthy replicas) — the ``Retry-After`` source once replicas
        exist. Falls back to an instantaneous estimate before the
        supervisor's first pass."""
        cap = self._capacity_rps
        if cap > 0.0:
            return cap
        rate = 0.0
        for rep in self._replicas:
            eng = rep.engine
            if rep.healthy and eng is not None:
                ewma = eng.batch_s_ewma or _DEFAULT_BATCH_S
                rate += eng.buckets[-1] / max(ewma, 1e-4)
        return rate

    def retry_after_ms(self) -> float:
        """Aggregate backlog over aggregate service rate: when the
        FLEET rejects, this is how long until capacity frees up."""
        rate = max(self.surviving_capacity_rps(), 1e-3)
        return 1000.0 * (self.total_queue_depth + 1) / rate

    def stats(self) -> dict:
        """Flat ``tmpi_router_``-prefixed numeric snapshot (the
        ``kind=router`` snapshot record's metrics map — prefix enforced
        by the schema checker)."""
        return {
            "tmpi_router_replicas": float(len(self._replicas)),
            "tmpi_router_healthy": float(self.healthy_count),
            "tmpi_router_queue_depth": float(self.total_queue_depth),
            "tmpi_router_capacity_rps": float(self.surviving_capacity_rps()),
            "tmpi_router_step_floor": float(self._step_floor),
            "tmpi_router_served_total":
                self._c_requests.value(status="served"),
            "tmpi_router_dropped_total":
                self._c_requests.value(status="dropped"),
            "tmpi_router_rejected_total":
                self._c_requests.value(status="rejected"),
            "tmpi_router_expired_total":
                self._c_requests.value(status="expired"),
            "tmpi_router_stale_retries_total":
                self._c_requests.value(status="stale_retry"),
            "tmpi_router_stale_served_total":
                self._c_requests.value(status="stale_served"),
            "tmpi_router_failovers_total": self._c_failovers.value(),
            "tmpi_router_restarts_total": self._c_restarts.value(),
            "tmpi_router_restart_failures_total":
                self._c_restarts.value(status="failed"),
            "tmpi_router_reloads_total": self._c_reloads.value(),
        }

    def router_record(self) -> dict:
        """The ``kind=router`` snapshot record (schema:
        tools/check_obs_schema.py)."""
        return {"kind": "router", "t": time.time(), "event": "snapshot",
                "metrics": self.stats()}

    def healthz(self) -> tuple:
        """(ok, body) for the HTTP front's ``/healthz``: the fleet is
        routable while it is not draining and >=1 member is healthy."""
        body = {
            "params_step": self.params_step,
            "queue_depth": self.total_queue_depth,
            "draining": self.draining,
            "replicas": len(self._replicas),
            "healthy": self.healthy_count,
            "states": {str(rep.replica_id): rep.state
                       for rep in self._replicas},
        }
        ok = not self.draining and self.healthy_count > 0
        return ok, body

    def _event(self, event: str, rep: Optional[Replica],
               from_state: Optional[str] = None,
               to_state: Optional[str] = None,
               to_replica: Optional[int] = None,
               error: Optional[str] = None,
               backoff_s: Optional[float] = None) -> dict:
        rec = {"kind": "router", "t": time.time(), "event": event}
        if rep is not None:
            rec["replica_id"] = rep.replica_id
        if from_state is not None:
            rec["from_state"] = from_state
        if to_state is not None:
            rec["to_state"] = to_state
        if to_replica is not None:
            rec["to_replica"] = int(to_replica)
        if error is not None:
            rec["error"] = str(error)[:300]
        if backoff_s is not None:
            rec["backoff_s"] = round(float(backoff_s), 4)
        return rec

    def _write_record(self, rec: dict) -> None:
        if self.obs_dir is None:
            return
        with self._sink_lock:
            if self._sink_retired:
                return
            if self._sink_f is None:
                os.makedirs(self.obs_dir, exist_ok=True)
                self._sink_f = open(
                    os.path.join(self.obs_dir, "router.jsonl"), "a"
                )
            self._sink_f.write(json.dumps(rec) + "\n")
            self._sink_f.flush()
