"""Serving subsystem: dynamic micro-batching TPU inference with
checkpoint hot-reload — the train→serve loop the ROADMAP's "serves
heavy traffic from millions of users" north star needs (the reference,
like the paper, stopped at training).

Design rule, inherited from the training side's dispatch discipline:
**never pay compilation or transfer cost on the request hot path.**

- :mod:`~theanompi_tpu.serve.engine` — :class:`ServeEngine`: a bounded
  request queue + batcher thread that coalesces waiting requests, pads
  them to a small set of bucketed batch shapes (default 1/8/32/128) so
  the jitted eval-mode ``apply`` (``models/zoo.infer_fn``: train=False,
  no rng, fixed BN stats, donation-free) compiles exactly once per
  bucket — AOT-warmed at startup, counted, and provable
  (``compile_count``). Admission control: per-request deadlines,
  reject-with-retry-after on a full queue, graceful drain on SIGTERM.
  Telemetry: ``tmpi_serve_*`` latency histograms (p50/p99), queue-depth
  and batch-fill gauges, request counters through the existing
  :class:`~theanompi_tpu.obs.metrics.MetricsRegistry`, plus ``serve``/
  ``reload`` JSONL records in ``<obs_dir>/serve.jsonl`` (schema:
  ``tools/check_obs_schema.py``).
- :mod:`~theanompi_tpu.serve.reload` — :class:`CheckpointReloader`:
  polls a training run's checkpoint keep-chain via
  ``utils/checkpoint.newer_verified_checkpoint`` (the short-circuit
  walk: a steady-state poll verifies NOTHING, and a corrupt newest
  checkpoint is skipped without touching the file already served) and
  atomically swaps params between batches — in-flight requests finish
  on the params they started with; the served step only moves forward.
- :mod:`~theanompi_tpu.serve.frontend` — a stdlib-only HTTP front
  (POST /infer, GET /healthz, GET /metrics) for the ``tmpi serve`` CLI
  subcommand; the engine itself is transport-agnostic and in-process.
"""

from theanompi_tpu.serve.engine import (  # noqa: F401
    DeadlineExceeded,
    EngineDraining,
    EngineOverloaded,
    Rejected,
    ServeEngine,
    ServeResult,
)
from theanompi_tpu.serve.reload import (  # noqa: F401
    CheckpointReloader,
    load_for_serving,
    serving_state_template,
)
