"""Stdlib HTTP front for the serve engine (no new dependencies).

The engine (serve/engine.py) is transport-agnostic; this module gives
``tmpi serve`` a wire. ``ThreadingHTTPServer`` is enough because every
handler thread just blocks on a :class:`ServeFuture` — the actual work
is batched on the engine's single batcher thread, which is exactly the
dynamic micro-batching story: N concurrent HTTP clients coalesce into
bucket-shaped forwards.

The same handler fronts a replica-group :class:`~theanompi_tpu.serve.
router.Router` (``tmpi serve --replicas N``): the router duck-types
``submit``/``params_step``/``draining``/``registry``, failover happens
inside ``fut.result()`` on the handler thread, and a fleet-level 503's
``Retry-After`` comes from the ROUTER's surviving-capacity EWMA
(``RouterOverloaded.retry_after_ms`` — total backlog over the healthy
replicas' aggregate service rate), never a single engine's view. The
single-engine path is byte-identical to the pre-router behavior.

Routes::

    POST /infer    {"input": <nested list, recipe.input_shape>,
                    "deadline_ms": <optional>}
                -> 200 {"logits": [...], "step": N}
                   (fronting a decode engine — ``tmpi serve --decode``
                   — "input" is a 1-D token prompt and the response is
                   {"tokens": [...], "step": N}: the generated
                   continuation instead of a logits row)
                   503 + Retry-After on overload/draining
                   504 on deadline expiry
    GET /healthz -> 200 {"params_step", "queue_depth", "draining"} —
                   the load-balancer probe (draining -> 503 so a
                   SIGTERM'd replica falls out of rotation while it
                   finishes its backlog). Fronting a router, the body
                   also carries {"replicas", "healthy", "states"} and
                   503 means ZERO healthy replicas (one dead member of
                   a degraded-but-serving fleet keeps the probe green)
    GET /metrics -> Prometheus text of the engine registry
                   (tmpi_serve_* families; tmpi_router_* for a router)
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from theanompi_tpu.serve.engine import (
    DeadlineExceeded,
    Rejected,
)


def make_handler(engine):
    """Build the handler class over one serve target — a bare
    :class:`ServeEngine` or a replica-group ``Router`` (duck-typed)."""
    class Handler(BaseHTTPRequestHandler):
        # request logging off the hot path: per-request stderr lines at
        # serving rates are their own denial of service
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _reply(self, code: int, body: dict, headers: dict = ()):
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in dict(headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            if self.path == "/healthz":
                hz = getattr(engine, "healthz", None)
                if hz is not None:  # a Router: fleet-level probe
                    ok, body = hz()
                    self._reply(200 if ok else 503, body)
                    return
                body = {
                    "params_step": engine.params_step,
                    # the shared-surface property, NOT a stats() key —
                    # ServeEngine prefixes tmpi_serve_, DecodeEngine
                    # tmpi_decode_; only queue_depth is common
                    "queue_depth": int(engine.queue_depth),
                    "draining": engine.draining,
                }
                self._reply(503 if engine.draining else 200, body)
            elif self.path == "/metrics":
                data = engine.registry.to_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):  # noqa: N802
            if self.path != "/infer":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                x = np.asarray(req["input"])
                deadline_ms = req.get("deadline_ms")
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": f"bad request: {e!r}"})
                return
            try:
                fut = engine.submit(x, deadline_ms=deadline_ms)
                res = fut.result(timeout=None)
            except Rejected as e:
                headers = {}
                if e.retry_after_ms is not None:
                    # HTTP Retry-After is whole seconds; round up
                    headers["Retry-After"] = str(
                        max(1, int(-(-e.retry_after_ms // 1000)))
                    )
                self._reply(503, {"error": str(e)}, headers)
            except DeadlineExceeded as e:
                self._reply(504, {"error": str(e)})
            except ValueError as e:  # shape mismatch
                self._reply(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — a failed batch
                # surfaces its raw error through the future (engine
                # loop survives it); the client must get a JSON 500,
                # not a reset socket
                self._reply(500, {"error": f"inference failed: {e!r}"})
            else:
                if hasattr(res, "tokens"):
                    # decode engine (serve/decode): the result is the
                    # generated continuation, not a logits row
                    self._reply(200, {
                        "tokens": np.asarray(res.tokens, np.int64).tolist(),
                        "step": res.step,
                    })
                else:
                    self._reply(200, {
                        "logits": np.asarray(res.logits, np.float64).tolist(),
                        "step": res.step,
                    })

    return Handler


def serve_http(engine, host: str = "127.0.0.1",
               port: int = 8300) -> ThreadingHTTPServer:
    """Bind and return the server (caller runs ``serve_forever`` — the
    CLI does it on the main thread so SIGTERM lands there)."""
    return ThreadingHTTPServer((host, port), make_handler(engine))
