"""``tmpi serve`` — the serving subcommand (dispatched from cli.py).

Serve a training run's checkpoints over HTTP with dynamic
micro-batching and (``--watch``) checkpoint hot-reload::

    tmpi serve --ckpt-dir runs/ck --model cifar10 --watch \\
               --buckets 1,8,32,128 --max-queue 256 --deadline-ms 250 \\
               --obs-dir runs/obs --port 8300

Two engine kinds share this command:

- **Eval-forward** (default): one logits row per request
  (serve/engine.py). ``--buckets`` are its BATCH buckets — requests
  pad UP to the smallest fitting batch size, one compiled program per
  bucket. This flag applies to the eval engine ONLY.
- **LM decode** (``--decode``): continuous-batching generation over a
  paged KV-cache (serve/decode/) — requests are 1-D token prompts,
  responses are generated continuations. Its compiled-program knobs
  are ``--prefill-buckets`` (prompt-length buckets, page-size
  multiples) and ``--kv-pages`` (total KV pool pages) — NOT
  ``--buckets``. ``--shard tensor`` serves Megatron tensor-sharded
  params placed by ``ShardingRecipe.serve_tensor`` (degenerates to
  replicated on one device)::

      tmpi serve --decode --shard tensor --ckpt-dir runs/ck \\
                 --model runs/lm.py:TransformerLMModel \\
                 --prefill-buckets 16,64 --kv-pages 256

SIGTERM drains gracefully: admission stops (healthz flips 503, so a
load balancer rotates the replica out), the queued backlog is served —
for decode, every admitted generation runs to completion — then the
process exits. ``--selftest N`` skips the HTTP server and drives N
closed-loop local requests instead (smoke/CI path; prints the final
stats line and exits).

``--replicas N`` (N > 1) fronts an N-member replica group through
serve/router.py instead of one engine — for BOTH engine kinds (the
decode engine exposes the same submit/drain/set_params surface, so the
router is unchanged): health-checked least-loaded routing with bounded
failover, a supervisor restarting crashed members with jitter backoff,
central hot-reload under ``--watch``, and ``kind=router`` records in
``<obs-dir>/router.jsonl`` (members write ``serve_r<id>.jsonl`` /
``decode_r<id>.jsonl``). The final stdout line is then a schema-valid
``router`` snapshot record rather than a ``serve``/``decode`` one.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tmpi serve",
        description="TPU inference: dynamic micro-batching engine with "
                    "checkpoint hot-reload",
        allow_abbrev=False,
    )
    p.add_argument("--ckpt-dir", required=True,
                   help="training run's checkpoint dir; the newest "
                        "VERIFIED checkpoint is served (keep-chain walk)")
    p.add_argument("--model", required=True,
                   help="zoo short name (cifar10, alexnet, ...), or "
                        "module:Class / path.py:Class — must match the "
                        "recipe that trained the checkpoints (the resume "
                        "contract)")
    p.add_argument("--recipe-arg", action="append", default=[], metavar="K=V",
                   help="recipe override (repeatable, JSON values) — must "
                        "mirror the overrides the training run used")
    p.add_argument("--buckets", default="1,8,32,128",
                   help="EVAL-FORWARD engine only: comma-separated batch "
                        "buckets; requests pad UP to the smallest fitting "
                        "bucket, one compiled program per bucket, all "
                        "AOT-warmed at startup (the decode engine's "
                        "program knobs are --prefill-buckets/--kv-pages)")
    p.add_argument("--decode", action="store_true",
                   help="LM decode serving (serve/decode/): requests are "
                        "1-D token prompts, responses generated "
                        "continuations via continuous batching over a "
                        "paged KV-cache; needs a model with the "
                        "incremental decode surface (transformer_lm zoo "
                        "family)")
    p.add_argument("--prefill-buckets", default="16,64",
                   help="DECODE engine only: comma-separated prompt-length "
                        "buckets (page-size multiples); one compiled "
                        "prefill program per bucket + ONE decode program, "
                        "all AOT-warmed")
    p.add_argument("--kv-pages", type=int, default=256,
                   help="DECODE engine only: total pages in the "
                        "preallocated KV pool (admission reserves "
                        "worst-case pages per generation)")
    p.add_argument("--page-size", type=int, default=16,
                   help="DECODE engine only: positions per KV page")
    p.add_argument("--max-seqs", type=int, default=8,
                   help="DECODE engine only: decode batch width "
                        "(concurrent generations)")
    p.add_argument("--max-new-tokens", type=int, default=32,
                   help="DECODE engine only: default per-request output "
                        "budget")
    p.add_argument("--shard", choices=("none", "tensor"), default="none",
                   help="DECODE engine only: 'tensor' serves Megatron "
                        "tensor-sharded params over all local devices "
                        "(ShardingRecipe.serve_tensor; checkpoints load "
                        "through load_resharded onto the serving mesh); "
                        "'none' = replicated single-device serving")
    p.add_argument("--max-queue", type=int, default=256,
                   help="admission bound: a full queue rejects with "
                        "retry-after instead of growing latency unbounded")
    p.add_argument("--deadline-ms", type=float, default=1000.0,
                   help="default per-request deadline (0 = none): expired "
                        "requests are rejected, not served")
    p.add_argument("--watch", action="store_true",
                   help="hot-reload: poll the keep-chain and atomically "
                        "swap to newer verified checkpoints while serving")
    p.add_argument("--poll-interval", type=float, default=2.0,
                   help="--watch poll cadence in seconds")
    p.add_argument("--obs-dir", default=None,
                   help="telemetry dir: serve.jsonl records "
                        "(kind=serve/reload; tools/check_obs_schema.py)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8300,
                   help="HTTP port (serve/frontend.py)")
    p.add_argument("--replicas", type=int, default=1, metavar="N",
                   help="replica-group serving (serve/router.py): N "
                        "engines behind one endpoint with health-checked "
                        "least-loaded routing, bounded failover, and a "
                        "supervisor that restarts crashed members; "
                        "checkpoint hot-reload becomes central (one load, "
                        "fleet-wide swap). 1 = the classic single engine")
    p.add_argument("--selftest", type=int, default=0, metavar="N",
                   help="no HTTP: run N closed-loop local requests, print "
                        "stats JSON, exit (smoke path)")
    return p


def _resolve_serve_model(spec: str, recipe_args: list):
    """Model instance from a zoo short name or module:Class spec."""
    import ast

    from theanompi_tpu.launch.session import resolve_model
    from theanompi_tpu.models import MODEL_REGISTRY

    if ":" in spec:
        modelfile, _, classname = spec.rpartition(":")
        cls = resolve_model(modelfile, classname)
    elif spec.lower() in MODEL_REGISTRY:
        modelfile, classname = MODEL_REGISTRY[spec.lower()]
        cls = resolve_model(modelfile, classname)
    else:
        raise SystemExit(
            f"--model {spec!r}: not a zoo short name "
            f"({sorted(MODEL_REGISTRY)}) and not module:Class"
        )
    overrides = {}
    for kv in recipe_args:
        k, sep, v = kv.partition("=")
        if not sep:
            raise SystemExit(f"--recipe-arg expects K=V, got {kv!r}")
        try:
            val = json.loads(v)
        except json.JSONDecodeError:
            try:
                val = ast.literal_eval(v)
            except (ValueError, SyntaxError):
                val = v
        overrides[k] = tuple(val) if isinstance(val, list) else val
    recipe = cls.default_recipe()
    if overrides:
        recipe = recipe.replace(**overrides)
    return cls(recipe)


def serve_main(argv=None) -> int:
    args = build_serve_parser().parse_args(argv)

    from theanompi_tpu.serve.engine import ServeEngine
    from theanompi_tpu.serve.reload import CheckpointReloader

    model = _resolve_serve_model(args.model, args.recipe_arg)
    buckets = tuple(int(b) for b in args.buckets.split(","))
    replicas = max(1, int(args.replicas))

    if args.decode:
        from theanompi_tpu.serve.decode import DecodeEngine

        prefill_buckets = tuple(
            int(b) for b in args.prefill_buckets.split(","))
        sharding = None
        if args.shard == "tensor":
            # specs are born in parallel/recipe.py (source guard:
            # serve/* never constructs a PartitionSpec)
            from theanompi_tpu.parallel.recipe import ShardingRecipe

            sharding = ShardingRecipe.serve_tensor(model)

        def _make(rid=None):
            return DecodeEngine(
                model,
                prefill_buckets=prefill_buckets,
                kv_pages=args.kv_pages,
                page_size=args.page_size,
                max_seqs=args.max_seqs,
                max_new_tokens=args.max_new_tokens,
                max_queue=args.max_queue,
                default_deadline_ms=args.deadline_ms or None,
                obs_dir=args.obs_dir,
                replica_id=rid,
                sink_name=("decode.jsonl" if rid is None
                           else f"decode_r{rid}.jsonl"),
                sharding=sharding,
            )

        engine_kind, program_note = "decode", (
            f"prefill buckets {prefill_buckets} + 1 decode program")
    else:
        def _make(rid=None):
            return ServeEngine(
                model,
                buckets=buckets,
                max_queue=args.max_queue,
                default_deadline_ms=args.deadline_ms or None,
                obs_dir=args.obs_dir,
                replica_id=rid,
                sink_name=("serve.jsonl" if rid is None
                           else f"serve_r{rid}.jsonl"),
            )

        engine_kind, program_note = "serve", f"buckets {buckets}"

    if replicas == 1:
        engine = _make()
        step = engine.load_initial(args.ckpt_dir)
        compiled = engine.warmup()
        print(f"[serve] {engine_kind} engine: {model.name} step {step}; "
              f"{compiled} programs AOT-warmed ({program_note})",
              flush=True)
        engine.start()
        final_record = (engine.decode_record if args.decode
                        else engine.serve_record)
    else:
        from theanompi_tpu.serve.router import Router

        def _member(rid):
            # the replica factory: the supervisor reuses it to restart
            # crashed members from the newest verified checkpoint
            eng = _make(rid)
            eng.load_initial(args.ckpt_dir)
            eng.warmup()
            eng.start()
            return eng

        engine = Router(
            _member, replicas,
            obs_dir=args.obs_dir,
            default_deadline_ms=args.deadline_ms or None,
        )
        engine.start()
        print(f"[serve] {replicas}-replica {engine_kind} fleet serving "
              f"{model.name} step {engine.params_step}; {program_note} "
              "AOT-warmed per member", flush=True)
        final_record = engine.router_record
    reloader = None
    if args.watch:
        # fronting a Router this is CENTRAL hot-reload: one checkpoint
        # load, one set_params fan-out, every replica swaps to the
        # same step (the Router duck-types the reloader's engine)
        reloader = CheckpointReloader(
            engine, args.ckpt_dir, interval=args.poll_interval
        )
        reloader.start()

    def _shutdown():
        # reloader FIRST: a poll landing after the final record would
        # print past the "last stdout line is a schema-valid serve
        # record" contract; then drain (idempotent, like stop)
        if reloader is not None:
            reloader.stop()
        engine.drain(timeout=30.0)

    try:
        if args.selftest:
            import numpy as np

            rng = np.random.RandomState(0)
            if args.decode:
                # decode selftest: mixed-length int32 prompts exercise
                # every prefill bucket plus the shared decode program
                vocab = int(model.recipe.num_classes)
                top = max(int(b) for b in args.prefill_buckets.split(",")) + 1
                for i in range(args.selftest):
                    n = 1 + (i * 3) % top
                    engine.infer(rng.randint(0, vocab, size=n, dtype=np.int32))
            else:
                shape = tuple(model.recipe.input_shape)
                for _ in range(args.selftest):
                    engine.infer(rng.randn(*shape))
            _shutdown()
            # LAST stdout line = one schema-valid stats record
            # (kind=serve/decode, or kind=router for a replica fleet)
            print(json.dumps(final_record()))
            return 0

        from theanompi_tpu.serve.frontend import serve_http

        httpd = serve_http(engine, host=args.host, port=args.port)

        import signal
        import threading

        def _graceful(signum, frame):
            # SIGTERM: flip to draining (healthz -> 503 rotates the
            # replica out), serve the queued backlog, then stop the
            # accept loop — all off the signal handler's thread.
            # _shutdown (not a bare drain): the reloader must stop
            # BEFORE the engine retires its sink, or a poll landing
            # mid-drain prints past the final serve record and its
            # reload record is silently dropped
            def _drain_then_stop():
                _shutdown()
                httpd.shutdown()

            threading.Thread(target=_drain_then_stop,
                             name="tmpi-serve-drain", daemon=True).start()

        signal.signal(signal.SIGTERM, _graceful)
        print(f"[serve] http on {args.host}:{httpd.server_address[1]} "
              "(POST /infer, GET /healthz, GET /metrics)", flush=True)
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()
        _shutdown()
        print(json.dumps(final_record()), flush=True)
        return 0
    finally:
        _shutdown()


if __name__ == "__main__":
    sys.exit(serve_main())
