"""In-process TPU inference engine: request queue + dynamic micro-batcher.

The serving analogue of the training side's dispatch pipeline
(utils/dispatch.py): keep Python, compilation, and host syncs OFF the
hot path. Three rules shape the implementation:

1. **Bucketed shapes, compiled once.** XLA compiles one program per
   input shape; letting request coalescing produce arbitrary batch
   sizes would compile an unbounded program set and pay seconds of
   latency on the first request of every new size. The engine instead
   pads every micro-batch UP to a small ascending set of batch buckets
   (default 1/8/32/128), so the jitted eval-mode apply
   (``models/zoo.infer_fn`` — train=False, no rng, fixed BN stats, and
   donation-FREE: the served params must survive the call) compiles at
   most ``len(buckets)`` programs, all AOT-warmed in :meth:`warmup`
   before the first request arrives. Padding is sound because
   eval-mode forwards are row-independent (no cross-batch reduction:
   BN uses running stats, dropout is off), so the padded rows cannot
   perturb the real ones — proven bit-identical in
   tests/test_serve_engine.py.

2. **Coalesce what is waiting, never wait to coalesce.** The batcher
   takes every queued request up to the largest bucket and serves them
   as one forward. Under load, batches fill toward the big buckets
   (throughput); when idle, a lone request rides the size-1 bucket
   immediately (latency). No artificial batching window.

3. **Swap params between batches.** Hot reload (serve/reload.py)
   publishes a new :class:`ServedParams` by atomic reference swap; the
   batcher reads the reference once per micro-batch, so every request
   is served by exactly one coherent (params, model_state, step)
   triple, the served step only moves forward, and zero requests fail
   or drop during a swap (tests/test_serve_reload.py hammers this).

Admission control: the queue is bounded (``max_queue``) — a full queue
rejects with :class:`EngineOverloaded` carrying a ``retry_after_ms``
estimate from the EWMA batch time, per-request deadlines expire queued
requests with :class:`DeadlineExceeded` (rejected, never served), and
:meth:`drain` (wired to SIGTERM by the CLI, reusing the training
driver's grace discipline) stops admission, finishes the backlog, and
only then stops the batcher.

Telemetry rides the existing obs subsystem: ``tmpi_serve_*`` counters/
gauges/histograms in a :class:`~theanompi_tpu.obs.metrics.
MetricsRegistry` (p50/p99 via ``Histogram.quantile``), and periodic
``serve`` JSONL records (plus the reloader's ``reload`` records) in
``<obs_dir>/serve.jsonl`` — schemas in tools/check_obs_schema.py.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import NamedTuple, Optional, Sequence

import numpy as np

DEFAULT_BUCKETS = (1, 8, 32, 128)

# latency histogram bounds: request latencies live in the 1ms..seconds
# band (the obs DEFAULT_BUCKETS top out at 60s — step/checkpoint scale)
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0,
)


class Rejected(RuntimeError):
    """Base: the engine refused to take (or serve) a request."""

    retry_after_ms: Optional[float] = None


class EngineOverloaded(Rejected):
    """Admission control: the bounded queue is full. ``retry_after_ms``
    estimates when capacity frees up (queue depth x EWMA batch time)."""

    def __init__(self, depth: int, retry_after_ms: float):
        self.retry_after_ms = float(retry_after_ms)
        super().__init__(
            f"serve queue full ({depth} waiting); retry in "
            f"~{retry_after_ms:.0f} ms"
        )


class EngineDraining(Rejected):
    """The engine is draining (SIGTERM / shutdown): backlog is being
    served, new requests are not admitted."""

    def __init__(self):
        super().__init__("serve engine is draining; not admitting requests")


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed while it waited — rejected, not
    served (serving a result the client stopped waiting for wastes a
    batch slot someone else's deadline needed)."""


class EngineDead(RuntimeError):
    """The engine hard-died (:meth:`ServeEngine.abort` — a crashed
    replica, or chaos's ``replica_crash``): queued AND in-flight
    requests are rejected with this error, which a fronting router
    (serve/router.py) treats as "re-admit on a healthy replica", never
    as a client-visible failure."""


class ServedParams(NamedTuple):
    """One coherent serving triple, swapped by atomic reference."""

    params: object
    model_state: object
    step: int


class ServeResult(NamedTuple):
    """Per-request result: the logits row and the checkpoint step of
    the params that produced it (reload tests assert monotonicity)."""

    logits: np.ndarray
    step: int


class ServeFuture:
    """Minimal completion handle (threading.Event + slots — no
    concurrent.futures machinery on the hot path)."""

    __slots__ = ("_event", "_lock", "_value", "_error", "t_submit")

    def __init__(self):
        self._event = threading.Event()
        # settlement can come from the batcher thread OR a router
        # failover/abort path on another thread; first writer wins
        self._lock = threading.Lock()
        self._value: Optional[ServeResult] = None
        self._error: Optional[BaseException] = None
        self.t_submit = time.monotonic()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._event.wait(timeout):
            raise TimeoutError("serve request still in flight")
        if self._error is not None:
            raise self._error
        return self._value

    # -- engine side --------------------------------------------------------
    def _resolve(self, value: ServeResult) -> None:
        with self._lock:
            if not self._event.is_set():
                self._value = value
                self._event.set()

    def _reject(self, error: BaseException) -> None:
        with self._lock:
            if not self._event.is_set():
                self._error = error
                self._event.set()


class _Request:
    __slots__ = ("x", "deadline", "future")

    def __init__(self, x, deadline: Optional[float], future: ServeFuture):
        self.x = x
        self.deadline = deadline  # absolute time.monotonic(), or None
        self.future = future


class ServeEngine:
    """Dynamic micro-batching inference engine over one model.

    ``model`` is a constructed :class:`~theanompi_tpu.models.contract.
    Model`; requests are single examples shaped ``recipe.input_shape``
    (float images, or int token rows for LM models). Params come from
    :meth:`load_initial` / :meth:`set_params` (serve/reload.py swaps
    them live). Lifecycle: construct → ``load_initial`` → ``warmup`` →
    ``start`` → ``submit``/``infer`` ... → ``drain``.

    ``default_deadline_ms``: applied to requests that don't carry their
    own; None = requests wait indefinitely.
    ``record_every``: write a ``serve`` JSONL record every N
    micro-batches (obs_dir only); one final record lands at drain.
    ``replica_id``: set by the router (serve/router.py) when this
    engine is one member of a replica group — rides every ``serve``
    record so a fleet's obs streams attribute to the member.
    ``sink_name``: the JSONL file under ``obs_dir`` (replica members
    write ``serve_r<id>.jsonl`` so N members never interleave one
    file).
    """

    def __init__(
        self,
        model,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_queue: int = 256,
        default_deadline_ms: Optional[float] = None,
        obs_dir: Optional[str] = None,
        registry=None,
        record_every: int = 50,
        replica_id: Optional[int] = None,
        sink_name: str = "serve.jsonl",
    ):
        from theanompi_tpu.models.zoo import infer_fn
        from theanompi_tpu.obs.metrics import MetricsRegistry

        self.model = model
        self.buckets = tuple(sorted(int(b) for b in buckets))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets!r}")
        if len(set(self.buckets)) != len(self.buckets):
            raise ValueError(f"duplicate buckets in {buckets!r}")
        self.max_queue = int(max_queue)
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.default_deadline_ms = default_deadline_ms
        self.obs_dir = obs_dir
        self.record_every = max(1, int(record_every))
        self.replica_id = None if replica_id is None else int(replica_id)
        self.sink_name = str(sink_name)

        ishape = tuple(model.recipe.input_shape)
        self._ishape = ishape
        self._in_dtype = (
            np.int32 if getattr(model, "is_lm", False) else np.float32
        )

        # the ONE inference definition (models/zoo.infer_fn), jitted
        # donation-free; the host-side trace counter increments once per
        # compiled program (jit retraces exactly when a new input
        # signature arrives), so ``compile_count`` is the proof handle
        # for "≤ len(buckets) programs" (tests/test_serve_engine.py)
        import jax

        self._trace_count = 0
        fwd = infer_fn(model)

        def _counted(params, model_state, x):
            self._trace_count += 1  # trace-time only, never per call
            return fwd(params, model_state, x)

        self._fwd = jax.jit(_counted)
        # the serving ShardingRecipe (parallel/recipe.py): params/BN
        # replicated on the serving mesh — the DECLARED placement the
        # train->serve handoff check (tools/analyze/sharding.py,
        # SHARD004) compares against the training engine's stamped
        # ``__topology__`` specs, and the placement set_params uses
        from theanompi_tpu.parallel.recipe import ShardingRecipe

        self.sharding = ShardingRecipe.serve()

        self._served: Optional[ServedParams] = None
        self._swap_lock = threading.Lock()
        self._q: collections.deque[_Request] = collections.deque()
        self._cond = threading.Condition()
        self._draining = False
        self._abort_error: Optional[BaseException] = None
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._batch_s_ewma: Optional[float] = None
        self._batches = 0
        self._fill_sum = 0.0
        self._serve_f = None
        self._sink_lock = threading.Lock()
        self._sink_retired = False

        self.registry = registry or MetricsRegistry()
        self._h_latency = self.registry.histogram(
            "tmpi_serve_latency_seconds",
            help="request latency, submit -> result (serve/engine.py)",
            buckets=LATENCY_BUCKETS,
        )
        self._g_queue = self.registry.gauge(
            "tmpi_serve_queue_depth", help="requests waiting for a batch slot"
        )
        self._g_fill = self.registry.gauge(
            "tmpi_serve_batch_fill",
            help="real rows / bucket rows of the last micro-batch",
        )
        self._g_step = self.registry.gauge(
            "tmpi_serve_params_step", help="checkpoint step currently served"
        )
        self._c_requests = self.registry.counter(
            "tmpi_serve_requests_total",
            help="requests by outcome (status=served|expired|rejected)",
        )
        self._c_batches = self.registry.counter(
            "tmpi_serve_batches_total",
            help="micro-batches by bucket size (bucket=N)",
        )
        self._c_reloads = self.registry.counter(
            "tmpi_serve_reloads_total",
            help="checkpoint hot-reloads applied (serve/reload.py)",
        )

    # -- params -------------------------------------------------------------
    @property
    def params_step(self) -> int:
        """Checkpoint step currently served (-1 before load_initial)."""
        served = self._served
        return served.step if served is not None else -1

    def load_initial(self, ckpt_dir: str) -> int:
        """Load the newest VERIFIED checkpoint from a training run's
        keep-chain (the same discovery resume uses) and serve it."""
        from theanompi_tpu.serve.reload import load_for_serving
        from theanompi_tpu.utils.checkpoint import latest_checkpoint

        path = latest_checkpoint(ckpt_dir, verify=True)
        if path is None:
            raise FileNotFoundError(
                f"no verified checkpoint under {ckpt_dir!r} to serve"
            )
        params, model_state, step = load_for_serving(path, self.model)
        self.set_params(params, model_state, step)
        return step

    def set_params(self, params, model_state, step: int) -> bool:
        """Atomically publish a serving triple. Refuses to move the
        served step BACKWARD (a slow reload racing a fresh one must not
        regress what is served); returns whether the swap happened.
        In-flight micro-batches finish on the triple they read — the
        swap is a reference assignment, nothing is mutated. The
        device_put runs OUTSIDE the swap lock (it is the slow part),
        and the step check re-runs under it, so two racing publishers
        cannot interleave check and assignment."""
        step = int(step)
        current = self._served
        if current is not None and step <= current.step:
            return False
        # placement per the serving recipe (replicated; plain
        # device_put on the single-device mesh — see
        # ShardingRecipe.place_replicated)
        params = self.sharding.place_replicated(params)
        model_state = self.sharding.place_replicated(model_state)
        with self._swap_lock:
            current = self._served
            if current is not None and step <= current.step:
                return False
            self._served = ServedParams(params, model_state, step)
            # gauge inside the lock: a racing older publisher must not
            # leave the exported step regressed vs what is served
            self._g_step.set(step)
        return True

    def note_reload(self, from_step: int, to_step: int, ms: float) -> None:
        """Reloader hook: count the swap + write a ``reload`` record."""
        self._c_reloads.inc()
        self._write_record({
            "kind": "reload", "t": time.time(),
            "from_step": int(from_step), "to_step": int(to_step),
            "ms": round(float(ms), 3),
        })

    def note_reload_failed(self, from_step: int, error: str) -> None:
        """Reloader hook for a reload that verified but failed to LOAD
        (the keep-chain pruned the file between discovery and open —
        the TOCTOU race — or a structure mismatch): count it and write
        a failed ``reload`` record (``ok: false``, ``to_step: -1``) so
        the telemetry shows the race happened even though serving never
        blinked and the next poll simply retries."""
        self._c_reloads.inc(status="failed")
        self._write_record({
            "kind": "reload", "t": time.time(),
            "from_step": int(from_step), "to_step": -1,
            "ok": False, "error": str(error)[:500],
        })

    # -- lifecycle ----------------------------------------------------------
    def warmup(self) -> int:
        """AOT-warm every bucket shape through the jitted apply, so no
        request ever pays a compile. Returns the compile count (==
        len(buckets) on a fresh engine; re-warms are free)."""
        import jax.numpy as jnp

        if self._served is None:
            raise RuntimeError("warmup needs params (load_initial first)")
        served = self._served
        for b in self.buckets:
            x = jnp.zeros((b, *self._ishape), self._in_dtype)
            np.asarray(self._fwd(served.params, served.model_state, x))
        return self.compile_count

    @property
    def compile_count(self) -> int:
        """Programs compiled so far (trace-count of the jitted apply)."""
        return self._trace_count

    def start(self) -> None:
        # under the engine lock: the router's supervisor starts
        # restarted members from its own thread
        with self._cond:
            if self._thread is not None:
                raise RuntimeError("engine already started")
            self._thread = threading.Thread(
                target=self._loop, name="tmpi-serve-batcher", daemon=True
            )
        self._thread.start()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: reject new admissions, serve everything
        already queued, stop the batcher, flush the final ``serve``
        record. Idempotent. Returns True when the backlog fully
        drained inside ``timeout``."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = None if timeout is None else time.monotonic() + timeout
        drained = True
        if self._thread is not None:
            self._thread.join(
                None if deadline is None else max(0.0, deadline - time.monotonic())
            )
            drained = not self._thread.is_alive()
        # claim the final record exactly once, under the sink lock:
        # drain is reachable from the SIGTERM drain thread AND the
        # CLI's finally concurrently, and a bare check-then-act here
        # wrote the final record twice
        with self._sink_lock:
            first = not self._stopped.is_set()
            self._stopped.set()
        if first and self.obs_dir is not None:
            # compute the record outside the lock (it reads the
            # internally-locked counters), then write-and-retire in
            # ONE hold — a straggling reloader write can land before
            # the final record, never after it
            rec = self.serve_record()
            with self._sink_lock:
                if not self._sink_retired:
                    if self._serve_f is None:
                        os.makedirs(self.obs_dir, exist_ok=True)
                        self._serve_f = open(
                            os.path.join(self.obs_dir, self.sink_name), "a"
                        )
                    self._serve_f.write(json.dumps(rec) + "\n")
                    self._sink_retired = True
                    self._serve_f.close()
                    self._serve_f = None
        return drained

    close = drain

    def abort(self, error: Optional[BaseException] = None) -> None:
        """Hard death (the crash analogue of :meth:`drain`): stop
        admitting, reject every QUEUED request with ``error``
        (default :class:`EngineDead`), and poison the in-flight batch
        so its futures reject too — nothing resolves after an abort.
        A fronting router re-admits the rejected requests on healthy
        replicas; a bare engine surfaces them as failures. Idempotent.
        """
        err = error if error is not None else EngineDead("engine aborted")
        with self._cond:
            if self._abort_error is None:
                self._abort_error = err
            self._draining = True
            doomed = list(self._q)
            self._q.clear()
            self._g_queue.set(0.0)
            self._cond.notify_all()
        for r in doomed:
            r.future._reject(err)
        if doomed:
            self._c_requests.inc(len(doomed), status="failed")

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def alive(self) -> bool:
        """Health the router polls: a started, un-aborted, un-draining
        engine whose batcher thread is running."""
        t = self._thread
        return (t is not None and t.is_alive()
                and self._abort_error is None and not self._draining)

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a batch slot (the router's load signal)."""
        return len(self._q)

    @property
    def batch_s_ewma(self) -> Optional[float]:
        """EWMA seconds per micro-batch (None before the first batch) —
        the other half of the router's least-loaded score."""
        return self._batch_s_ewma

    # -- request path -------------------------------------------------------
    def submit(self, x, deadline_ms: Optional[float] = None) -> ServeFuture:
        """Enqueue one example; returns a :class:`ServeFuture`.
        Raises :class:`EngineOverloaded` / :class:`EngineDraining`
        synchronously (admission control); deadline expiry surfaces
        from ``future.result()`` as :class:`DeadlineExceeded`."""
        x = np.asarray(x, self._in_dtype)
        if x.shape != self._ishape:
            raise ValueError(
                f"request shape {x.shape} != model input {self._ishape}"
            )
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline = (
            time.monotonic() + float(deadline_ms) / 1000.0
            if deadline_ms else None
        )
        fut = ServeFuture()
        with self._cond:
            if self._draining:
                self._c_requests.inc(status="rejected")
                raise EngineDraining()
            if len(self._q) >= self.max_queue:
                self._c_requests.inc(status="rejected")
                batch_s = self._batch_s_ewma or 0.05
                n_batches = -(-len(self._q) // self.buckets[-1])
                raise EngineOverloaded(
                    len(self._q), retry_after_ms=1000.0 * batch_s * n_batches
                )
            self._q.append(_Request(x, deadline, fut))
            self._g_queue.set(len(self._q))
            self._cond.notify()
        return fut

    def infer(self, x, deadline_ms: Optional[float] = None,
              timeout: Optional[float] = 30.0) -> ServeResult:
        """Blocking convenience: submit + wait."""
        return self.submit(x, deadline_ms=deadline_ms).result(timeout)

    # -- batcher ------------------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _loop(self) -> None:
        max_take = self.buckets[-1]
        while True:
            with self._cond:
                while not self._q and not self._draining:
                    self._cond.wait(0.05)
                if not self._q and self._draining:
                    return
                reqs = [
                    self._q.popleft()
                    for _ in range(min(len(self._q), max_take))
                ]
                self._g_queue.set(len(self._q))
            try:
                self._serve_batch(reqs)
            except BaseException as e:  # noqa: BLE001 — requests must
                # never hang on an engine bug: fail THIS batch's futures
                # and keep serving (a poisoned input must not take the
                # engine down with it). An abort poisons the batch on
                # purpose — those count as failed, not rejected
                failed = 0
                for r in reqs:
                    if not r.future.done():
                        r.future._reject(e)
                        failed += 1
                if failed:
                    status = ("failed" if e is self._abort_error
                              else "rejected")
                    self._c_requests.inc(failed, status=status)

    def _serve_batch(self, reqs: list) -> None:
        import jax.numpy as jnp

        err = self._abort_error
        if err is not None:  # the replica died under this batch
            raise err
        now = time.monotonic()
        live = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                r.future._reject(DeadlineExceeded(
                    f"deadline passed {1000 * (now - r.deadline):.1f} ms "
                    "before a batch slot opened"
                ))
                self._c_requests.inc(status="expired")
            else:
                live.append(r)
        if not live:
            return
        t0 = time.monotonic()
        bucket = self._bucket_for(len(live))
        batch = np.zeros((bucket, *self._ishape), self._in_dtype)
        for i, r in enumerate(live):
            batch[i] = r.x
        served = self._served  # ONE read: the swap point for hot reload
        logits = np.asarray(
            self._fwd(served.params, served.model_state, jnp.asarray(batch))
        )
        t_done = time.monotonic()
        err = self._abort_error
        if err is not None:  # abort landed mid-forward: nothing
            raise err        # resolves after a death
        for i, r in enumerate(live):
            r.future._resolve(ServeResult(logits[i], served.step))
            self._h_latency.observe(t_done - r.future.t_submit)
        self._c_requests.inc(len(live), status="served")
        self._c_batches.inc(bucket=bucket)
        fill = len(live) / bucket
        self._g_fill.set(fill)
        self._fill_sum += fill
        self._batches += 1
        batch_s = t_done - t0
        self._batch_s_ewma = (
            batch_s if self._batch_s_ewma is None
            else 0.8 * self._batch_s_ewma + 0.2 * batch_s
        )
        if self._batches % self.record_every == 0:
            self._write_serve_record()

    # -- stats / telemetry --------------------------------------------------
    @property
    def mean_batch_fill(self) -> Optional[float]:
        return self._fill_sum / self._batches if self._batches else None

    def latency_ms(self, q: float) -> Optional[float]:
        s = self._h_latency.quantile(q)
        return None if s is None else 1000.0 * s

    def stats(self) -> dict:
        """Flat numeric snapshot (the ``serve`` record's metrics map;
        every key is ``tmpi_serve_``-prefixed — enforced by the schema
        checker so serve records stay greppable by one prefix)."""
        out = {
            "tmpi_serve_queue_depth": float(len(self._q)),
            "tmpi_serve_served_total": self._c_requests.value(status="served"),
            "tmpi_serve_expired_total": self._c_requests.value(status="expired"),
            "tmpi_serve_rejected_total": self._c_requests.value(status="rejected"),
            "tmpi_serve_failed_total": self._c_requests.value(status="failed"),
            "tmpi_serve_reloads_total": self._c_reloads.value(),
            "tmpi_serve_reload_failures_total":
                self._c_reloads.value(status="failed"),
            "tmpi_serve_batches_total": float(self._batches),
        }
        if self._batches:
            out["tmpi_serve_batch_fill_mean"] = self.mean_batch_fill
        for name, q in (("p50", 0.5), ("p99", 0.99)):
            ms = self.latency_ms(q)
            if ms is not None:
                out[f"tmpi_serve_{name}_ms"] = ms
        return out

    def serve_record(self) -> dict:
        """The one constructor of a ``kind=serve`` record (schema:
        tools/check_obs_schema.py) — used for the periodic/drain-time
        obs lines AND the CLI's final stdout line, so the two can never
        drift apart on shape. Replica members stamp ``replica_id``."""
        rec = {"kind": "serve", "t": time.time(),
               "params_step": self.params_step, "metrics": self.stats()}
        if self.replica_id is not None:
            rec["replica_id"] = self.replica_id
        return rec

    def _write_serve_record(self) -> None:
        self._write_record(self.serve_record())

    def _write_record(self, rec: dict) -> None:
        if self.obs_dir is None:
            return
        with self._sink_lock:
            if self._sink_retired:
                return
            if self._serve_f is None:
                os.makedirs(self.obs_dir, exist_ok=True)
                self._serve_f = open(
                    os.path.join(self.obs_dir, self.sink_name), "a"
                )
            self._serve_f.write(json.dumps(rec) + "\n")
            self._serve_f.flush()
