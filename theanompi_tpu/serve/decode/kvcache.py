"""Paged KV-cache for continuous-batching LM decode.

ONE preallocated device pool per pool-kind (K and V), shaped

    [n_layers, n_pages + 1, page_size, n_heads, head_dim]

so the compiled decode/prefill programs see a FIXED shape forever: pages
are handed out and returned by a host-side free-list, and the programs
receive gather/scatter *indices* (per-sequence page tables) instead of
resized buffers. Index ``n_pages`` is the SCRATCH page — never owned by
any sequence; inactive batch slots and the padding tail of a prefill
scatter are routed there, so every write in the jitted step is
unconditional (no dynamic shapes, no host-side branching) and the
garbage lands somewhere no read ever looks (reads are masked by
``seq_lens``).

Admission is worst-case: a sequence reserves
``pages_needed(prompt_len + max_new_tokens)`` pages up front, so a
running sequence can NEVER hit an out-of-pages fault mid-generation —
exhaustion is an admission-time signal (:class:`KVExhausted`), which the
scheduler turns into queueing, not corruption. Eviction (finish,
deadline, abort) returns the pages; the free-list keeps conservation
counters (``pages_out_total``/``pages_in_total``) so the chaos oracle
can assert pages_out == pages_in after drain.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


class KVExhausted(RuntimeError):
    """Admission could not reserve the sequence's worst-case pages."""


def pages_needed(total_len: int, page_size: int) -> int:
    """Pages covering ``total_len`` cache positions (ceil division)."""
    if total_len <= 0:
        return 0
    return -(-int(total_len) // int(page_size))


class FreeList:
    """Host-side page allocator over physical pages ``0..n_pages-1``.

    Not thread-safe by itself — the scheduler serializes access (one
    decode loop owns it). Double frees and foreign pages raise: a page
    accounting bug must surface as an exception, not as two sequences
    silently sharing a page.
    """

    def __init__(self, n_pages: int):
        if n_pages <= 0:
            raise ValueError(f"n_pages must be positive, got {n_pages}")
        self.n_pages = int(n_pages)
        # pop() from the tail hands out ascending page ids — makes unit
        # tests deterministic and keeps early pages hot
        self._free: List[int] = list(range(self.n_pages - 1, -1, -1))
        self._out: set = set()
        self.pages_out_total = 0
        self.pages_in_total = 0

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return self.n_pages - len(self._free)

    def alloc(self, n: int) -> List[int]:
        """Reserve ``n`` pages or raise :class:`KVExhausted` (atomic:
        either all ``n`` come out or none do)."""
        if n < 0:
            raise ValueError(f"cannot alloc {n} pages")
        if n > len(self._free):
            raise KVExhausted(
                f"need {n} KV pages, only {len(self._free)} free of "
                f"{self.n_pages}"
            )
        pages = [self._free.pop() for _ in range(n)]
        self._out.update(pages)
        self.pages_out_total += n
        return pages

    def free(self, pages: Sequence[int]) -> None:
        for pg in pages:
            pg = int(pg)
            if pg not in self._out:
                raise ValueError(
                    f"page {pg} returned but not outstanding "
                    "(double free, or a page this list never issued)"
                )
            self._out.discard(pg)
            self._free.append(pg)
            self.pages_in_total += 1

    def conserved(self) -> bool:
        """True iff every page ever issued came back — the chaos
        oracle's KV-conservation invariant after drain."""
        return (
            not self._out
            and len(self._free) == self.n_pages
            and self.pages_in_total == self.pages_out_total
        )


class PagedKVCache:
    """Pools + per-slot page tables + free-list for up to ``max_seqs``
    concurrent sequences.

    The pools are jax arrays threaded FUNCTIONALLY through the jitted
    programs (each step returns updated pools; the cache just holds the
    latest reference) — nothing here ever resizes device memory. The
    page tables are a host ``int32 [max_seqs, max_pages_per_seq]``
    array, scratch-filled for unowned entries, handed to the decode
    step as a plain input every iteration (a few hundred bytes of H2D).
    """

    def __init__(
        self,
        *,
        n_layers: int,
        n_heads: int,
        head_dim: int,
        page_size: int,
        n_pages: int,
        max_seqs: int,
        max_pages_per_seq: int,
        dtype=None,
    ):
        import jax.numpy as jnp  # deferred: FreeList stays importable sans jax

        if page_size <= 0:
            raise ValueError(f"page_size must be positive, got {page_size}")
        if max_pages_per_seq <= 0 or max_pages_per_seq > n_pages:
            raise ValueError(
                f"max_pages_per_seq={max_pages_per_seq} must be in "
                f"1..n_pages ({n_pages})"
            )
        self.page_size = int(page_size)
        self.n_pages = int(n_pages)
        self.scratch = self.n_pages  # the sacrificial page index
        self.max_seqs = int(max_seqs)
        self.max_pages_per_seq = int(max_pages_per_seq)
        shape = (n_layers, self.n_pages + 1, self.page_size, n_heads, head_dim)
        dt = dtype if dtype is not None else jnp.float32
        self.k_pool = jnp.zeros(shape, dt)
        self.v_pool = jnp.zeros(shape, dt)
        self.free_list = FreeList(self.n_pages)
        self.page_tables = np.full(
            (self.max_seqs, self.max_pages_per_seq), self.scratch, np.int32
        )
        self._slot_pages: dict = {}

    @property
    def max_context(self) -> int:
        """Longest sequence (prompt + generated) a slot can hold."""
        return self.max_pages_per_seq * self.page_size

    def reserve(self, slot: int, total_len: int) -> List[int]:
        """Reserve worst-case pages for a sequence of ``total_len``
        positions into ``slot``. Raises :class:`KVExhausted` when the
        free-list cannot cover it; raises ValueError for a slot already
        holding pages (the scheduler must release first)."""
        if slot in self._slot_pages:
            raise ValueError(f"slot {slot} already holds pages")
        need = pages_needed(total_len, self.page_size)
        if need > self.max_pages_per_seq:
            raise KVExhausted(
                f"sequence needs {need} pages "
                f"({total_len} positions / page_size {self.page_size}) "
                f"but a slot holds at most {self.max_pages_per_seq}"
            )
        pages = self.free_list.alloc(need)
        self.page_tables[slot, :] = self.scratch
        self.page_tables[slot, :need] = pages
        self._slot_pages[slot] = pages
        return pages

    def release(self, slot: int) -> int:
        """Return ``slot``'s pages to the free-list (idempotent for a
        slot holding none). Returns how many pages came back."""
        pages = self._slot_pages.pop(slot, None)
        self.page_tables[slot, :] = self.scratch
        if not pages:
            return 0
        self.free_list.free(pages)
        return len(pages)

    def release_all(self) -> int:
        """Drain-time sweep: return every outstanding slot's pages."""
        return sum(self.release(s) for s in list(self._slot_pages))

    @property
    def pages_used(self) -> int:
        return self.free_list.n_used

    @property
    def pages_free(self) -> int:
        return self.free_list.n_free
