"""Continuous-batching LM decode engine over a paged KV-cache.

The decode sibling of :class:`theanompi_tpu.serve.engine.ServeEngine`
(one-shot eval forwards): same queue/admission/drain/hot-reload
lifecycle and the same ``submit``/``drain``/``set_params``/
``params_step`` surface — so :class:`theanompi_tpu.serve.router.Router`
fronts N decode replicas UNCHANGED — but each request is a *generation*
(a prompt plus up to ``max_new_tokens`` sampled continuations), not a
single forward. Three rules carry over from the eval engine, reshaped
for autoregression:

1. **Fixed shapes, bounded programs.** The KV pool is ONE preallocated
   device array per layer (``serve/decode/kvcache.py``); page tables
   and per-slot operand vectors have fixed ``[max_seqs]`` shapes, so
   the single-token decode step compiles exactly ONCE no matter how
   sequences come and go. Prompt prefill pads into a small set of
   length buckets (page-size multiples), one compiled program each,
   AOT-warmed in :meth:`warmup`. Total programs:
   ``len(prefill_buckets) + 1`` — proven by the trace counter
   (``compile_count``), same idiom as the eval engine.

2. **Iteration-level scheduling.** Between decode steps the scheduler
   (``serve/decode/scheduler.py``) admits waiting prompts into free
   batch slots (reserving worst-case pages so a running sequence can
   never die of page exhaustion) and evicts finished/deadline-passed
   ones — sequences join and leave a RUNNING batch, no static-batch
   barrier. The prompt's first ``L-1`` tokens prefill the cache; its
   LAST token rides the decode step, so every emitted token exits
   through the one decode program and each iteration has exactly ONE
   host drain point (the ``np.asarray`` on the next-token vector —
   ``tools/check_hot_loop.py`` HOT004 guards this).

3. **Swap params between iterations.** Hot reload publishes a new
   :class:`~theanompi_tpu.serve.engine.ServedParams` by atomic
   reference swap; the decode loop reads the reference ONCE per
   iteration, so a mid-generation reload changes the params a sequence
   decodes with between tokens but never mid-step, the served step
   only moves forward, and zero in-flight generations drop
   (tests/test_decode_engine.py hammers this, chaos's decode
   schedules hammer it harder).

Telemetry is ``tmpi_decode_*``-prefixed (schema: ``kind=decode`` in
tools/check_obs_schema.py): TTFT/TPOT histograms, tokens/sec,
kv page occupancy, batch occupancy, eviction/expiry counters, plus
periodic ``decode`` JSONL records in ``<obs_dir>/decode.jsonl``.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import NamedTuple, Optional, Sequence

import numpy as np

from theanompi_tpu.serve.decode.kvcache import PagedKVCache, pages_needed
from theanompi_tpu.serve.decode.scheduler import DecodeScheduler, DecodeSequence
from theanompi_tpu.serve.engine import (
    LATENCY_BUCKETS,
    DeadlineExceeded,
    EngineDead,
    EngineDraining,
    EngineOverloaded,
    Rejected,
    ServedParams,
    ServeFuture,
)

__all__ = [
    "DecodeEngine",
    "DecodeResult",
    "DEFAULT_PREFILL_BUCKETS",
    "DeadlineExceeded",
    "EngineDead",
    "EngineDraining",
    "EngineOverloaded",
    "Rejected",
]

DEFAULT_PREFILL_BUCKETS = (16, 64)

# TPOT (time-per-output-token) lives well below request latency — extend
# the serve band downward into the sub-millisecond range
TPOT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0,
)


class DecodeResult(NamedTuple):
    """Per-request result: the generated token ids and the checkpoint
    step of the params that produced the LAST token (a mid-generation
    hot reload legitimately splits a sequence across steps; the final
    step is what monotonicity tests assert on)."""

    tokens: np.ndarray
    step: int


class DecodeEngine:
    """Continuous-batching generation engine over one LM.

    ``model`` is a constructed zoo model with ``supports_decode`` (the
    incremental ``decode_prefill``/``decode_step`` surface —
    models/lm.py). Requests are 1-D int32 token prompts of any length
    up to ``max(prefill_buckets) + 1``; results are
    :class:`DecodeResult`. Lifecycle mirrors the eval engine:
    construct → ``load_initial`` → ``warmup`` → ``start`` →
    ``submit``/``generate`` ... → ``drain``.

    ``kv_pages`` fixed device pages of ``page_size`` positions bound
    total cache capacity; ``max_seqs`` bounds the decode batch width.
    ``mode="static"`` disables iteration-level admission (a batch runs
    to completion before the next forms) — the strawman the decode
    bench's continuous-vs-static ratio measures against.
    ``temperature`` is the default sampling temperature (0 = greedy);
    sampling draws from a PRNG stream keyed by ``seed`` and the
    iteration counter INSIDE the jitted step, so replays are
    deterministic and the key never retraces the program.
    """

    def __init__(
        self,
        model,
        *,
        prefill_buckets: Sequence[int] = DEFAULT_PREFILL_BUCKETS,
        kv_pages: int = 64,
        page_size: int = 16,
        max_seqs: int = 8,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        mode: str = "continuous",
        max_queue: int = 256,
        default_deadline_ms: Optional[float] = None,
        obs_dir: Optional[str] = None,
        registry=None,
        record_every: int = 50,
        replica_id: Optional[int] = None,
        sink_name: str = "decode.jsonl",
        seed: int = 0,
        sharding=None,
    ):
        from theanompi_tpu.obs.metrics import MetricsRegistry

        if not getattr(model, "supports_decode", False):
            raise ValueError(
                f"{type(model).__name__} does not support incremental "
                "decode (no decode_prefill/decode_step surface — see "
                "models/lm.py); serve it with the eval-forward "
                "ServeEngine instead"
            )
        self.model = model
        arch = model.arch
        self.page_size = int(page_size)
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.default_temperature = float(temperature)
        self.max_queue = int(max_queue)
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.default_deadline_ms = default_deadline_ms
        self.obs_dir = obs_dir
        self.record_every = max(1, int(record_every))
        self.replica_id = None if replica_id is None else int(replica_id)
        self.sink_name = str(sink_name)
        self._seed = int(seed)

        buckets = tuple(sorted(int(b) for b in prefill_buckets))
        # longest generation the pool must hold: the largest admissible
        # prompt plus the full output budget, capped by the model's
        # position table
        self.max_context = min(
            int(arch.max_len), buckets[-1] + 1 + self.max_new_tokens
        )
        max_pages_per_seq = pages_needed(self.max_context, self.page_size)
        if kv_pages < max_pages_per_seq:
            raise ValueError(
                f"kv_pages={kv_pages} cannot hold even one worst-case "
                f"sequence ({max_pages_per_seq} pages for "
                f"{self.max_context} positions at page_size "
                f"{self.page_size})"
            )
        self._cache = PagedKVCache(
            n_layers=arch.n_layers,
            n_heads=arch.n_heads,
            head_dim=arch.d_model // arch.n_heads,
            page_size=self.page_size,
            n_pages=int(kv_pages),
            max_seqs=int(max_seqs),
            max_pages_per_seq=max_pages_per_seq,
        )
        self._sched = DecodeScheduler(
            self._cache, prefill_buckets=buckets, mode=mode
        )
        # the router reads eng.buckets[-1] for its backlog math; for a
        # decode member that's the prefill bucket set
        self.buckets = self._sched.buckets

        # two jitted programs (+1 shape per prefill bucket), both routed
        # through the host-side trace counter — ``compile_count`` proves
        # the "len(prefill_buckets) + 1 programs" bound under any
        # request mix (tests/test_decode_engine.py)
        import jax

        self._trace_count = 0
        seed_const = self._seed

        def _counted_prefill(params, tokens, pages, k_pool, v_pool):
            self._trace_count += 1  # trace-time only, never per call
            return model.decode_prefill(
                params, tokens, pages, k_pool, v_pool,
                page_size=self.page_size,
            )

        def _counted_decode(params, k_pool, v_pool, tables, seq_lens,
                            last, active, temp, it):
            self._trace_count += 1  # trace-time only, never per call
            # the sampling key is derived INSIDE the program from the
            # traced iteration counter — deterministic replay, no
            # per-iteration retrace, no host-side key threading
            key = jax.random.fold_in(jax.random.PRNGKey(seed_const), it)
            return model.decode_step(
                params, k_pool, v_pool, tables, seq_lens, last, active,
                temp, key, page_size=self.page_size,
            )

        self._prefill = jax.jit(_counted_prefill)
        self._decode = jax.jit(_counted_decode)

        from theanompi_tpu.parallel.recipe import ShardingRecipe

        # declared serving placement (SHARD004's comparison target);
        # ``tmpi serve --decode --shard tensor`` passes the tensor-serve
        # recipe here instead of the replicated default
        self.sharding = sharding if sharding is not None else ShardingRecipe.serve()

        self._served: Optional[ServedParams] = None
        self._swap_lock = threading.Lock()
        self._q: collections.deque[DecodeSequence] = collections.deque()
        self._cond = threading.Condition()
        self._draining = False
        self._abort_error: Optional[BaseException] = None
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._batch_s_ewma: Optional[float] = None
        self._iterations = 0
        self._tokens_total = 0
        self._t_started: Optional[float] = None
        self._sink_f = None
        self._sink_lock = threading.Lock()
        self._sink_retired = False

        self.registry = registry or MetricsRegistry()
        self._h_ttft = self.registry.histogram(
            "tmpi_decode_ttft_seconds",
            help="time to first generated token, submit -> token",
            buckets=LATENCY_BUCKETS,
        )
        self._h_tpot = self.registry.histogram(
            "tmpi_decode_tpot_seconds",
            help="per-output-token latency after the first token",
            buckets=TPOT_BUCKETS,
        )
        self._g_queue = self.registry.gauge(
            "tmpi_decode_queue_depth",
            help="generations waiting for a batch slot",
        )
        self._g_occupancy = self.registry.gauge(
            "tmpi_decode_batch_occupancy",
            help="running sequences / max_seqs of the last iteration",
        )
        self._g_pages_used = self.registry.gauge(
            "tmpi_decode_kv_pages_used", help="KV pool pages reserved"
        )
        self._g_pages_free = self.registry.gauge(
            "tmpi_decode_kv_pages_free", help="KV pool pages on the free-list"
        )
        self._g_step = self.registry.gauge(
            "tmpi_decode_params_step", help="checkpoint step currently served"
        )
        self._c_requests = self.registry.counter(
            "tmpi_decode_requests_total",
            help="generations by outcome "
                 "(status=served|expired|evicted|rejected|failed)",
        )
        self._c_tokens = self.registry.counter(
            "tmpi_decode_tokens_total", help="tokens generated"
        )
        self._c_prefills = self.registry.counter(
            "tmpi_decode_prefills_total",
            help="prompt prefills by length bucket (bucket=N)",
        )
        self._c_evicted = self.registry.counter(
            "tmpi_decode_evicted_total",
            help="running sequences evicted (deadline) — typed, not a drop",
        )
        self._c_preempted = self.registry.counter(
            "tmpi_decode_preempted_total",
            help="running sequences preempted for capacity (admission "
                 "reserves worst-case pages, so this stays 0 — the "
                 "counter exists so a future best-effort-admission mode "
                 "cannot hide preemptions)",
        )
        self._c_reloads = self.registry.counter(
            "tmpi_decode_reloads_total",
            help="checkpoint hot-reloads applied (serve/reload.py)",
        )

    # -- params (surface shared with ServeEngine; router/reloader use it) ---
    @property
    def params_step(self) -> int:
        """Checkpoint step currently served (-1 before load_initial)."""
        served = self._served
        return served.step if served is not None else -1

    def load_initial(self, ckpt_dir: str) -> int:
        """Load the newest VERIFIED checkpoint from a training run's
        keep-chain and serve it (same discovery/reshard path as the
        eval engine: serve/reload.py::load_for_serving)."""
        from theanompi_tpu.serve.reload import load_for_serving
        from theanompi_tpu.utils.checkpoint import latest_checkpoint

        path = latest_checkpoint(ckpt_dir, verify=True)
        if path is None:
            raise FileNotFoundError(
                f"no verified checkpoint under {ckpt_dir!r} to serve"
            )
        params, model_state, step = load_for_serving(
            path, self.model, target_mesh=self.sharding.mesh
        )
        self.set_params(params, model_state, step)
        return step

    def set_params(self, params, model_state, step: int) -> bool:
        """Atomically publish a serving triple; refuses to move the
        served step backward. Same discipline as the eval engine: the
        device placement runs OUTSIDE the swap lock, the step check
        re-runs under it. A generation in flight simply decodes its
        next token with the new params — the KV cache entries written
        under the old params remain valid context (same architecture,
        different weights: exactly the semantics of serving the newer
        checkpoint)."""
        step = int(step)
        current = self._served
        if current is not None and step <= current.step:
            return False
        place = getattr(self.sharding, "place_params", None)
        params = place(params) if place else self.sharding.place_replicated(params)
        model_state = self.sharding.place_replicated(model_state)
        with self._swap_lock:
            current = self._served
            if current is not None and step <= current.step:
                return False
            self._served = ServedParams(params, model_state, step)
            self._g_step.set(step)
        return True

    def note_reload(self, from_step: int, to_step: int, ms: float) -> None:
        """Reloader hook: count the swap + write a ``reload`` record."""
        self._c_reloads.inc()
        self._write_record({
            "kind": "reload", "t": time.time(),
            "from_step": int(from_step), "to_step": int(to_step),
            "ms": round(float(ms), 3),
        })

    def note_reload_failed(self, from_step: int, error: str) -> None:
        """Reloader hook for a verified-then-unloadable checkpoint (the
        TOCTOU race) — counted and recorded, serving never blinks."""
        self._c_reloads.inc(status="failed")
        self._write_record({
            "kind": "reload", "t": time.time(),
            "from_step": int(from_step), "to_step": -1,
            "ok": False, "error": str(error)[:500],
        })

    # -- lifecycle ----------------------------------------------------------
    def warmup(self) -> int:
        """AOT-compile every program before the first request: one
        prefill per bucket (pages all-scratch — the warmup K/V land on
        the write-discard page) and the single decode step (all slots
        inactive). Returns the compile count, ==
        ``len(prefill_buckets) + 1`` on a fresh engine."""
        import jax
        import jax.numpy as jnp

        if self._served is None:
            raise RuntimeError("warmup needs params (load_initial first)")
        served = self._served
        c = self._cache
        for b in self.buckets:
            toks = jnp.zeros((b,), jnp.int32)
            pages = jnp.full((b // self.page_size,), c.scratch, jnp.int32)
            out = self._prefill(served.params, toks, pages, c.k_pool, c.v_pool)
            jax.block_until_ready(out)  # compile now, discard scratch writes
        S = c.max_seqs
        nxt, _lg, _k, _v = self._decode(
            served.params, c.k_pool, c.v_pool,
            jnp.asarray(c.page_tables), jnp.zeros((S,), jnp.int32),
            jnp.zeros((S,), jnp.int32), jnp.zeros((S,), bool),
            jnp.zeros((S,), jnp.float32), np.int32(0),
        )
        np.asarray(nxt)
        return self.compile_count

    @property
    def compile_count(self) -> int:
        """Programs compiled so far (trace count across both jits)."""
        return self._trace_count

    def start(self) -> None:
        with self._cond:
            if self._thread is not None:
                raise RuntimeError("engine already started")
            self._t_started = time.monotonic()
            self._thread = threading.Thread(
                target=self._loop, name="tmpi-decode-batcher", daemon=True
            )
        self._thread.start()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: reject new admissions, run every queued
        AND running generation to completion (zero drops — the fleet
        invariant), stop the loop, flush the final ``decode`` record.
        Idempotent."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = None if timeout is None else time.monotonic() + timeout
        drained = True
        if self._thread is not None:
            self._thread.join(
                None if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            drained = not self._thread.is_alive()
        with self._sink_lock:
            first = not self._stopped.is_set()
            self._stopped.set()
        if first and self.obs_dir is not None:
            rec = self.decode_record()
            with self._sink_lock:
                if not self._sink_retired:
                    if self._sink_f is None:
                        os.makedirs(self.obs_dir, exist_ok=True)
                        self._sink_f = open(
                            os.path.join(self.obs_dir, self.sink_name), "a"
                        )
                    self._sink_f.write(json.dumps(rec) + "\n")
                    self._sink_retired = True
                    self._sink_f.close()
                    self._sink_f = None
        return drained

    close = drain

    def abort(self, error: Optional[BaseException] = None) -> None:
        """Hard death: stop admitting, reject every queued generation,
        poison the in-flight iteration so running generations reject
        too (the loop's failure path releases their KV pages — the
        free-list stays conserved even through a crash). A fronting
        router re-admits the rejected prompts on healthy replicas."""
        err = error if error is not None else EngineDead("engine aborted")
        with self._cond:
            if self._abort_error is None:
                self._abort_error = err
            self._draining = True
            doomed = list(self._q)
            self._q.clear()
            self._g_queue.set(0.0)
            self._cond.notify_all()
        for seq in doomed:
            seq.future._reject(err)
        if doomed:
            self._c_requests.inc(len(doomed), status="failed")

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def alive(self) -> bool:
        t = self._thread
        return (t is not None and t.is_alive()
                and self._abort_error is None and not self._draining)

    @property
    def queue_depth(self) -> int:
        """Generations waiting for a batch slot (the router's load
        signal): the submit queue plus the scheduler's waiting line."""
        return len(self._q) + self._sched.n_waiting

    @property
    def batch_s_ewma(self) -> Optional[float]:
        """EWMA seconds per decode iteration (prefills included)."""
        return self._batch_s_ewma

    # -- request path -------------------------------------------------------
    def submit(self, x, deadline_ms: Optional[float] = None,
               max_new_tokens: Optional[int] = None,
               temperature: Optional[float] = None) -> ServeFuture:
        """Enqueue one prompt (1-D int token ids); returns a future
        resolving to :class:`DecodeResult`. Admission control mirrors
        the eval engine: :class:`EngineOverloaded` /
        :class:`EngineDraining` raise synchronously, deadline expiry
        and eviction surface from ``future.result()`` as
        :class:`DeadlineExceeded`."""
        prompt = np.asarray(x, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(
                f"prompt must be a non-empty 1-D token row, got shape "
                f"{prompt.shape}"
            )
        if prompt.size > self._sched.max_prompt_len:
            raise ValueError(
                f"prompt of {prompt.size} tokens exceeds the largest "
                f"prefill bucket + 1 ({self._sched.max_prompt_len})"
            )
        n_new = self.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        n_new = min(n_new, self.max_context - int(prompt.size))
        if n_new < 1:
            raise ValueError(
                f"prompt of {prompt.size} tokens leaves no room to "
                f"generate within max_context {self.max_context}"
            )
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline = (
            time.monotonic() + float(deadline_ms) / 1000.0
            if deadline_ms else None
        )
        fut = ServeFuture()
        seq = DecodeSequence(
            prompt,
            max_new_tokens=n_new,
            temperature=(self.default_temperature if temperature is None
                         else float(temperature)),
            deadline=deadline,
            future=fut,
            t_submit=fut.t_submit,
        )
        with self._cond:
            if self._draining:
                self._c_requests.inc(status="rejected")
                raise EngineDraining()
            depth = len(self._q) + self._sched.n_waiting
            if depth >= self.max_queue:
                self._c_requests.inc(status="rejected")
                batch_s = self._batch_s_ewma or 0.05
                # a waiting generation needs ~max_new_tokens iterations
                # once admitted; estimate the backlog in batch rounds
                rounds = -(-depth // self._cache.max_seqs)
                raise EngineOverloaded(
                    depth,
                    retry_after_ms=1000.0 * batch_s
                    * self.max_new_tokens * rounds,
                )
            self._q.append(seq)
            self._g_queue.set(len(self._q) + self._sched.n_waiting)
            self._cond.notify()
        return fut

    def generate(self, x, deadline_ms: Optional[float] = None,
                 max_new_tokens: Optional[int] = None,
                 temperature: Optional[float] = None,
                 timeout: Optional[float] = 60.0) -> DecodeResult:
        """Blocking convenience: submit + wait."""
        return self.submit(
            x, deadline_ms=deadline_ms, max_new_tokens=max_new_tokens,
            temperature=temperature,
        ).result(timeout)

    def infer(self, x, deadline_ms: Optional[float] = None,
              timeout: Optional[float] = 60.0) -> DecodeResult:
        """ServeEngine-signature blocking call (the CLI selftest and
        frontend duck-type this surface)."""
        return self.generate(x, deadline_ms=deadline_ms, timeout=timeout)

    # -- decode loop --------------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cond:
                while (not self._q and not self._sched.has_work()
                       and not self._draining):
                    self._cond.wait(0.05)
                if (self._draining and not self._q
                        and not self._sched.has_work()):
                    return
                while self._q:
                    self._sched.add(self._q.popleft())
                self._g_queue.set(self._sched.n_waiting)
            try:
                self._iteration()
            except BaseException as e:  # noqa: BLE001 — generations must
                # never hang on an engine bug: fail everything this loop
                # owns (releasing its KV pages) and keep the thread
                # alive. An abort poisons the iteration on purpose —
                # those count as failed, not rejected
                self._fail_all(e)

    def _iteration(self) -> None:
        """One continuous-batching iteration: admit, prefill admitted
        prompts, run the single decode step, harvest tokens. Exactly
        ONE host drain point (the np.asarray on the next-token vector)
        — tools/check_hot_loop.py HOT004 walks this function."""
        import jax.numpy as jnp

        err = self._abort_error
        if err is not None:  # the replica died under this iteration
            raise err
        now = time.monotonic()
        served = self._served  # ONE read: the swap point for hot reload
        admitted, expired = self._sched.admit(now)
        for seq in expired:
            seq.future._reject(DeadlineExceeded(
                "deadline passed before a decode slot opened"
            ))
            self._c_requests.inc(status="expired")
        t0 = time.monotonic()
        c = self._cache
        for seq in admitted:
            pf = self._sched.prefill_args(seq)
            if pf is None:
                continue  # 1-token prompt: the decode step handles it
            bucket, toks, pages = pf
            c.k_pool, c.v_pool = self._prefill(
                served.params, jnp.asarray(toks), jnp.asarray(pages),
                c.k_pool, c.v_pool,
            )
            self._c_prefills.inc(bucket=bucket)
        if not self._sched.running:
            return
        tables, seq_lens, last, active, temp = self._sched.step_arrays()
        nxt, _logits, c.k_pool, c.v_pool = self._decode(
            served.params, c.k_pool, c.v_pool,
            jnp.asarray(tables), jnp.asarray(seq_lens), jnp.asarray(last),
            jnp.asarray(active), jnp.asarray(temp),
            np.int32(self._iterations),
        )
        next_np = np.asarray(nxt)  # the ONE host drain per iteration
        t_done = time.monotonic()
        err = self._abort_error
        if err is not None:  # abort landed mid-step: nothing resolves
            raise err        # after a death
        self._harvest(next_np, served.step, t_done, t0)

    def _harvest(self, next_np: np.ndarray, step: int, t_done: float,
                 t0: float) -> None:
        """Post-step bookkeeping: append tokens, resolve finished
        generations, evict deadline-passed ones (typed — never a
        silent drop), update telemetry."""
        n_live = 0
        for slot, seq in list(self._sched.running.items()):
            tok = int(next_np[slot])
            seq.generated.append(tok)
            n_live += 1
            if seq.t_first_token is None:
                seq.t_first_token = t_done
                if seq.t_submit is not None:
                    self._h_ttft.observe(t_done - seq.t_submit)
            if seq.done:
                self._sched.remove(slot, "finished")
                n = len(seq.generated)
                if n > 1 and seq.t_first_token is not None:
                    self._h_tpot.observe(
                        (t_done - seq.t_first_token) / (n - 1)
                    )
                seq.future._resolve(DecodeResult(
                    np.asarray(seq.generated, np.int32), step
                ))
                self._c_requests.inc(status="served")
        self._tokens_total += n_live
        self._c_tokens.inc(n_live)
        for slot in self._sched.running_deadline_victims(t_done):
            seq = self._sched.remove(slot, "evicted")
            seq.future._reject(DeadlineExceeded(
                f"deadline passed after {len(seq.generated)} of "
                f"{seq.max_new_tokens} tokens — evicted"
            ))
            self._c_evicted.inc()
            self._c_requests.inc(status="evicted")
        self._g_occupancy.set(self._sched.occupancy)
        self._g_pages_used.set(self._cache.pages_used)
        self._g_pages_free.set(self._cache.pages_free)
        batch_s = t_done - t0
        self._batch_s_ewma = (
            batch_s if self._batch_s_ewma is None
            else 0.8 * self._batch_s_ewma + 0.2 * batch_s
        )
        self._iterations += 1
        if self._iterations % self.record_every == 0:
            self._write_record(self.decode_record())

    def _fail_all(self, e: BaseException) -> None:
        """Failure path for a poisoned iteration: reject every
        generation the loop owns, RELEASING their KV pages so the
        free-list stays conserved (the chaos oracle checks) and the
        engine can keep serving if the error was input-local."""
        failed = 0
        for slot in list(self._sched.running):
            seq = self._sched.remove(slot, "evicted")
            if not seq.future.done():
                seq.future._reject(e)
                failed += 1
        while self._sched.waiting:
            seq = self._sched.waiting.popleft()
            if not seq.future.done():
                seq.future._reject(e)
                failed += 1
        if failed:
            status = "failed" if e is self._abort_error else "rejected"
            self._c_requests.inc(failed, status=status)

    # -- stats / telemetry --------------------------------------------------
    def tokens_per_sec(self) -> Optional[float]:
        if self._t_started is None or not self._tokens_total:
            return None
        dt = time.monotonic() - self._t_started
        return self._tokens_total / dt if dt > 0 else None

    def ttft_ms(self, q: float) -> Optional[float]:
        s = self._h_ttft.quantile(q)
        return None if s is None else 1000.0 * s

    def stats(self) -> dict:
        """Flat numeric snapshot (the ``decode`` record's metrics map;
        every key ``tmpi_decode_``-prefixed — enforced by the schema
        checker)."""
        fl = self._cache.free_list
        out = {
            "tmpi_decode_queue_depth": float(self.queue_depth),
            "tmpi_decode_running": float(self._sched.n_running),
            "tmpi_decode_batch_occupancy": self._sched.occupancy,
            "tmpi_decode_kv_pages_used": float(self._cache.pages_used),
            "tmpi_decode_kv_pages_free": float(self._cache.pages_free),
            "tmpi_decode_kv_pages_out_total": float(fl.pages_out_total),
            "tmpi_decode_kv_pages_in_total": float(fl.pages_in_total),
            "tmpi_decode_iterations_total": float(self._iterations),
            "tmpi_decode_tokens_total": float(self._tokens_total),
            "tmpi_decode_served_total": self._c_requests.value(status="served"),
            "tmpi_decode_expired_total": self._c_requests.value(status="expired"),
            "tmpi_decode_evicted_total": self._c_evicted.value(),
            "tmpi_decode_preempted_total": self._c_preempted.value(),
            "tmpi_decode_rejected_total":
                self._c_requests.value(status="rejected"),
            "tmpi_decode_failed_total": self._c_requests.value(status="failed"),
            "tmpi_decode_reloads_total": self._c_reloads.value(),
            "tmpi_decode_reload_failures_total":
                self._c_reloads.value(status="failed"),
        }
        tps = self.tokens_per_sec()
        if tps is not None:
            out["tmpi_decode_tokens_per_sec"] = tps
        for name, q in (("p50", 0.5), ("p99", 0.99)):
            ms = self.ttft_ms(q)
            if ms is not None:
                out[f"tmpi_decode_ttft_{name}_ms"] = ms
        tpot = self._h_tpot.quantile(0.5)
        if tpot is not None:
            out["tmpi_decode_tpot_ms"] = 1000.0 * tpot
        return out

    def decode_record(self) -> dict:
        """The one constructor of a ``kind=decode`` record (schema:
        tools/check_obs_schema.py). Replica members stamp
        ``replica_id``."""
        rec = {"kind": "decode", "t": time.time(),
               "params_step": self.params_step, "metrics": self.stats()}
        if self.replica_id is not None:
            rec["replica_id"] = self.replica_id
        return rec

    def _write_record(self, rec: dict) -> None:
        if self.obs_dir is None:
            return
        with self._sink_lock:
            if self._sink_retired:
                return
            if self._sink_f is None:
                os.makedirs(self.obs_dir, exist_ok=True)
                self._sink_f = open(
                    os.path.join(self.obs_dir, self.sink_name), "a"
                )
            self._sink_f.write(json.dumps(rec) + "\n")
            self._sink_f.flush()
