"""Iteration-level scheduler for continuous-batching decode.

Pure host-side logic between decode steps — the policy half of the
subsystem, kept free of jax/threading so it unit-tests in microseconds:

* **Admission** (continuous mode): FIFO from the waiting queue into free
  batch slots, each admit reserving its WORST-CASE pages
  (``prompt + max_new_tokens``) so a running sequence can never die of
  page exhaustion mid-generation; a reservation that doesn't fit stops
  admission (head-of-line FIFO — no starvation of long prompts behind
  short ones). ``mode="static"`` only admits into an EMPTY batch and
  then runs it to completion — the classic static-batching strawman the
  bench's continuous-vs-static ratio measures against.
* **Eviction**: deadline sweeps over both waiting and running
  sequences, finish-on-max-tokens, and drain-time aborts — every exit
  path releases the sequence's pages back to the free-list (the chaos
  oracle asserts conservation after drain).
* **Bucketed prefill**: a prompt of length L caches positions
  ``0..L-2`` padded into the smallest prefill bucket (each bucket is
  one compiled program; buckets must be page-size multiples); the
  prompt's LAST token enters through the regular decode step — so every
  generated token, including the first, exits via the single decode
  program and the engine keeps exactly one host drain per iteration.
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from theanompi_tpu.serve.decode.kvcache import (
    KVExhausted,
    PagedKVCache,
    pages_needed,
)

_seq_ids = itertools.count()


class DecodeSequence:
    """One request's life: waiting -> running(slot) -> finished/evicted."""

    __slots__ = (
        "seq_id", "prompt", "max_new_tokens", "temperature", "deadline",
        "future", "t_submit", "slot", "generated", "t_first_token",
    )

    def __init__(self, prompt, *, max_new_tokens: int,
                 temperature: float = 0.0,
                 deadline: Optional[float] = None, future=None,
                 t_submit: Optional[float] = None):
        self.seq_id = next(_seq_ids)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={max_new_tokens} must be >= 1")
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.deadline = deadline
        self.future = future
        self.t_submit = t_submit
        self.slot: Optional[int] = None
        self.generated: List[int] = []
        self.t_first_token: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.size)

    @property
    def n_cache(self) -> int:
        """Positions the prefill program caches (all but the last prompt
        token, which rides the decode step)."""
        return self.prompt_len - 1

    @property
    def total_len(self) -> int:
        """Worst-case cache positions — the admission reservation."""
        return self.prompt_len + self.max_new_tokens

    @property
    def pos(self) -> int:
        """Position of the token the NEXT decode step processes."""
        return self.prompt_len - 1 + len(self.generated)

    @property
    def last_token(self) -> int:
        return int(self.generated[-1] if self.generated else self.prompt[-1])

    @property
    def done(self) -> bool:
        return len(self.generated) >= self.max_new_tokens


class DecodeScheduler:
    """Admission/eviction policy over one :class:`PagedKVCache`."""

    def __init__(self, cache: PagedKVCache, *,
                 prefill_buckets: Tuple[int, ...],
                 mode: str = "continuous"):
        if mode not in ("continuous", "static"):
            raise ValueError(f"mode={mode!r} (continuous|static)")
        buckets = tuple(sorted(int(b) for b in prefill_buckets))
        if not buckets:
            raise ValueError("need at least one prefill bucket")
        for b in buckets:
            if b <= 0 or b % cache.page_size:
                raise ValueError(
                    f"prefill bucket {b} must be a positive multiple of "
                    f"page_size {cache.page_size}"
                )
        self.cache = cache
        self.buckets = buckets
        self.mode = mode
        self.waiting: Deque[DecodeSequence] = deque()
        self.running: Dict[int, DecodeSequence] = {}
        self._free_slots = list(range(cache.max_seqs - 1, -1, -1))
        self.admitted_total = 0
        self.finished_total = 0
        self.evicted_total = 0
        self.expired_total = 0

    # -- capacity limits the engine validates submissions against -------

    @property
    def max_prompt_len(self) -> int:
        """Longest admissible prompt: its first L-1 tokens must fit the
        largest prefill bucket (+1 for the token the decode step eats)."""
        return self.buckets[-1] + 1

    @property
    def n_waiting(self) -> int:
        return len(self.waiting)

    @property
    def n_running(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    @property
    def occupancy(self) -> float:
        return len(self.running) / max(1, self.cache.max_seqs)

    # -- admission / eviction -------------------------------------------

    def add(self, seq: DecodeSequence) -> None:
        self.waiting.append(seq)

    def admit(self, now: float):
        """Between-steps admission pass. Returns ``(admitted, expired)``
        — ``expired`` are waiting sequences whose deadline passed before
        they ever reached a slot (the caller owns their futures)."""
        admitted: List[DecodeSequence] = []
        expired: List[DecodeSequence] = []
        still: Deque[DecodeSequence] = deque()
        for seq in self.waiting:
            if seq.deadline is not None and now >= seq.deadline:
                expired.append(seq)
                self.expired_total += 1
            else:
                still.append(seq)
        self.waiting = still
        if self.mode == "static" and self.running:
            return admitted, expired
        while self.waiting and self._free_slots:
            seq = self.waiting[0]
            slot = self._free_slots[-1]
            try:
                self.cache.reserve(slot, seq.total_len)
            except KVExhausted:
                break  # FIFO under page pressure: wait, don't starve
            self.waiting.popleft()
            self._free_slots.pop()
            seq.slot = slot
            self.running[slot] = seq
            self.admitted_total += 1
            admitted.append(seq)
        return admitted, expired

    def remove(self, slot: int, reason: str) -> DecodeSequence:
        """Take a running sequence out (``finished`` | ``evicted``),
        returning its pages to the free-list."""
        seq = self.running.pop(slot)
        self.cache.release(slot)
        self._free_slots.append(slot)
        seq.slot = None
        if reason == "finished":
            self.finished_total += 1
        else:
            self.evicted_total += 1
        return seq

    def running_deadline_victims(self, now: float) -> List[int]:
        """Slots whose sequence ran past its deadline (evict these)."""
        return [
            slot for slot, seq in self.running.items()
            if seq.deadline is not None and now >= seq.deadline
        ]

    # -- jitted-program operands ----------------------------------------

    def bucket_for(self, n_cache: int) -> int:
        for b in self.buckets:
            if b >= n_cache:
                return b
        raise ValueError(
            f"prompt caches {n_cache} positions but the largest prefill "
            f"bucket is {self.buckets[-1]}"
        )

    def prefill_args(self, seq: DecodeSequence):
        """``(bucket, tokens[bucket], pages[bucket/page_size])`` for an
        admitted sequence, or None when the prompt is a single token
        (nothing to cache — the decode step handles it)."""
        n_cache = seq.n_cache
        if n_cache == 0:
            return None
        bucket = self.bucket_for(n_cache)
        toks = np.zeros((bucket,), np.int32)
        toks[:n_cache] = seq.prompt[:-1]
        pages = np.full(
            (bucket // self.cache.page_size,), self.cache.scratch, np.int32
        )
        npg = pages_needed(n_cache, self.cache.page_size)
        pages[:npg] = self.cache.page_tables[seq.slot, :npg]
        return bucket, toks, pages

    def step_arrays(self):
        """Fixed-shape operands for the decode program: ``(page_tables,
        seq_lens, last_tokens, active, temperature)``."""
        S = self.cache.max_seqs
        seq_lens = np.zeros((S,), np.int32)
        last = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        temp = np.zeros((S,), np.float32)
        for slot, seq in self.running.items():
            seq_lens[slot] = seq.pos
            last[slot] = seq.last_token
            active[slot] = True
            temp[slot] = seq.temperature
        return self.cache.page_tables, seq_lens, last, active, temp
