"""Continuous-batching LM decode serving (ISSUE 20 tentpole).

Layers, host-side up:

- ``kvcache``   — paged KV pool: one fixed-shape device allocation,
                  host free-list + per-sequence page tables.
- ``scheduler`` — iteration-level admission/eviction over the cache,
                  bucketed prefill planning.
- ``engine``    — :class:`DecodeEngine`: the threaded decode loop with
                  the same submit/drain/set_params surface as the
                  eval-forward ``ServeEngine``, so ``serve/router.py``
                  fronts decode replicas unchanged.
"""

from theanompi_tpu.serve.decode.engine import (  # noqa: F401
    DEFAULT_PREFILL_BUCKETS,
    DecodeEngine,
    DecodeResult,
)
from theanompi_tpu.serve.decode.kvcache import (  # noqa: F401
    FreeList,
    KVExhausted,
    PagedKVCache,
    pages_needed,
)
from theanompi_tpu.serve.decode.scheduler import (  # noqa: F401
    DecodeScheduler,
    DecodeSequence,
)
