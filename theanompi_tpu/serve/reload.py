"""Checkpoint hot-reload: follow a live training run without restarts.

The supervisor (PR 4) keeps a verified checkpoint keep-chain current
while training; this module closes the loop by letting a serving engine
track it. :class:`CheckpointReloader` polls
``utils/checkpoint.newer_verified_checkpoint(dir, than_step)`` — the
factored keep-chain walk that short-circuits AT the served step, so a
steady-state poll (no new saves) costs one ``os.listdir`` and ZERO
verification work: it never re-decompresses the multi-hundred-MB file
it already serves. When a strictly newer VERIFIED checkpoint exists,
the reloader loads it off the hot path (the batcher keeps serving the
old params), then publishes it with ``engine.set_params`` — an atomic
reference swap between micro-batches. A corrupt newest checkpoint (a
training host died mid-write) is walked past without ever touching the
served file, and the engine simply keeps serving the previous verified
step — zero failed requests either way (tests/test_serve_reload.py).

The load template comes from ``jax.eval_shape`` over the model's
``init_train_state`` — structure and dtypes without a single FLOP of
real initialization — which also means the serving model's recipe
(optimizer choice included) must match the training run's, exactly the
resume contract the trainer already enforces.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


def serving_state_template(model):
    """Abstract TrainState (ShapeDtypeStructs) matching what the
    training driver checkpoints — the structure/dtype template
    ``load_checkpoint`` needs, built without materializing anything."""
    import jax

    from theanompi_tpu.train import init_train_state

    return jax.eval_shape(
        lambda k: init_train_state(model, k), jax.random.PRNGKey(0)
    )


def load_for_serving(path: str, model, target_mesh=None):
    """Restore ``(params, model_state, step)`` from a training
    checkpoint — the optimizer state and rng are loaded (the file's
    structure demands it) and dropped (serving needs neither).

    The load goes through :func:`~theanompi_tpu.utils.checkpoint.
    load_resharded` against the SERVING mesh (``ShardingRecipe.serve()``
    by default; ``tmpi serve --shard tensor`` passes its tensor-serve
    mesh), which is the real train->serve handoff: a checkpoint stamped
    on a pod's training mesh loads onto a 1-chip serving mesh (or a
    tensor-sharded serving mesh) by reading each leaf's GLOBAL bounds
    under the stamped ``__topology__`` manifest. A pre-manifest
    checkpoint whose leaves match falls back to the plain structural
    load — same-mesh serving stays bit-identical
    (tests/test_serve_reload.py::test_load_for_serving_cross_topology).
    """
    from theanompi_tpu.utils.checkpoint import checkpoint_step, load_resharded

    if target_mesh is None:
        from theanompi_tpu.parallel.recipe import ShardingRecipe

        target_mesh = ShardingRecipe.serve().mesh
    state, _rng, info = load_resharded(
        path, serving_state_template(model), target_mesh
    )
    if info.get("resharded"):
        print(
            f"[serve.reload] resharded {path!r} from a "
            f"{info.get('from_world')}-device training mesh onto the "
            f"{info.get('to_world')}-device serving mesh", flush=True,
        )
    return state.params, state.model_state, checkpoint_step(path)


def serving_leaf_specs(model) -> list:
    """The DECLARED per-leaf serving specs for the leaves the engine
    actually serves (params + model_state), resolved by the serving
    ShardingRecipe over the same template ``load_for_serving`` loads
    with. This is the serve half of the train->serve handoff check
    (tools/analyze/sharding.py SHARD004): the training engine's recipe
    stamps its per-leaf specs into every checkpoint's ``__topology__``
    manifest, and the analyzer verifies this table agrees with it."""
    from theanompi_tpu.parallel.recipe import ShardingRecipe

    recipe = ShardingRecipe.serve()
    return [(p, s) for p, s in
            recipe.leaf_specs(serving_state_template(model))
            if p.startswith(".params") or p.startswith(".model_state")]


class CheckpointReloader:
    """Poll a training run's keep-chain; swap the engine's params.

    ``poll_once()`` is the unit of work (tests drive it directly for
    determinism); ``start()`` runs it on a background thread every
    ``interval`` seconds until ``stop()``. Failures to LOAD a
    checkpoint that verified a moment earlier — the discovery/load
    TOCTOU: the training run's keep-chain pruned the file between
    ``newer_verified_checkpoint()`` and the open, or the dir points at
    a structurally different run — are absorbed, not surfaced as a
    reload failure of the SERVING side: the engine keeps serving its
    current params, a failed ``reload`` record (``ok: false``) lands
    in serve.jsonl (``tmpi_serve_reload_failures_total`` counts it),
    and the next poll simply retries against whatever the keep-chain
    holds then. A reloader crash must never take serving down.
    """

    def __init__(self, engine, ckpt_dir: str, *, interval: float = 2.0):
        self.engine = engine
        self.ckpt_dir = ckpt_dir
        self.interval = float(interval)
        self._stop = threading.Event()
        # stop() is reachable from the SIGTERM drain thread and the
        # CLI's finally concurrently — the handoff must be atomic
        self._stop_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> Optional[int]:
        """One poll: swap to the newest verified step newer than what
        is served; returns the new step, or None when nothing newer
        (or the newer files are all corrupt)."""
        from theanompi_tpu.utils.checkpoint import newer_verified_checkpoint

        current = self.engine.params_step
        path = newer_verified_checkpoint(self.ckpt_dir, than_step=current)
        if path is None:
            return None
        t0 = time.monotonic()
        try:
            params, model_state, step = load_for_serving(path, self.engine.model)
        except Exception as e:  # noqa: BLE001 — keep serving on any load
            # failure (the keep-chain pruned the file mid-load, etc.);
            # the failed-reload record makes the TOCTOU race observable
            # without ever surfacing it to a request
            print(f"[serve.reload] load of {path!r} failed ({e!r}); "
                  "keeping current params, retrying next poll", flush=True)
            note = getattr(self.engine, "note_reload_failed", None)
            if note is not None:
                note(current, repr(e))
            return None
        if not self.engine.set_params(params, model_state, step):
            return None  # raced a newer swap; served step never regresses
        ms = 1000.0 * (time.monotonic() - t0)
        self.engine.note_reload(current, step, ms)
        print(f"[serve.reload] now serving step {step} "
              f"(was {current}; load+swap {ms:.0f} ms)", flush=True)
        return step

    # -- background polling -------------------------------------------------
    def start(self) -> None:
        with self._stop_lock:
            if self._thread is not None:
                raise RuntimeError("reloader already started")
            self._thread = threading.Thread(
                target=self._loop, name="tmpi-serve-reload", daemon=True
            )
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001
                print(f"[serve.reload] poll failed ({e!r}); retrying",
                      flush=True)

    def stop(self) -> None:
        self._stop.set()
        with self._stop_lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)
