"""bench.py — headline benchmark, run on real TPU hardware by the driver.

Metric (BASELINE.json): AlexNet ImageNet images/sec. The authoritative
reference target is "match 8xP100 BSP wall-clock on ImageNet AlexNet";
8xP100 AlexNet BSP throughput is ~8000 img/s (fp32 cuDNN era, near-linear
scaling per the paper), so vs_baseline = img/s / 8000 with the
chips we have (one v5e here; the 8-chip pod target divides per-chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np


BASELINE_IMG_S = 8000.0  # 8xP100 AlexNet BSP (BASELINE.md authoritative target)


def main() -> int:
    import jax
    import jax.numpy as jnp

    from theanompi_tpu.models.alex_net import AlexNet
    from theanompi_tpu.parallel import make_mesh
    from theanompi_tpu.parallel.mesh import put_global_batch

    from theanompi_tpu.train import make_multi_step, make_train_step, init_train_state
    from theanompi_tpu.parallel.strategies import get_strategy

    n_dev = len(jax.devices())
    # reference recipe: batch 128/worker (SURVEY.md §2.1 AlexNet)
    batch = 128 * n_dev
    model = AlexNet(AlexNet.default_recipe().replace(batch_size=batch))
    mesh = make_mesh(n_dev)
    steps = 20

    # the full BSP train step (fwd+bwd+sync+update), k steps fused into
    # one program so host dispatch latency doesn't pollute the measurement
    if n_dev == 1:
        runner = jax.jit(make_multi_step(make_train_step(model), steps))
    else:
        from jax.sharding import PartitionSpec as P

        base = make_train_step(model, grad_sync=get_strategy("psum", "data", n_dev))
        runner = jax.jit(
            jax.shard_map(
                make_multi_step(base, steps),
                mesh=mesh,
                in_specs=(P(), P("data"), P("data"), P()),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )

    state = init_train_state(model, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    x = put_global_batch(
        mesh, jnp.asarray(rng.randn(batch, 227, 227, 3), jnp.float32)
    )
    y = put_global_batch(mesh, jnp.asarray(rng.randint(0, 1000, batch), jnp.int32))

    # warmup / compile
    state, metrics = runner(state, x, y, jax.random.PRNGKey(1))
    jax.block_until_ready(metrics["loss"])

    best = None
    for trial in range(3):
        t0 = time.perf_counter()
        state, metrics = runner(state, x, y, jax.random.PRNGKey(2 + trial))
        jax.block_until_ready(metrics["loss"])
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)

    img_s = steps * batch / best
    print(
        json.dumps(
            {
                "metric": f"alexnet_imagenet_bsp_images_per_sec_{n_dev}chip",
                "value": round(img_s, 1),
                "unit": "images/sec",
                "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
