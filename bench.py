"""bench.py — benchmark harness; run on real TPU hardware by the driver.

Headline metric (BASELINE.json): AlexNet ImageNet images/sec, BSP. The
authoritative target is "match 8xP100 BSP wall-clock on ImageNet
AlexNet"; 8xP100 AlexNet BSP throughput is ESTIMATED at ~8000 img/s
(fp32 cuDNN era, near-linear scaling per arXiv:1605.08325 — no published
number survives, see BASELINE.md). vs_baseline = img/s / 8000 against
the FULL 8-GPU cluster number, deliberately NOT normalized per chip
(same semantics as BENCH_r01/r02): a single v5e already exceeding the
8xP100 cluster is the headline claim, and vs_baseline > 1 states it.

Modes (default ``compute`` keeps the driver contract: the LAST stdout
line is ONE JSON object {"metric", "value", "unit", "vs_baseline", ...}):

  python bench.py                  # compute: fused train steps, synthetic batch
  python bench.py --model resnet50 # compute mode for any zoo model
                                   #   (alexnet/googlenet/resnet50/vgg16/wrn;
                                   #   snapshot in ZOO_BENCH.json)
  python bench.py --mode e2e       # full run_training over disk shards +
                                   #   PrefetchLoader; reports wait fraction
  python bench.py --mode scaling   # 1..8-device weak-scaling table on the
                                   #   virtual CPU mesh (comm-overhead audit);
                                   #   writes SCALING.json
  python bench.py --serve-bench    # serving: closed-loop load over the
                                   #   dynamic micro-batching inference
                                   #   engine (serve/) — sustained req/s,
                                   #   p50/p99 latency, batch-fill
  python bench.py --decode-bench   # LM token serving: open-loop Poisson
                                   #   prompts over the continuous-
                                   #   batching decode engine
                                   #   (serve/decode/) — tokens/sec,
                                   #   p50/p99 TTFT, TPOT, and the
                                   #   continuous-vs-static ratio
  python bench.py --bucket-sweep   # bucketed-allreduce sweep (bucket
                                   #   size x engine variant); compute
                                   #   mode also takes --fused-update /
                                   #   --allreduce-buckets directly

Beyond img/s, compute mode reports achieved TFLOP/s and MFU from XLA's
cost analysis of the compiled program (utils/flops.py) — the reference
never measured utilization (SURVEY.md §5.1); the BASELINE scaling-
efficiency metric needs it.
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_IMG_S = 8000.0  # ESTIMATED 8xP100 AlexNet BSP (BASELINE.md)


def _measure(runner, args, sync_leaf, trials=5):
    """Wall-clock of ``trials`` fresh invocations (post-warmup). Returns
    ``(times, last_out)`` so callers can take the median (round-4
    verdict item 7: the tunneled chip shows ±4% run-to-run variance, so
    single-sample best-of readings cannot distinguish round deltas from
    noise) and verify executed work."""
    out = runner(*args)
    jax_block(sync_leaf(out))
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        out = runner(*args)
        jax_block(sync_leaf(out))
        times.append(time.perf_counter() - t0)
    return times, out


def _timing_stats(times) -> dict:
    """{median, spread, k}: spread = (max-min)/median, the honest
    run-to-run noise band around the quoted median."""
    med = float(np.median(times))
    return {
        "k": len(times),
        "median_s": round(med, 6),
        "spread_frac": round((max(times) - min(times)) / med, 4) if med else None,
    }


def _assert_executed(out_state, expected_steps: int, where: str):
    """Hard executed-work check (round-3 verdict item 5): the train state
    carries a step counter incremented INSIDE the compiled program, so a
    backend that returns without executing (the tunneled silent-scan
    fault, tools/repro_tunnel_fault.py) cannot fake it. Fetched from the
    host AFTER the timed runs — not a sync artifact."""
    got = int(np.asarray(_first_shard(out_state.step)))
    if got != expected_steps:
        raise RuntimeError(
            f"{where}: step counter advanced {got} != expected "
            f"{expected_steps} — the backend did not execute the measured "
            "program (silent-scan fault; see tools/repro_tunnel_fault.py)"
        )


def _first_shard(x):
    """Host value of a (possibly sharded) array's first shard — the
    shared mesh helper (single implementation; see parallel/mesh.py)."""
    from theanompi_tpu.parallel.mesh import first_local_value

    return first_local_value(x)


def jax_block(x):
    import jax

    jax.block_until_ready(x)


def _roundtrip_latency() -> float:
    """Median host<->device round-trip of a trivial varied op — the
    tunnel's fetch latency (~115 ms on the axon dev chip), measured so
    the round-trip-synced fallback below can subtract it."""
    import jax.numpy as jnp

    lats = []
    for i in range(5):
        t0 = time.perf_counter()
        float(jnp.sum(jnp.ones(()) * i))
        lats.append(time.perf_counter() - t0)
    return sorted(lats)[len(lats) // 2]


def _measure_roundtrip(runner, state, x, y, trials=3):
    """Fallback timing when block_until_ready stops blocking (a tunneled-
    backend fault observed after cost-analysis AOT calls: dispatch
    returns in ~2 ms, results are correct, the sync is a no-op). Each
    trial varies the rng key (defeats any result caching) and syncs with
    an actual host fetch of the losses; the separately measured fetch
    latency is subtracted."""
    import jax

    lat = _roundtrip_latency()
    times = []
    out = None
    for t in range(trials):
        t0 = time.perf_counter()
        out = runner(state, x, y, jax.random.PRNGKey(100 + t))
        np.asarray(out[1]["loss"])
        times.append(time.perf_counter() - t0 - lat)
    # median, matching the primary path's quoted statistic (a min here
    # would systematically bias the fallback fast vs the median rows)
    best = float(np.median(times))
    if hasattr(out[0], "step"):
        got = int(np.asarray(_first_shard(out[0].step)))
        start = int(np.asarray(_first_shard(state.step)))
        if got <= start:
            raise RuntimeError(
                f"_measure_roundtrip: step counter did not advance "
                f"({start} -> {got}) — backend not executing"
            )
    if best <= lat * 0.25:
        # the work window is in the latency noise — a clamped value
        # would feed the physics guard a bogus astronomic rate with a
        # misleading diagnosis
        raise RuntimeError(
            f"unmeasurable on this backend: step window {best*1000:.1f} ms "
            f"is below the tunnel round-trip latency {lat*1000:.1f} ms — "
            "raise --steps so the fused window dominates the fetch"
        )
    return best


def _zoo_entry(name: str):
    """(model_cls, single_chip_global_batch) — the registry (and the
    batch policy notes) live in theanompi_tpu.models.zoo, shared with
    tools/op_profile.py."""
    from theanompi_tpu.models.zoo import zoo_entry

    return zoo_entry(name)


def bench_compute(steps: int = 20, trials: int = 5, model_name: str = "alexnet",
                  fused_update: bool = False,
                  allreduce_buckets: float = 0.0) -> dict:
    """Fused-step device throughput: fwd+bwd+sync+update, input pipeline
    excluded (see e2e mode for the honest framework number).

    ``fused_update`` / ``allreduce_buckets``: the MFU-push knobs
    (ROADMAP item 2a/2b) — the one-pass optimizer epilogue
    (ops/pallas_update.py) and the bucketed overlap-with-backward
    allreduce (parallel/strategies.py; a no-op on one chip)."""
    import jax
    import jax.numpy as jnp

    from theanompi_tpu.parallel import make_mesh
    from theanompi_tpu.parallel.mesh import put_global_batch
    from theanompi_tpu.parallel.strategies import bucketed, get_strategy
    from theanompi_tpu.train import init_train_state, make_multi_step, make_train_step
    from theanompi_tpu.utils.flops import compiled_cost, peak_flops

    n_dev = len(jax.devices())
    model_cls, base_batch = _zoo_entry(model_name)
    # single-chip global batch, scaled per-chip past 8 devices for the
    # weak-scaling shape; rounded up to shard evenly on any device count
    batch = base_batch * n_dev // 8 if n_dev > 8 else base_batch
    batch = -(-batch // n_dev) * n_dev
    model = model_cls(model_cls.default_recipe().replace(batch_size=batch))
    mesh = make_mesh(n_dev)
    # Models that only fit when the runner DONATES its state (the 350M
    # LM: two f32 params+adam states ~ 8.6 GB would OOM one v5e) use the
    # thread-state timing path below — state flows through the trials
    # instead of re-timing from one immortal input.
    thread_state = model_name.endswith("_350m") and n_dev == 1

    if n_dev == 1:
        step1 = make_train_step(model, fused_update=fused_update)
        single = jax.jit(step1)
        runner = jax.jit(
            make_multi_step(step1, steps),
            donate_argnums=(0,) if thread_state else (),
        )
    else:
        from jax.sharding import PartitionSpec as P

        sync = (
            bucketed("psum", "data", n_dev, allreduce_buckets)
            if allreduce_buckets
            else get_strategy("psum", "data", n_dev)
        )
        base = make_train_step(model, grad_sync=sync,
                               fused_update=fused_update)
        specs = dict(
            mesh=mesh,
            in_specs=(P(), P("data"), P("data"), P()),
            out_specs=(P(), P()),
            check_vma=False,
        )
        single = jax.jit(jax.shard_map(base, **specs))
        runner = jax.jit(jax.shard_map(make_multi_step(base, steps), **specs))

    state = init_train_state(model, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ishape = tuple(model.recipe.input_shape)
    ncls = model.recipe.num_classes
    is_lm = bool(getattr(model, "is_lm", False))
    if is_lm:
        # token batches: x IS the label stream (next-token objective)
        toks = rng.randint(0, ncls, (batch, *ishape)).astype(np.int32)
        x = put_global_batch(mesh, jnp.asarray(toks))
        y = x
    else:
        x = put_global_batch(mesh, jnp.asarray(rng.randn(batch, *ishape), jnp.float32))
        y = put_global_batch(mesh, jnp.asarray(rng.randint(0, ncls, batch), jnp.int32))
    args = (state, x, y, jax.random.PRNGKey(1))

    # XLA's cost analysis counts a scan body ONCE regardless of trip
    # count (measured), so take one step's cost and multiply — via the
    # SHARED CostModel (utils/flops.py), the same object the live
    # attribution gauges and `tmpi profile` consume
    cost = compiled_cost(single, *args)
    flops_step = cost.flops if cost is not None else None
    flops_total = flops_step * steps if flops_step else None
    peak_bound = peak_flops()
    if thread_state:
        # donate-and-thread: the state argument is consumed each call,
        # so trials chain (state_t -> state_{t+1}); sync is a host fetch
        # of the stacked losses (block_until_ready can no-op through the
        # tunnel) with the round trip subtracted, and the executed-work
        # counter must advance steps x (warmup + trials)
        lat = _roundtrip_latency()
        start = int(np.asarray(_first_shard(state.step)))
        state, m = runner(state, x, y, jax.random.PRNGKey(1))
        np.asarray(m["loss"])
        times = []
        for t in range(trials):
            t0 = time.perf_counter()
            state, m = runner(state, x, y, jax.random.PRNGKey(100 + t))
            np.asarray(m["loss"])
            times.append(time.perf_counter() - t0 - lat)
        got = int(np.asarray(_first_shard(state.step)))
        want = start + steps * (trials + 1)
        if got != want:
            raise RuntimeError(
                f"bench_compute(thread_state): step counter {got} != "
                f"{want} — backend did not execute the measured program"
            )
        timing = {**_timing_stats(times), "sync": "roundtrip",
                  "donated": True}
        med = timing["median_s"]
        if med <= lat * 0.25:
            # same guard as _measure_roundtrip: a window inside the
            # latency noise would publish an absurd (possibly negative)
            # rate that also slips past the physics check
            raise RuntimeError(
                f"unmeasurable on this backend: step window "
                f"{med*1000:.1f} ms is within the tunnel round-trip "
                f"latency {lat*1000:.1f} ms — raise --steps so the "
                "donated window dominates the fetch"
            )
        img_s = steps * batch / med
    else:
        times, out = _measure(runner, args, lambda out: out[1]["loss"], trials)
        # every invocation starts from the same input state, so the final
        # counter must be exactly `steps` regardless of trial count
        _assert_executed(out[0], steps, "bench_compute")
        timing = _timing_stats(times)
        med = timing["median_s"]
        img_s = steps * batch / med

    # Physics guard: a backend fault can make block_until_ready return
    # without blocking (observed on the tunneled chip; results are
    # correct, only the sync breaks). Anything beyond the 100%-MFU bound
    # is impossible — fall back to round-trip-synced measurement.
    if flops_step and peak_bound:
        max_img_s = peak_bound * batch / flops_step
        if img_s > max_img_s and not thread_state:
            # (thread_state already times via round-trip fetches; if ITS
            # reading breaks physics the raise below fires directly)
            med = _measure_roundtrip(runner, state, x, y, trials)
            timing = {"k": trials, "median_s": round(med, 6),
                      "spread_frac": None, "fallback": "roundtrip_sync"}
            img_s = steps * batch / med
        if img_s > max_img_s:
            raise RuntimeError(
                f"measured {img_s:.0f} img/s exceeds the 100%-MFU bound "
                f"{max_img_s:.0f} — backend not actually executing"
            )
    flops_s = flops_total / med if flops_total else None
    # per-step seconds for the utilization views (the k-step window
    # divided by its trip count)
    sps = med / steps if med else None
    mfu_val = cost.mfu(sps) if cost is not None else None
    hbm_gbps = cost.hbm_gbps(sps) if cost is not None else None
    result = {
        "metric": f"{model_name}_{model.recipe.dataset}_bsp_images_per_sec_{n_dev}chip",
        "value": round(img_s, 1),
        "unit": "images/sec",
        # the 8xP100 estimate is an ALEXNET number (BASELINE config #2);
        # other zoo models report throughput without a baseline ratio
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4) if model_name == "alexnet" else None,
        "baseline_estimated": model_name == "alexnet",
        "n_devices": n_dev,
        "device_kind": jax.devices()[0].device_kind,
        "tflops_per_sec": round(flops_s / 1e12, 2) if flops_s else None,
        "mfu": round(mfu_val, 4) if mfu_val is not None else None,
        "hbm_gbps": round(hbm_gbps, 2) if hbm_gbps is not None else None,
        "batch": batch,
        "timing": timing,  # {k, median_s, spread_frac}: value quotes the median
        # MFU-push knobs this reading was taken under (perf_gate pairs
        # compare like with like)
        "fused_update": bool(fused_update),
        "allreduce_buckets": float(allreduce_buckets or 0.0),
    }
    if is_lm:
        import jax.numpy as jnp

        seq_len = ishape[0]
        result["unit"] = "sequences/sec"
        result["seq_len"] = seq_len
        result["tokens_per_sec"] = round(img_s * seq_len, 1)
        if model.recipe.compute_dtype == jnp.bfloat16:
            result["mfu_note"] = "bf16 compute vs bf16 peak"
        else:
            result["mfu_note"] = "f32 compute vs bf16 peak (conservative)"
    return result


def bench_e2e(max_steps: int = 48, batch: int = 0,
              dispatch_depths=(1,), numerics: bool = False,
              recovery: bool = False) -> dict:
    """The honest framework benchmark: run_training end-to-end — disk
    shards -> mmap gather -> crop/mirror/normalize -> PrefetchLoader ->
    H2D -> fused step. The reference's headline claim was "I/O fully
    hidden behind compute" (SURVEY.md §6); wait_frac measures it, and
    host_blocked_frac measures the OUTPUT-side tax: the fraction of the
    train loop the host spent blocked on device syncs (the per-step
    round trip the async dispatch pipeline removes — utils/dispatch.py).
    ``batch=0``: recipe batch (128) per visible device.

    ``dispatch_depths``: one run per depth over the SAME shard files;
    the deepest run is the headline and, when more than one depth was
    swept, the per-depth readings land in ``dispatch_sweep`` so the
    dispatch win is visible directly in the bench JSON.

    ``numerics``: also run the headline depth with ``--numerics-freq 1``
    (in-graph sentinels on EVERY step — the worst case) and report
    ``numerics_overhead_frac``: the step-time fraction the flight
    recorder's sentinels cost, measured, not guessed.

    ``recovery``: also time one clean checkpointed run against one run
    with an injected crash mid-way, auto-resumed by the supervisor
    (launch/supervisor.py, zero backoff), and report
    ``recovery_overhead_frac``: the wall-time fraction one
    crash+verified-resume costs — the recovery path's tracked perf
    number (replay from the last epoch boundary dominates it)."""
    import tempfile

    import jax

    from theanompi_tpu.data.imagenet import write_shards
    from theanompi_tpu.launch.worker import run_training
    from theanompi_tpu.models.alex_net import AlexNet

    n_dev = len(jax.devices())
    batch = batch or 128 * n_dev
    rng = np.random.RandomState(0)
    n_train = max(2048, 8 * batch)
    rows = []
    with tempfile.TemporaryDirectory(prefix="tmpi_bench_") as d:
        write_shards(
            d, "train",
            rng.randint(0, 256, size=(n_train, 256, 256, 3)).astype(np.uint8),
            rng.randint(0, 1000, size=n_train).astype(np.int64),
            shard_size=1024,
        )
        write_shards(
            d, "val",
            rng.randint(0, 256, size=(256, 256, 256, 3)).astype(np.uint8),
            rng.randint(0, 1000, size=256).astype(np.int64),
            shard_size=256,
        )
        def run_kwargs(depth, numerics_freq=0):
            return dict(
                rule="bsp",
                model_cls=AlexNet,
                dataset="imagenet",
                dataset_kwargs={"root": d},
                recipe_overrides={"batch_size": batch},
                n_epochs=max(1, max_steps // (n_train // batch)),
                max_steps=max_steps,
                dispatch_depth=depth,
                numerics_freq=numerics_freq,
                print_freq=0,
                return_recorder=True,
                # obs on: the engine's cost model then rides the run,
                # so every e2e row reports mfu from the SHARED
                # attribution module (None on spec-less devices)
                obs_dir=os.path.join(d, f"obs_d{depth}_n{numerics_freq}"),
            )

        def one_run(depth, numerics_freq=0):
            return run_training(**run_kwargs(depth, numerics_freq))

        raw_step_s: dict = {}  # unrounded per-depth step time (the
        # numerics-overhead baseline must not absorb row rounding)
        for depth in dispatch_depths:
            summary = one_run(depth)
            rec = summary["recorder"]
            # executed-work check: device-side counter vs host dispatches
            if summary.get("device_steps") != summary["steps"]:
                raise RuntimeError(
                    f"bench_e2e: device executed {summary.get('device_steps')} "
                    f"steps but the host dispatched {summary['steps']} — "
                    "backend dropped work (see tools/repro_tunnel_fault.py)"
                )
            # drop the first epoch's first steps (compile) via last-n means
            n = max(4, max_steps // 2)
            step_t = rec.mean_time("step", n)
            raw_step_s[depth] = step_t
            wait_t = rec.mean_time("wait", n)
            img_s = batch / (step_t + wait_t) if (step_t + wait_t) else 0.0
            rows.append({
                "dispatch_depth": depth,
                "images_per_sec": round(img_s, 1),
                "wait_ms": round(1000 * wait_t, 2),
                "step_ms": round(1000 * step_t, 2),
                "wait_frac": round(wait_t / (step_t + wait_t), 4) if step_t else None,
                "host_blocked_frac": summary.get("host_blocked_frac"),
                "mfu": summary.get("mfu"),
            })
        nm_overhead = None
        if numerics:
            # same shards, headline depth, sentinels on EVERY step: the
            # measured per-step tax of the numerics flight recorder
            # (noise floor applies — on small CPU runs a slightly
            # negative reading means "within noise, effectively free")
            head_depth = max(dispatch_depths)
            rec_nm = one_run(head_depth, numerics_freq=1)["recorder"]
            n = max(4, max_steps // 2)
            step_nm = rec_nm.mean_time("step", n)
            base_s = raw_step_s[head_depth]
            if base_s:
                nm_overhead = (step_nm - base_s) / base_s
        recovery_overhead = None
        if recovery:
            # same shards, headline depth, epoch checkpoints on: one
            # clean wall-clock vs one with a crash injected mid-run and
            # auto-resumed by the supervisor (verified checkpoint +
            # mid-epoch replay) — the measured cost of surviving one
            # host death
            from theanompi_tpu.launch.supervisor import supervise_training

            head_depth = max(dispatch_depths)
            kw = run_kwargs(head_depth)
            kw["return_recorder"] = False
            t0 = time.perf_counter()
            run_training(ckpt_dir=os.path.join(d, "ck_clean"), **kw)
            t_clean = time.perf_counter() - t0
            crash_at = max(2, max_steps // 2)
            t0 = time.perf_counter()
            crashed = supervise_training(
                ckpt_dir=os.path.join(d, "ck_crash"),
                max_retries=1, backoff_base=0.0,
                inject_faults=[f"crash@{crash_at}"], **kw,
            )
            t_crash = time.perf_counter() - t0
            if crashed["retries"] != 1:
                raise RuntimeError(
                    f"recovery bench: expected exactly 1 retry, got "
                    f"{crashed['retries']}"
                )
            if t_clean > 0:
                recovery_overhead = (t_crash - t_clean) / t_clean
    head = max(rows, key=lambda r: r["dispatch_depth"])  # deepest = headline
    result = {
        "metric": f"alexnet_e2e_images_per_sec_{n_dev}chip",
        "value": head["images_per_sec"],
        "unit": "images/sec",
        "vs_baseline": round(head["images_per_sec"] / BASELINE_IMG_S, 4),
        "baseline_estimated": True,
        "wait_ms": head["wait_ms"],
        "step_ms": head["step_ms"],
        "wait_frac": head["wait_frac"],
        "host_blocked_frac": head["host_blocked_frac"],
        "mfu": head["mfu"],  # shared cost model (launch/worker.py
        # summary; None where the device has no spec peak)
        "dispatch_depth": head["dispatch_depth"],
        "batch": batch,
        "max_steps": max_steps,
    }
    if nm_overhead is not None:
        result["numerics_overhead_frac"] = round(nm_overhead, 4)
    if recovery_overhead is not None:
        result["recovery_overhead_frac"] = round(recovery_overhead, 4)
    if len(rows) > 1:
        result["dispatch_sweep"] = rows
    return result


def bench_serve(duration_s: float = 2.0, clients: int = 8,
                buckets=(1, 8, 32)) -> dict:
    """Closed-loop serving benchmark (ISSUE 5): ``clients`` threads
    hammer an in-process :class:`~theanompi_tpu.serve.engine.
    ServeEngine` back-to-back for ``duration_s`` over a real saved
    checkpoint (save -> verified load -> AOT warmup -> serve — the full
    train→serve path), reporting sustained throughput, client-observed
    p50/p99 latency, and the mean batch-fill fraction (how well the
    dynamic micro-batcher coalesces a concurrent closed loop into the
    bucketed shapes). Runs on JAX_PLATFORMS=cpu; like every bench mode
    the result also rides the metrics-snapshot schema via
    ``obs/metrics.result_to_snapshot``."""
    import tempfile
    import threading

    import jax

    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.serve.engine import ServeEngine
    from theanompi_tpu.train import init_train_state
    from theanompi_tpu.utils.checkpoint import save_checkpoint

    model = Cifar10_model()
    buckets = tuple(buckets)
    with tempfile.TemporaryDirectory(prefix="tmpi_serve_bench_") as d:
        state = init_train_state(model, jax.random.PRNGKey(0))
        save_checkpoint(d, state, 1, rng=jax.random.PRNGKey(1))
        engine = ServeEngine(
            model, buckets=buckets,
            max_queue=max(256, 8 * buckets[-1]),
        )
        engine.load_initial(d)
        compiled = engine.warmup()
        engine.start()
        ishape = tuple(model.recipe.input_shape)
        stop = threading.Event()
        lats: list[list] = [[] for _ in range(clients)]

        def client(i: int) -> None:
            r = np.random.RandomState(i)
            x = r.randn(*ishape).astype(np.float32)
            while not stop.is_set():
                t0 = time.perf_counter()
                engine.infer(x, timeout=60.0)
                lats[i].append(time.perf_counter() - t0)

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=60.0)
        elapsed = time.perf_counter() - t0
        engine.drain(timeout=30.0)
        if not any(lats):
            raise RuntimeError(
                "serve bench completed zero requests — raise --serve-duration"
            )
        all_lat = np.concatenate([np.asarray(l) for l in lats if l])
        return {
            "metric": "serve_cifar10_requests_per_sec",
            "value": round(all_lat.size / elapsed, 1),
            "unit": "requests/sec",
            "vs_baseline": None,  # no serving side existed before ISSUE 5
            "p50_ms": round(1000 * float(np.percentile(all_lat, 50)), 3),
            "p99_ms": round(1000 * float(np.percentile(all_lat, 99)), 3),
            "batch_fill": round(engine.mean_batch_fill or 0.0, 4),
            "served": int(all_lat.size),
            "clients": clients,
            "buckets": ",".join(str(b) for b in buckets),
            "compiled_programs": compiled,
            "duration_s": round(elapsed, 3),
            "device_kind": jax.devices()[0].device_kind,
        }


def bench_serve_fleet(duration_s: float = 4.0, replicas: int = 2,
                      buckets=(1, 8, 32), waiters: int = 16,
                      seed: int = 0) -> dict:
    """Open-loop serving benchmark over an N-replica router (ISSUE 19,
    the ROADMAP's load generator grown from the closed loop above):

    1. **calibrate** — a short closed-loop burst measures the fleet's
       service capacity (requests/s);
    2. **overload probe** — Poisson arrivals at ~2.5x capacity for a
       slice: goodput must saturate near capacity while the admission
       path REJECTS the excess with retry-after (never queues it into
       unbounded latency);
    3. **measured window** — Poisson arrivals at ~0.35x capacity
       (open loop: latency is measured from each request's SCHEDULED
       arrival, so queueing delay counts), with a hard
       ``kill_replica(0)`` at ~45% of the window. The survivors absorb
       the offered load while the supervisor restarts the dead member;
       ``recovery_ratio`` compares the SERVED FRACTION of offered
       arrivals in the tail (last 30% of the window) to the pre-kill
       window — the acceptance bar is >= 0.9. A fraction-of-offered
       ratio (not a rate ratio) is deliberate: at bench-scale arrival
       counts a rate ratio is dominated by Poisson shot noise and by
       uniform box slowdown, neither of which is a recovery failure;
       requests the post-kill fleet rejects, drops, or fails DO score
       against it.

    Reports p50/p99/p999 latency, ``serve_goodput_rps`` and
    ``serve_p99_ms`` (the perf_gate metrics), and the router's own
    failover/restart counters. CPU-friendly like every bench mode."""
    import queue as _queue
    import tempfile
    import threading

    import jax

    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.serve.engine import Rejected, ServeEngine
    from theanompi_tpu.serve.router import RequestDropped, Router
    from theanompi_tpu.train import init_train_state
    from theanompi_tpu.utils.checkpoint import save_checkpoint

    model = Cifar10_model()
    buckets = tuple(buckets)
    with tempfile.TemporaryDirectory(prefix="tmpi_serve_fleet_") as d:
        state = init_train_state(model, jax.random.PRNGKey(0))
        save_checkpoint(d, state, 1, rng=jax.random.PRNGKey(1))
        compiled = []

        def member(rid):
            eng = ServeEngine(
                model, buckets=buckets,
                max_queue=max(256, 8 * buckets[-1]),
                replica_id=rid, sink_name=f"serve_r{rid}.jsonl",
            )
            eng.load_initial(d)
            compiled.append(eng.warmup())
            eng.start()
            return eng

        router = Router(member, replicas, seed=seed,
                        health_interval=0.1, restart_base_s=0.1,
                        restart_cap_s=1.0)
        router.start()
        ishape = tuple(model.recipe.input_shape)
        rng = np.random.RandomState(seed)
        x = rng.randn(*ishape).astype(np.float32)

        # -- phase 1: closed-loop capacity calibration ------------------
        stop = threading.Event()
        cal_counts = [0] * 8

        def cal_client(i: int) -> None:
            while not stop.is_set():
                router.infer(x, timeout=60.0)
                cal_counts[i] += 1

        cal_threads = [threading.Thread(target=cal_client, args=(i,),
                                        daemon=True) for i in range(8)]
        t0 = time.perf_counter()
        for t in cal_threads:
            t.start()
        time.sleep(max(0.5, duration_s / 8))
        stop.set()
        for t in cal_threads:
            t.join(timeout=60.0)
        capacity = sum(cal_counts) / (time.perf_counter() - t0)
        if capacity <= 0:
            raise RuntimeError("serve fleet calibration served nothing")

        def open_loop(lam: float, window: float, on_tick=None):
            """Poisson arrivals at ``lam`` req/s for ``window`` s;
            returns (records, elapsed). Each record: scheduled arrival,
            terminal status, and open-loop latency (completion minus
            SCHEDULED arrival)."""
            arrivals = []
            t = rng.exponential(1.0 / lam)
            while t < window:
                arrivals.append(t)
                t += rng.exponential(1.0 / lam)
            recs = [None] * len(arrivals)
            futq: _queue.Queue = _queue.Queue()

            def waiter() -> None:
                while True:
                    item = futq.get()
                    if item is None:
                        return
                    i, sched, fut = item
                    try:
                        fut.result(timeout=60.0)
                        recs[i] = ("served",
                                   (time.perf_counter() - start) - sched)
                    except RequestDropped:
                        recs[i] = ("dropped", None)
                    except Exception:  # noqa: BLE001 — terminal non-
                        # served outcomes all score against goodput
                        recs[i] = ("failed", None)

            ws = [threading.Thread(target=waiter, daemon=True)
                  for _ in range(waiters)]
            for w in ws:
                w.start()
            start = time.perf_counter()
            i = 0
            while i < len(arrivals):
                now = time.perf_counter() - start
                if on_tick is not None:
                    on_tick(now)
                if arrivals[i] > now:
                    time.sleep(min(0.002, arrivals[i] - now))
                    continue
                while i < len(arrivals) and arrivals[i] <= now:
                    try:
                        fut = router.submit(x)
                        futq.put((i, arrivals[i], fut))
                    except Rejected:
                        recs[i] = ("rejected", None)
                    i += 1
            for _ in ws:
                futq.put(None)
            for w in ws:
                w.join(timeout=120.0)
            elapsed = time.perf_counter() - start
            out = [(arrivals[i], *(recs[i] or ("failed", None)))
                   for i in range(len(arrivals))]
            return out, elapsed

        # -- phase 2: overload probe (admission control, not queues,
        # absorbs the excess) --------------------------------------------
        over_recs, over_elapsed = open_loop(
            2.5 * capacity, max(0.4, duration_s / 10))
        over_served = sum(1 for _, s, _ in over_recs if s == "served")
        over_rejected = sum(1 for _, s, _ in over_recs if s == "rejected")

        # -- phase 3: measured window with a mid-run replica kill -------
        kill_t = 0.45 * duration_s
        killed = threading.Event()

        def on_tick(now: float) -> None:
            if replicas > 1 and now >= kill_t and not killed.is_set():
                killed.set()
                router.kill_replica(0)

        lam = 0.35 * capacity
        recs, elapsed = open_loop(lam, duration_s, on_tick=on_tick)
        router.drain(timeout=30.0)
        rstats = router.stats()

        served = [(sched, lat) for sched, s, lat in recs if s == "served"]
        if not served:
            raise RuntimeError(
                "serve fleet bench served zero requests — raise "
                "--serve-duration")
        lats = np.asarray([lat for _, lat in served])
        n_dropped = sum(1 for _, s, _ in recs if s == "dropped")
        n_failed = sum(1 for _, s, _ in recs if s == "failed")
        n_rejected = sum(1 for _, s, _ in recs if s == "rejected")
        goodput = len(served) / elapsed
        # segment by SCHEDULED arrival; rates are informational, the
        # recovery verdict is served-fraction-of-offered per window
        tail_start = 0.7 * duration_s
        pre_off = [s for sched, s, _ in recs if sched < kill_t]
        tail_off = [s for sched, s, _ in recs if sched >= tail_start]
        pre_rate = sum(1 for s in pre_off if s == "served") / kill_t
        tail_rate = (sum(1 for s in tail_off if s == "served")
                     / (duration_s - tail_start))
        pre_frac = (sum(1 for s in pre_off if s == "served")
                    / max(len(pre_off), 1))
        tail_frac = (sum(1 for s in tail_off if s == "served")
                     / max(len(tail_off), 1))
        return {
            "metric": f"serve_fleet_goodput_rps_{replicas}r",
            "value": round(goodput, 1),
            "unit": "requests/sec",
            "vs_baseline": None,
            "serve_goodput_rps": round(goodput, 1),
            "serve_p50_ms": round(1000 * float(np.percentile(lats, 50)), 3),
            "serve_p99_ms": round(1000 * float(np.percentile(lats, 99)), 3),
            "serve_p999_ms": round(1000 * float(np.percentile(lats, 99.9)), 3),
            "capacity_rps_est": round(capacity, 1),
            "offered_rps": round(lam, 1),
            "goodput_prekill_rps": round(pre_rate, 1),
            "goodput_postkill_rps": round(tail_rate, 1),
            "recovery_ratio": round(tail_frac / max(pre_frac, 1e-9), 4),
            "overload_offered_rps": round(2.5 * capacity, 1),
            "overload_goodput_rps": round(over_served / over_elapsed, 1),
            "overload_rejected": int(over_rejected),
            "served": len(served),
            "rejected": int(n_rejected),
            "dropped": int(n_dropped),
            "failed": int(n_failed),
            "failovers": int(rstats["tmpi_router_failovers_total"]),
            "restarts": int(rstats["tmpi_router_restarts_total"]),
            "replicas": replicas,
            "buckets": ",".join(str(b) for b in buckets),
            "compiled_programs": compiled[0] if compiled else 0,
            "duration_s": round(elapsed, 3),
            "device_kind": jax.devices()[0].device_kind,
        }


def bench_decode(duration_s: float = 3.0, seed: int = 0,
                 prefill_buckets=(4, 8), page_size: int = 4,
                 max_seqs: int = 4, max_new_tokens: int = 12,
                 rate_rps: float = 100.0) -> dict:
    """LM token-serving benchmark over the continuous-batching decode
    engine (serve/decode/, ISSUE 20): one mixed workload — prompt
    lengths uniform over ``1..max(prefill_buckets)+1`` (every prefill
    bucket plus the prefill-free single-token path), output budgets
    uniform over ``1..max_new_tokens`` — measured two ways:

    1. **saturating burst** — all requests offered back-to-back, run
       once through a ``mode="continuous"`` engine and once through a
       ``mode="static"`` engine (admit only into an empty batch, run it
       to completion — the classic static-batching strawman). Sustained
       tokens/sec each; ``continuous_vs_static`` is the ratio the
       acceptance bar wants > 1: with mixed budgets the static batch
       convoys on its longest member while continuous refills freed
       slots every iteration.
    2. **open-loop Poisson window** — arrivals at a FIXED ``rate_rps``
       against a fresh continuous engine; latency is engine-measured
       submit->first-token, so queueing delay counts. Reports
       ``decode_p50_ttft_ms``/``decode_p99_ttft_ms`` (the perf_gate
       invariant) and TPOT. The rate is fixed rather than derived from
       the burst measurement on purpose: a derived rate couples the
       TTFT operating point to burst wall-clock jitter and the p99
       stops being gate-stable (re-baseline with ``--decode-rate``
       when the host class changes, like every experiments/ snapshot).

    Runs on JAX_PLATFORMS=cpu over a real checkpoint round-trip
    (save -> verified load -> AOT warmup -> serve) like every serve
    bench; the tiny-LM geometry keeps the three engines' compile cost
    (len(prefill_buckets)+1 programs each) in CI range."""
    import tempfile

    import jax

    from theanompi_tpu.models.zoo import zoo_entry
    from theanompi_tpu.serve.decode import DecodeEngine
    from theanompi_tpu.train import init_train_state
    from theanompi_tpu.utils.checkpoint import save_checkpoint

    buckets = tuple(prefill_buckets)
    cls, _ = zoo_entry("transformer_lm")
    model = cls(cls.default_recipe().replace(
        input_shape=(64,), num_classes=64, d_model=32, n_heads=2,
        n_layers=2, d_ff=64, attn="ring", batch_size=max_seqs,
    ))
    rng = np.random.RandomState(seed)
    vocab = int(model.recipe.num_classes)
    top = buckets[-1] + 1

    def make_workload(n: int):
        """(prompt, budget) pairs — same RNG stream per phase seed."""
        r = np.random.RandomState(seed + n)
        return [
            (r.randint(0, vocab, size=r.randint(1, top + 1),
                       dtype=np.int32),
             int(r.randint(1, max_new_tokens + 1)))
            for _ in range(n)
        ]

    with tempfile.TemporaryDirectory(prefix="tmpi_decode_bench_") as d:
        state = init_train_state(model, jax.random.PRNGKey(0))
        save_checkpoint(d, state, 1, rng=jax.random.PRNGKey(1))
        compiled = []

        def make_engine(mode: str) -> DecodeEngine:
            eng = DecodeEngine(
                model, prefill_buckets=buckets, page_size=page_size,
                kv_pages=4 * max_seqs * ((top + max_new_tokens)
                                         // page_size + 1),
                max_seqs=max_seqs, max_new_tokens=max_new_tokens,
                max_queue=4096, mode=mode, seed=seed,
            )
            eng.load_initial(d)
            compiled.append(eng.warmup())
            eng.start()
            return eng

        def burst(mode: str, work):
            """Offer the whole workload at once; sustained tokens/s
            plus the engine's iteration count (DETERMINISTIC for a
            fixed workload — the structural continuous-vs-static gap
            survives wall-clock jitter)."""
            eng = make_engine(mode)
            t0 = time.perf_counter()
            futs = [eng.submit(p, max_new_tokens=b) for p, b in work]
            toks = sum(len(f.result(timeout=600.0).tokens) for f in futs)
            tps = toks / (time.perf_counter() - t0)
            iters = eng.stats()["tmpi_decode_iterations_total"]
            eng.drain(timeout=30.0)
            if toks != sum(b for _, b in work):
                raise RuntimeError(
                    f"decode burst ({mode}) lost tokens: got {toks}")
            return tps, int(iters)

        n_burst = 40 * max_seqs
        work = make_workload(n_burst)
        cont_tps, cont_iters = burst("continuous", work)
        static_tps, static_iters = burst("static", work)

        # open-loop TTFT window at the fixed offered rate (~0.25x this
        # host class's continuous capacity at the defaults): loaded
        # enough that batching engages, light enough that p99 measures
        # the engine's iteration time rather than saturation queueing
        lam = max(1.0, float(rate_rps))
        arrivals, t = [], rng.exponential(1.0 / lam)
        while t < duration_s:
            arrivals.append(t)
            t += rng.exponential(1.0 / lam)
        if not arrivals:
            raise RuntimeError(
                "decode bench scheduled zero arrivals — raise "
                "--serve-duration")
        poisson_work = make_workload(len(arrivals))
        eng = make_engine("continuous")
        futs = []
        start = time.perf_counter()
        for sched, (p, b) in zip(arrivals, poisson_work):
            lag = sched - (time.perf_counter() - start)
            if lag > 0:
                time.sleep(lag)
            futs.append(eng.submit(p, max_new_tokens=b))
        for f in futs:
            f.result(timeout=600.0)
        elapsed = time.perf_counter() - start
        eng.drain(timeout=30.0)
        stats = eng.stats()

        return {
            "metric": "decode_tokens_per_sec",
            "value": round(cont_tps, 1),
            "unit": "tokens/sec",
            "vs_baseline": None,  # no token serving existed before
            "decode_tokens_per_sec": round(cont_tps, 1),
            "decode_p50_ttft_ms": stats.get("tmpi_decode_ttft_p50_ms"),
            "decode_p99_ttft_ms": stats.get("tmpi_decode_ttft_p99_ms"),
            "decode_tpot_ms": stats.get("tmpi_decode_tpot_ms"),
            "static_tokens_per_sec": round(static_tps, 1),
            "continuous_vs_static": round(cont_tps / static_tps, 4),
            # deterministic companions to the wall-clock ratio: decode
            # iterations each mode needed for the SAME workload
            "continuous_iterations": cont_iters,
            "static_iterations": static_iters,
            "offered_rps": round(lam, 2),
            "poisson_requests": len(arrivals),
            "burst_requests": n_burst,
            "max_seqs": max_seqs,
            "max_new_tokens": max_new_tokens,
            "prefill_buckets": ",".join(str(b) for b in buckets),
            "compiled_programs": compiled[0] if compiled else 0,
            "duration_s": round(elapsed, 3),
            "device_kind": jax.devices()[0].device_kind,
        }


def bench_codec_sweep(engines=("bsp", "zero1", "easgd", "gosgd", "nd"),
                      codecs=("none", "bf16", "int8", "int8:ef"),
                      max_steps: int = 6) -> dict:
    """Compressed-collectives sweep (codec x engine): run every engine's
    exchange through every wire codec (parallel/codec.py) for a few
    steps on the visible mesh, and read back each run's ``kind=comm``
    wire declaration from its obs metrics.jsonl — so the table's
    raw/wire bytes are the SAME records production telemetry emits, not
    a side computation. Each row: effective vs raw per-step bytes,
    compression ratio, throughput, final val loss (quantization noise
    must not break the mini-run). Headline value: the MINIMUM
    compression ratio across int8 rows — the acceptance floor (>= 3.5x
    incl. scale overhead) every engine must clear."""
    import json as _json
    import tempfile

    import jax

    from theanompi_tpu.launch.worker import run_training
    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.models.lm import TransformerLMModel

    n_dev = len(jax.devices())
    n = min(4, n_dev)
    if n < 2:
        # Single-device runs hit every engine's n==1 codec bypass, so
        # every int8 row would read compression_ratio 1.0 — a spurious
        # "floor failed" table. Refuse instead of reporting garbage.
        raise RuntimeError(
            "--codec-sweep needs >= 2 devices; on CPU set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=4 "
            "(before jax import)")
    if n % 2:
        n -= n % 2  # the nd row runs tp=2
    rows = []
    img_recipe = {"batch_size": 16, "input_shape": (16, 16, 3),
                  "sched_kwargs": {"lr": 0.05, "boundaries": [10 ** 9]}}
    lm_recipe = {"batch_size": 8, "d_model": 32, "n_heads": 4,
                 "n_layers": 2, "d_ff": 64, "input_shape": (16,),
                 "num_classes": 32}
    grid = {
        "bsp": dict(rule="bsp", model_cls=Cifar10_model,
                    recipe_overrides=img_recipe),
        "zero1": dict(rule="bsp", zero=1, model_cls=Cifar10_model,
                      recipe_overrides=img_recipe),
        "easgd": dict(rule="easgd", avg_freq=2, model_cls=Cifar10_model,
                      recipe_overrides=img_recipe),
        "gosgd": dict(rule="gosgd", p_push=0.5, model_cls=Cifar10_model,
                      recipe_overrides=img_recipe),
        "nd": dict(rule="bsp", tp=2, model_cls=TransformerLMModel,
                   recipe_overrides=lm_recipe),
    }
    with tempfile.TemporaryDirectory(prefix="tmpi_codec_sweep_") as d:
        for engine in engines:
            kw = dict(grid[engine])
            if engine == "nd" and n < 2:
                continue  # tp=2 needs at least 2 chips
            for codec in codecs:
                obs_dir = os.path.join(d, f"{engine}_{codec.replace(':', '_')}")
                summary = run_training(
                    devices=n, wire_codec=codec, max_steps=max_steps,
                    n_epochs=100, dataset="synthetic",
                    # n_val covers the per-worker-batch rules' global
                    # val batch (n workers x recipe batch)
                    dataset_kwargs={"n_train": 128, "n_val": 64,
                                    "image_shape": (16, 16, 3)}
                    if engine != "nd" else {"n_train": 64, "n_val": 32},
                    obs_dir=obs_dir, print_freq=0, seed=7, **kw,
                )
                comm = None
                with open(os.path.join(obs_dir, "metrics.jsonl")) as f:
                    for line in f:
                        rec = _json.loads(line)
                        if rec.get("kind") == "comm":
                            comm = rec  # last declaration wins
                if comm is None:
                    raise RuntimeError(
                        f"{engine}/{codec}: no kind=comm record in "
                        f"{obs_dir}/metrics.jsonl — the engine did not "
                        "declare its wire model"
                    )
                rows.append({
                    "engine": engine,
                    "codec": codec,
                    "raw_bytes_per_step": round(comm["raw_bytes"], 1),
                    "wire_bytes_per_step": round(comm["wire_bytes"], 1),
                    "compression_ratio": round(comm["compression_ratio"], 3),
                    "images_per_sec": round(summary["images_per_sec"], 1),
                    # shared attribution module's utilization reading
                    # (run_training summary; None on spec-less devices)
                    "mfu": summary.get("mfu"),
                    "val_loss": round(summary["val"]["loss"], 4)
                    if "val" in summary else None,
                    "steps": summary["steps"],
                })
    int8_ratios = [r["compression_ratio"] for r in rows
                   if r["codec"].startswith("int8")]
    return {
        "metric": "codec_sweep_min_int8_compression",
        "value": round(min(int8_ratios), 3) if int8_ratios else None,
        "unit": "x raw wire bytes (min across int8 engine rows)",
        "vs_baseline": round(min(int8_ratios) / 3.5, 4) if int8_ratios
        else None,  # acceptance floor: >= 3.5x incl. scale overhead
        "baseline_estimated": False,
        "n_devices": n,
        "engines": ",".join(engines),
        "codecs": ",".join(codecs),
        "max_steps": max_steps,
        "table": rows,
    }


def bench_bucket_sweep(engines=("bsp", "bsp_fused"),
                       bucket_mbs=(0.0, 4.0, 8.0, 32.0),
                       max_steps: int = 6) -> dict:
    """Bucketed-allreduce sweep (bucket size x engine variant): run the
    BSP rule with ``--allreduce-buckets`` at each size — per-step and
    fused-dispatch (``bsp_fused`` = ``--steps-per-dispatch 4``) engine
    variants — and report throughput next to the analytic bucket count
    and overlap fraction per row. Headline value: best bucketed img/s
    over the unbucketed (size-0) baseline of the same engine variant —
    > 1.0 means the overlap schedule pays for its bucket overheads on
    this backend. Emitted through the standard snapshot schema like
    every bench mode."""
    import tempfile

    import jax

    from theanompi_tpu.launch.worker import run_training
    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.parallel.strategies import (
        BucketedOverlapSync,
        bucket_overlap_frac,
    )

    n_dev = len(jax.devices())
    n = min(4, n_dev)
    if n < 2:
        # a 1-device mesh has no allreduce: every row would read the
        # single-device fast path and the table would "prove" buckets
        # free — refuse instead (same policy as --codec-sweep)
        raise RuntimeError(
            "--bucket-sweep needs >= 2 devices; on CPU set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=4 "
            "(before jax import)")
    recipe_overrides = {"batch_size": 16, "input_shape": (16, 16, 3),
                        "sched_kwargs": {"lr": 0.05,
                                         "boundaries": [10 ** 9]}}
    # analytic geometry per size (model-dependent, run-invariant)
    model = Cifar10_model(
        Cifar10_model.default_recipe().replace(**recipe_overrides)
    )
    params = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0))[0])
    variants = {"bsp": 1, "bsp_fused": 4}  # steps_per_dispatch
    # validate the whole engine list BEFORE any training runs — a typo
    # in the second name must not discard minutes of completed sweep
    for engine in engines:
        if engine not in variants:
            raise ValueError(
                f"unknown bucket-sweep engine {engine!r}; known: "
                f"{sorted(variants)}"
            )
    rows = []
    with tempfile.TemporaryDirectory(prefix="tmpi_bucket_sweep_") as d:
        for engine in engines:
            for mb in bucket_mbs:
                summary = run_training(
                    rule="bsp", model_cls=Cifar10_model, devices=n,
                    allreduce_buckets=mb,
                    steps_per_dispatch=variants[engine],
                    max_steps=max_steps, n_epochs=100,
                    dataset="synthetic",
                    dataset_kwargs={"n_train": 128, "n_val": 64,
                                    "image_shape": (16, 16, 3)},
                    recipe_overrides=recipe_overrides,
                    obs_dir=os.path.join(
                        d, f"{engine}_{str(mb).replace('.', 'p')}"),
                    print_freq=0, seed=7,
                )
                nb = (
                    BucketedOverlapSync("data", bucket_mb=mb).n_buckets(params)
                    if mb else 1
                )
                rows.append({
                    "engine": engine,
                    "bucket_mb": float(mb),
                    "n_buckets": nb,
                    "overlap_frac": round(
                        bucket_overlap_frac(nb) if mb else 0.0, 4),
                    "images_per_sec": round(summary["images_per_sec"], 1),
                    "val_loss": round(summary["val"]["loss"], 4)
                    if "val" in summary else None,
                    "steps": summary["steps"],
                })
    def _best_ratio(engine):
        base = [r for r in rows
                if r["engine"] == engine and not r["bucket_mb"]]
        bucketed_rows = [r for r in rows
                         if r["engine"] == engine and r["bucket_mb"]]
        if not base or not bucketed_rows or not base[0]["images_per_sec"]:
            return None
        return max(r["images_per_sec"] for r in bucketed_rows) / \
            base[0]["images_per_sec"]

    ratios = [r for r in (_best_ratio(e) for e in engines) if r]
    return {
        "metric": "bucket_sweep_best_speedup_vs_unbucketed",
        "value": round(max(ratios), 4) if ratios else None,
        "unit": "x img/s of the size-0 baseline (best bucketed row)",
        "vs_baseline": round(max(ratios), 4) if ratios else None,
        "baseline_estimated": False,
        "n_devices": n,
        "engines": ",".join(engines),
        "bucket_mbs": ",".join(str(b) for b in bucket_mbs),
        "max_steps": max_steps,
        "table": rows,
    }


_SCALING_PROBE = """
# per-step timing, no scan fusion: XLA:CPU compiles a k-step scan of a
# conv model pathologically slowly (~5 min measured), and CPU dispatch
# overhead is negligible anyway
import os, jax, json, time
jax.config.update('jax_platforms', 'cpu')
import numpy as np, jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from theanompi_tpu.models.cifar10 import Cifar10_model
from theanompi_tpu.parallel import make_mesh
from theanompi_tpu.parallel.mesh import make_multislice_mesh, put_global_batch
from theanompi_tpu.parallel.strategies import get_strategy
from theanompi_tpu.train import init_train_state, make_train_step
n_dev = {n}; steps = {steps}; n_slices = {n_slices}; strategy = '{strategy}'
batch = 512  # TOTAL batch fixed across n (fixed-work overhead audit)
model = Cifar10_model(Cifar10_model.default_recipe().replace(batch_size=batch))
if n_slices > 1:
    mesh = make_multislice_mesh(n_dev, n_slices=n_slices)
    axes = tuple(mesh.axis_names)
    sizes = tuple(int(mesh.shape[a]) for a in axes)
    sync = (get_strategy('hier', axes, n_dev, axis_sizes=sizes)
            if strategy == 'hier' else get_strategy('psum', axes, n_dev))
    base = make_train_step(model, grad_sync=sync)
    runner = jax.jit(jax.shard_map(base, mesh=mesh,
        in_specs=(P(), P(axes), P(axes), P()), out_specs=(P(), P()), check_vma=False))
elif n_dev == 1:
    mesh = make_mesh(n_dev)
    runner = jax.jit(make_train_step(model))
else:
    mesh = make_mesh(n_dev)
    base = make_train_step(model, grad_sync=get_strategy('psum', 'data', n_dev))
    runner = jax.jit(jax.shard_map(base, mesh=mesh,
        in_specs=(P(), P('data'), P('data'), P()), out_specs=(P(), P()), check_vma=False))
state = init_train_state(model, jax.random.PRNGKey(0))
n_par = sum(int(l.size) for l in jax.tree_util.tree_leaves(state.params))
r = np.random.RandomState(0)
x = put_global_batch(mesh, jnp.asarray(r.randn(batch, 32, 32, 3), jnp.float32))
y = put_global_batch(mesh, jnp.asarray(r.randint(0, 10, batch), jnp.int32))
state, m = runner(state, x, y, jax.random.PRNGKey(1)); jax.block_until_ready(m['loss'])
best = None
for trial in range(3):
    t0 = time.perf_counter()
    for i in range(steps):
        state, m = runner(state, x, y, jax.random.PRNGKey(2 + i))
    jax.block_until_ready(m['loss'])
    best = min(best or 1e9, time.perf_counter() - t0)
# executed-work check (state threads through warmup + 3 trial loops)
got = int(np.asarray(state.step.addressable_shards[0].data).reshape(-1)[0])
assert got == 1 + 3 * steps, f'step counter {{got}} != {{1 + 3 * steps}}'
print(json.dumps({{'n': n_dev, 'img_s': steps * batch / best, 'params': n_par}}))
"""


def _dump_partial_scaling(rows, hier_rows, failed: str) -> None:
    """Persist whatever the scaling sweep measured BEFORE a probe
    failure aborts it (ISSUE 17 satellite: probes run minutes each —
    losing the completed ones to a late failure made reruns pure
    waste). Written next to SCALING.json under a .partial name so the
    committed artifact is never half-updated."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "SCALING.partial.json")
    with open(path, "w") as f:
        json.dump({"failed_probe": failed, "table": rows,
                   "hier_measured": hier_rows}, f, indent=1)
    sys.stderr.write(f"\npartial scaling results saved to {path}\n")


def _run_scaling_probe(n: int, steps: int, n_slices: int = 1,
                       strategy: str = "psum",
                       on_fail=None) -> dict:
    """One subprocess probe run. On any failure: record partial results
    (``on_fail`` callback) and raise WITH the underlying cause chained —
    a child process has no exception object, so the canonical
    CalledProcessError is synthesized to carry the exit code and stderr
    into ``__cause__`` instead of being dropped."""
    tag = f"n={n}" + (f" slices={n_slices} strategy={strategy}"
                      if n_slices > 1 else "")
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={n}"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["TMPI_FORCE_PLATFORM"] = "cpu"
    src = _SCALING_PROBE.format(n=n, steps=steps, n_slices=n_slices,
                                strategy=strategy)
    try:
        p = subprocess.run(
            [sys.executable, "-c", src],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired as e:
        if on_fail:
            on_fail(tag)
        raise RuntimeError(f"scaling probe {tag} timed out") from e
    if p.returncode != 0:
        sys.stderr.write(p.stderr[-2000:])
        if on_fail:
            on_fail(tag)
        raise RuntimeError(
            f"scaling probe {tag} failed (exit {p.returncode}; stderr "
            "tail above)"
        ) from subprocess.CalledProcessError(
            p.returncode, p.args, output=p.stdout, stderr=p.stderr)
    try:
        return json.loads(p.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError) as e:
        if on_fail:
            on_fail(tag)
        raise RuntimeError(
            f"scaling probe {tag} printed no result JSON; stdout tail: "
            f"{p.stdout[-300:]!r}") from e


# analytic scaling-model constants, matched to the committed
# SCALING_MODEL.json inputs (A4 v5e: ICI 90 GB/s usable, DCN 3.1
# GB/s/chip; A5: the 256-chip BASELINE point is 4 slices x 64) and its
# measured alexnet single-chip throughput — the curve below EXTENDS that
# trajectory with the explicit two-hop hierarchy
_HIER_BW_ICI = 90e9
_HIER_BW_DCN = 3.1e9
_HIER_ALEX = {"params": 61_000_000, "img_s": 18605.0, "b": 128}


def _scaling_hier_model(measured: list, n_params: int) -> dict:
    """Analytic + fitted flat-vs-hierarchical model (ISSUE 17 proof
    artifact). Two legs:

    - **analytic_curve**: alexnet weak scaling over the BASELINE
      trajectory (64 / 2x64 / 4x64 chips) comparing (a) a flat psum
      lowered as one ring over the combined mesh — every step of that
      ring is gated by the slowest link, so the whole exchange runs at
      DCN speed; (b) the ideal GSPMD hierarchical lowering, which the
      per-link TrafficModel split (obs/comm.py::dcn_fraction) assumes
      and which moves byte-for-byte what the explicit hierarchy moves;
      (c) the explicit 'hier' strategy, fp32 and with the int8:ef codec
      on the DCN hop only. (a) vs (c) is where the hierarchy wins big;
      (b) vs (c)-fp32 ties by construction, so against an ideal
      lowering only the DCN-hop codec buys anything.

    - **fit**: on the virtual CPU mesh both strategies move identical
      bytes through host memory, so the measured paired step-time delta
      isolates the fixed dispatch cost of the 3-collective pipeline
      (RS + AR + AG vs one psum). Combined with the A4 bandwidths that
      yields the crossover gradient size: below it the extra dispatch
      overhead eats the wire saving and flat psum stays faster."""
    from theanompi_tpu.obs.comm import bsp_traffic, hier_traffic
    from theanompi_tpu.parallel.codec import CODEC_WIRE_BYTES

    int8_scale = CODEC_WIRE_BYTES["int8"] / 4.0
    alex = _HIER_ALEX
    t_comp = alex["b"] / alex["img_s"]  # per-chip step seconds, weak scaling
    curve = []
    for r in (1, 2, 4):
        n = r * 64
        flat = bsp_traffic(alex["params"], n, n_slices=r)
        # ideal lowering == explicit hier fp32 (identical split)
        t_ideal = (flat.raw_ici_bytes_per_step / _HIER_BW_ICI
                   + flat.raw_dcn_bytes_per_step / _HIER_BW_DCN)
        if r > 1:
            h = hier_traffic(alex["params"], n, r)
            # one flat ring over the combined mesh: every link carries
            # ~2(n-1)/n*N*b and the DCN links set the pace
            t_ring = (flat.raw_ici_bytes_per_step
                      + flat.raw_dcn_bytes_per_step) / _HIER_BW_DCN
            t_hier = (h.raw_ici_bytes_per_step / _HIER_BW_ICI
                      + h.raw_dcn_bytes_per_step / _HIER_BW_DCN)
            t_hier8 = (h.raw_ici_bytes_per_step / _HIER_BW_ICI
                       + h.raw_dcn_bytes_per_step * int8_scale / _HIER_BW_DCN)
        else:
            t_ring = t_hier = t_hier8 = t_ideal
        curve.append({
            "n_chips": n, "slices": r,
            "t_comm_flat_ring_ms": round(t_ring * 1e3, 3),
            "t_comm_hier_ms": round(t_hier * 1e3, 3),
            "t_comm_hier_int8ef_ms": round(t_hier8 * 1e3, 3),
            "eff_flat_ring": round(t_comp / (t_comp + t_ring), 4),
            "eff_hier": round(t_comp / (t_comp + t_hier), 4),
            "eff_hier_int8ef": round(t_comp / (t_comp + t_hier8), 4),
            "comm_speedup_hier_vs_ring": round(t_ring / t_hier, 2),
        })

    fit: dict = {"pairs": []}
    deltas = []
    by_n: dict = {}
    for m in measured:
        by_n.setdefault(m["n_devices"], {})[m["strategy"]] = m
    for n, pair in sorted(by_n.items()):
        if "psum" in pair and "hier" in pair:
            d = pair["hier"]["step_s"] - pair["psum"]["step_s"]
            deltas.append(d)
            fit["pairs"].append({"n_devices": n, "slices": 2,
                                 "hier_minus_flat_step_s": round(d, 6)})
    overhead = max(0.0, sum(deltas) / len(deltas)) if deltas else None
    fit["hier_overhead_s"] = overhead
    fit["note"] = (
        "CPU-calibrated: identical wire bytes per strategy on the "
        "virtual mesh, so the paired delta is the hierarchy's fixed "
        "3-collective dispatch cost; clamped at 0 (scheduling noise "
        "can favor either side on a shared host)")

    crossover: dict = {
        "model": "hier wins once the DCN seconds it saves exceed its "
                 "fixed dispatch overhead: bytes_flat/BW_dcn - "
                 "(ici_bytes/BW_ici + dcn_bytes/BW_dcn) > overhead_s",
        "flat_baseline": "one ring over the combined mesh, paced by the "
                         "slowest (DCN) link; when GSPMD already lowers "
                         "hierarchically, fp32 hier ties and only the "
                         "DCN-hop codec wins",
    }
    if overhead is not None:
        r, s = 4, 64
        n = r * s
        flat = bsp_traffic(n_params or alex["params"], n, n_slices=r)
        h = hier_traffic(n_params or alex["params"], n, r)
        total = flat.raw_ici_bytes_per_step + flat.raw_dcn_bytes_per_step
        # per-byte wire seconds saved at the 4x64 point
        save = (1.0 / _HIER_BW_DCN
                - (h.raw_ici_bytes_per_step / total) / _HIER_BW_ICI
                - (h.raw_dcn_bytes_per_step / total) / _HIER_BW_DCN)
        if save > 0:
            # overhead/save = total allreduce wire bytes at break-even;
            # back out the gradient size via total = 2(n-1)/n * N_bytes
            grad_bytes = overhead / save / (2.0 * (n - 1) / n)
            crossover["min_grad_mb_at_4x64_v5e"] = round(
                grad_bytes / (1 << 20), 3)
        crossover["overhead_s_fitted"] = round(overhead, 6)
    return {
        "model_params_probe": n_params,
        "measured": measured,
        "fit": fit,
        "analytic_curve": curve,
        "crossover": crossover,
        "bandwidths": {"ici_gbps": _HIER_BW_ICI / 1e9,
                       "dcn_gbps": _HIER_BW_DCN / 1e9,
                       "source": "SCALING_MODEL.json A4 (v5e)"},
    }


def bench_scaling(ns=(1, 2, 4, 8), steps: int = 4) -> dict:
    """Fixed-work (strong-scaling) overhead audit on the virtual CPU
    mesh. All virtual devices share the same host cores, so total FLOPs
    throughput is invariant in n — which makes any slowdown vs n=1 a
    direct measurement of the partition + collective overhead the
    framework adds per step. (Weak scaling per-device throughput is
    meaningless here: n=8 splits the same cores 8 ways.) Run on a real
    pod for the true BASELINE scaling-efficiency number; this mode
    guards against framework-inserted overhead regressions."""
    rows: list = []
    hier_rows: list = []
    on_fail = lambda tag: _dump_partial_scaling(rows, hier_rows, tag)  # noqa: E731
    for n in ns:  # sequential: concurrent probes contend for host cores
        rows.append(_run_scaling_probe(n, steps, on_fail=on_fail))

    # flat-vs-hier measured pairs on 2-slice virtual meshes (ISSUE 17):
    # same devices, same bytes — on the CPU mesh the paired delta
    # isolates the fixed dispatch cost of the 3-collective hierarchical
    # pipeline, which _scaling_hier_model combines with the A4
    # bandwidths into the crossover fit
    batch = 512  # probe's fixed total batch
    n_params = rows[0].get("params", 0)
    for n in sorted({n for n in ns if n >= 4 and n % 2 == 0})[:2]:
        for strat in ("psum", "hier"):
            r = _run_scaling_probe(n, steps, n_slices=2, strategy=strat,
                                   on_fail=on_fail)
            hier_rows.append({
                "n_devices": n, "slices": 2, "strategy": strat,
                "images_per_sec": round(r["img_s"], 1),
                "step_s": batch / r["img_s"],
            })
            n_params = r.get("params", n_params)

    base = rows[0]["img_s"]
    base_n = rows[0]["n"]
    host_cores = os.cpu_count() or 1
    table = [
        {
            "n_devices": r["n"],
            "images_per_sec": round(r["img_s"], 1),
            "efficiency": round(r["img_s"] / base, 4),  # t(1)/t(n), work fixed
            # n far beyond the host's cores measures XLA per-partition
            # thread scheduling on a tiny fixed-batch slice, not the
            # framework's collectives — labeled so the table cannot be
            # misread as a framework-overhead regression (round-4
            # verdict weak #6), and excluded from the headline below
            **({"host_bound": True} if r["n"] >= max(16, 8 * host_cores) else {}),
        }
        for r in rows
    ]
    non_host = [t for t in table if not t.get("host_bound")]
    headline = (non_host or table)[-1]  # all-host-bound sweep still reports
    result = {
        "metric": "cifar10_cnn_bsp_fixed_work_efficiency_cpu_mesh",
        "value": headline["efficiency"],
        "headline_n": headline["n_devices"],
        "unit": f"t(n={base_n})/t(n) at fixed total batch",
        "base_n": base_n,
        "vs_baseline": round(headline["efficiency"] / 0.90, 4),  # target >=90%
        "table": table,
        "note": "virtual CPU mesh, shared host cores, total work fixed: "
        "deviation from 1.0 = partition/collective overhead the framework "
        "adds per step (NOT chip scaling; run on a pod for that). "
        "Run-to-run variance ~±10% on small shared hosts — compare trends, "
        "not single runs. Rows marked host_bound measure XLA per-partition "
        "thread-scheduling overhead on a tiny per-device slice of the fixed "
        "batch — they bound framework overhead from above and are excluded "
        "from the headline value; the committed answer to the BASELINE "
        "8->256 scaling question is the analytic SCALING_MODEL.json, "
        "extended by the hier block below with the flat-vs-hierarchical "
        "crossover model",
    }
    if hier_rows:
        result["hier"] = _scaling_hier_model(hier_rows, n_params)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)), "SCALING.json"), "w") as f:
        json.dump(result, f, indent=1)
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", choices=["compute", "e2e", "scaling"], default="compute")
    ap.add_argument("--model", default="alexnet",
                    choices=["alexnet", "googlenet", "resnet50", "vgg16", "wrn",
                             "transformer_lm", "transformer_lm_350m", "mlp"],
                    help="compute mode: which zoo model to benchmark "
                         "(the driver contract stays the AlexNet default; "
                         "mlp is the CPU-runnable smoke entry)")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--dispatch-depth", type=int, default=1,
                    help="e2e mode: async dispatch pipeline depth "
                         "(run_training --dispatch-depth; 1 = classic "
                         "per-step sync)")
    ap.add_argument("--dispatch-depths", default=None,
                    help="e2e mode: comma-separated depth sweep (e.g. "
                         "1,4,8) over the same shard files; emits the "
                         "per-depth table as dispatch_sweep in the "
                         "bench JSON, headline = deepest")
    ap.add_argument("--numerics-overhead", action="store_true",
                    help="e2e mode: also run the headline depth with "
                         "--numerics-freq 1 and report "
                         "numerics_overhead_frac (the measured step-"
                         "time cost of the in-graph sentinels)")
    ap.add_argument("--recovery-overhead", action="store_true",
                    help="e2e mode: also time clean vs injected-crash+"
                         "supervisor-resume runs and report "
                         "recovery_overhead_frac (the measured wall-"
                         "time cost of surviving one crash)")
    ap.add_argument("--codec-sweep", action="store_true",
                    help="compressed-collectives sweep (codec x engine "
                         "matrix over the wire codecs in "
                         "parallel/codec.py): per-row effective vs raw "
                         "wire bytes from each run's kind=comm record, "
                         "compression ratio, throughput and mini-run "
                         "val loss; headline = min int8 compression "
                         "ratio (overrides --mode)")
    ap.add_argument("--codec-engines", default="bsp,zero1,easgd,gosgd,nd",
                    help="codec sweep: comma-separated engine subset")
    ap.add_argument("--codecs", default="none,bf16,int8,int8:ef",
                    help="codec sweep: comma-separated codec subset")
    ap.add_argument("--fused-update", action="store_true",
                    help="compute mode: one-pass fused optimizer "
                         "epilogue (ops/pallas_update.py; ROADMAP 2a)")
    ap.add_argument("--allreduce-buckets", type=float, default=0.0,
                    metavar="MB",
                    help="compute mode: bucketed overlap-with-backward "
                         "allreduce (parallel/strategies.py; no-op on "
                         "one chip; ROADMAP 2b)")
    ap.add_argument("--bucket-sweep", action="store_true",
                    help="bucketed-allreduce sweep (bucket size x "
                         "engine variant over the BSP rule): per-row "
                         "img/s + analytic bucket count/overlap; "
                         "headline = best speedup vs the unbucketed "
                         "baseline (overrides --mode)")
    ap.add_argument("--bucket-engines", default="bsp,bsp_fused",
                    help="bucket sweep: engine variants (bsp = "
                         "per-step dispatch, bsp_fused = "
                         "--steps-per-dispatch 4)")
    ap.add_argument("--bucket-sizes", default="0,4,8,32",
                    help="bucket sweep: comma-separated bucket sizes "
                         "in MB (0 = the unbucketed baseline row)")
    ap.add_argument("--serve-bench", action="store_true",
                    help="closed-loop serving benchmark over the "
                         "dynamic micro-batching engine (serve/): "
                         "sustained req/s + p50/p99 latency + batch-"
                         "fill over a real checkpoint round-trip "
                         "(overrides --mode)")
    ap.add_argument("--decode-bench", action="store_true",
                    help="LM token-serving benchmark over the "
                         "continuous-batching decode engine "
                         "(serve/decode/): sustained tokens/sec and "
                         "continuous-vs-static ratio under a "
                         "saturating mixed-length burst, plus p50/p99 "
                         "TTFT and TPOT under open-loop Poisson "
                         "arrivals (overrides --mode; baseline under "
                         "experiments/decode_bench/)")
    ap.add_argument("--decode-rate", type=float, default=100.0,
                    help="decode bench: fixed open-loop Poisson offered "
                         "rate (requests/sec) for the TTFT window; "
                         "re-baseline with a rate ~0.25x the host's "
                         "burst capacity when the CI host class changes")
    ap.add_argument("--serve-duration", type=float, default=2.0,
                    help="serve bench: closed-loop load window seconds")
    ap.add_argument("--serve-clients", type=int, default=8,
                    help="serve bench: concurrent closed-loop clients")
    ap.add_argument("--serve-buckets", default="1,8,32",
                    help="serve bench: comma-separated batch buckets")
    ap.add_argument("--replicas", type=int, default=1, metavar="N",
                    help="serve bench: N > 1 switches to the OPEN-LOOP "
                         "replica-fleet benchmark (Poisson arrivals, "
                         "p50/p99/p999, goodput under overload and "
                         "under a mid-run replica kill with recovery "
                         "ratio); 1 = the classic closed loop")
    ap.add_argument("--ns", default=None,
                    help="scaling mode: comma-separated device counts "
                         "(default 1,2,4,8; the verdict-3 extension runs "
                         "--ns 1,2,4,8,16,32,64)")
    ap.add_argument("--obs-dir", default=None,
                    help="also append the result, re-expressed in the obs "
                         "metrics-snapshot schema, to <dir>/metrics.jsonl "
                         "(one JSONL format for bench output and training "
                         "telemetry; schema: tools/check_obs_schema.py)")
    args = ap.parse_args()

    if args.codec_sweep:
        result = bench_codec_sweep(
            engines=tuple(e for e in args.codec_engines.split(",") if e),
            codecs=tuple(c for c in args.codecs.split(",") if c),
            max_steps=args.steps or 6,
        )
    elif args.bucket_sweep:
        result = bench_bucket_sweep(
            engines=tuple(e for e in args.bucket_engines.split(",") if e),
            bucket_mbs=tuple(float(b) for b in args.bucket_sizes.split(",")),
            max_steps=args.steps or 6,
        )
    elif args.decode_bench:
        result = bench_decode(duration_s=args.serve_duration,
                              rate_rps=args.decode_rate)
    elif args.serve_bench:
        if args.replicas > 1:
            result = bench_serve_fleet(
                duration_s=args.serve_duration, replicas=args.replicas,
                buckets=tuple(int(b)
                              for b in args.serve_buckets.split(",")),
            )
        else:
            result = bench_serve(
                duration_s=args.serve_duration,
                clients=args.serve_clients,
                buckets=tuple(int(b)
                              for b in args.serve_buckets.split(",")),
            )
    elif args.mode == "compute":
        result = bench_compute(steps=args.steps or 20, model_name=args.model,
                               fused_update=args.fused_update,
                               allreduce_buckets=args.allreduce_buckets)
    elif args.mode == "e2e":
        depths = (
            tuple(int(k) for k in args.dispatch_depths.split(","))
            if args.dispatch_depths else (args.dispatch_depth,)
        )
        result = bench_e2e(max_steps=args.steps or 48, dispatch_depths=depths,
                           numerics=args.numerics_overhead,
                           recovery=args.recovery_overhead)
    else:
        ns = tuple(int(n) for n in args.ns.split(",")) if args.ns else (1, 2, 4, 8)
        result = bench_scaling(ns=ns, steps=args.steps or 4)
    # obs emission (ISSUE 1 satellite): the same result as a metrics-
    # snapshot record, printed BEFORE the driver-contract line (the LAST
    # stdout line stays the raw result object) and optionally appended
    # to an obs metrics sink
    from theanompi_tpu.obs.metrics import result_to_snapshot

    snapshot = result_to_snapshot(result, source="bench")
    print(json.dumps(snapshot))
    if args.obs_dir:
        os.makedirs(args.obs_dir, exist_ok=True)
        with open(os.path.join(args.obs_dir, "metrics.jsonl"), "a") as f:
            f.write(json.dumps(snapshot) + "\n")
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
