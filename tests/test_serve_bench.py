"""Acceptance: ``python bench.py --serve-bench`` runs on
JAX_PLATFORMS=cpu and reports sustained throughput + p99 latency in the
standard snapshot schema; ``tmpi serve --selftest`` serves a real
checkpoint end-to-end from the CLI."""

import json
import os
import subprocess
import sys

import jax

from theanompi_tpu.tools.check_obs_schema import validate_record

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, timeout=600):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["TMPI_FORCE_PLATFORM"] = "cpu"
    p = subprocess.run(
        cmd, cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    assert p.returncode == 0, f"{cmd} failed:\n{p.stderr[-3000:]}"
    return [l for l in p.stdout.strip().splitlines() if l.strip()]


def test_serve_bench_cpu_snapshot_schema():
    lines = _run([
        sys.executable, "bench.py", "--serve-bench",
        "--serve-duration", "0.6", "--serve-clients", "3",
        "--serve-buckets", "1,4",
    ])
    # driver contract: LAST line is the raw result object
    result = json.loads(lines[-1])
    assert result["metric"] == "serve_cifar10_requests_per_sec"
    assert result["unit"] == "requests/sec"
    assert result["value"] > 0
    assert result["p99_ms"] > 0 and result["p50_ms"] <= result["p99_ms"]
    assert 0 < result["batch_fill"] <= 1.0
    assert result["compiled_programs"] == 2  # one per bucket
    # satellite: the result ALSO rides the metrics-snapshot schema
    snapshot = json.loads(lines[-2])
    assert snapshot["kind"] == "metrics"
    assert validate_record(snapshot) == []
    assert snapshot["metrics"]["bench_p99_ms"] == result["p99_ms"]
    assert snapshot["metrics"]["bench_value"] == result["value"]


def test_cli_serve_selftest_roundtrip(tmp_path):
    """tmpi serve over a checkpoint this test saves: load -> AOT warm ->
    closed-loop selftest requests -> schema-valid serve stats line."""
    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.train import init_train_state
    from theanompi_tpu.utils.checkpoint import save_checkpoint

    model = Cifar10_model()
    state = init_train_state(model, jax.random.PRNGKey(0))
    save_checkpoint(str(tmp_path), state, 2, rng=jax.random.PRNGKey(1))

    obs = tmp_path / "obs"
    lines = _run([
        sys.executable, "-m", "theanompi_tpu.cli", "serve",
        "--ckpt-dir", str(tmp_path), "--model", "cifar10",
        "--buckets", "1,4", "--selftest", "5", "--obs-dir", str(obs),
    ])
    stats = json.loads(lines[-1])
    assert stats["params_step"] == 2
    assert stats["metrics"]["tmpi_serve_served_total"] == 5.0
    assert validate_record(stats) == []
    # the obs sink landed and validates too
    from theanompi_tpu.tools.check_obs_schema import check_file

    assert check_file(str(obs / "serve.jsonl")) == []


def test_serve_fleet_bench_open_loop_with_midrun_kill():
    """ISSUE 19 acceptance: ``bench.py --serve-bench --replicas 2``
    runs the OPEN-LOOP load generator (Poisson arrivals) over a
    2-replica router, reports p50/p99/p999 + goodput, kills a replica
    mid-run, and the post-kill goodput recovers to within 10% of the
    pre-kill rate — with zero dropped requests and the failover/restart
    counters showing the fleet actually absorbed the loss."""
    lines = _run([
        sys.executable, "bench.py", "--serve-bench", "--replicas", "2",
        "--serve-duration", "3.0", "--serve-buckets", "1,8",
    ])
    result = json.loads(lines[-1])
    assert result["metric"] == "serve_fleet_goodput_rps_2r"
    assert result["replicas"] == 2
    assert result["serve_goodput_rps"] > 0
    assert (0 < result["serve_p50_ms"] <= result["serve_p99_ms"]
            <= result["serve_p999_ms"])
    # the mid-run replica kill was absorbed: traffic failed over, the
    # supervisor restarted the member, nothing was dropped, and the
    # tail window served >= 0.9x the pre-kill fraction of its offered
    # arrivals (a served-fraction ratio — immune to Poisson shot noise
    # and box slowdown, but tail rejects/drops/failures score against it)
    assert result["failovers"] >= 0 and result["restarts"] >= 1
    assert result["dropped"] == 0 and result["failed"] == 0
    assert result["recovery_ratio"] >= 0.9, result
    # overload probe: the fleet sheds load via rejects, not drops
    assert result["overload_rejected"] >= 0
    # snapshot schema (second-to-last line), perf_gate's input shape:
    # the gated serve_p99_ms / serve_goodput_rps gauges are extractable
    snapshot = json.loads(lines[-2])
    assert snapshot["kind"] == "metrics"
    assert validate_record(snapshot) == []
    assert snapshot["metrics"]["bench_serve_p99_ms"] == result["serve_p99_ms"]
    from theanompi_tpu.tools.perf_gate import extract_invariants

    inv = extract_invariants(snapshot)
    assert inv["serve_p99_ms"] == result["serve_p99_ms"]
    assert inv["serve_goodput_rps"] == result["serve_goodput_rps"]


def test_serve_fleet_baseline_gates(tmp_path):
    """The committed experiments/serve_bench/baseline.json is a usable
    perf_gate baseline: gating it against itself passes, and a 2x p99
    regression (the drift the gate exists to catch) fails."""
    from theanompi_tpu.tools.perf_gate import main as gate_main

    base = os.path.join(REPO_ROOT, "experiments", "serve_bench",
                        "baseline.json")
    assert gate_main([base, base]) == 0
    snap = json.loads(open(base).read())
    snap["metrics"]["bench_serve_p99_ms"] *= 2.0
    cur = tmp_path / "regressed.json"
    cur.write_text(json.dumps(snap))
    assert gate_main([base, str(cur)]) == 1
