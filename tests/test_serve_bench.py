"""Acceptance: ``python bench.py --serve-bench`` runs on
JAX_PLATFORMS=cpu and reports sustained throughput + p99 latency in the
standard snapshot schema; ``tmpi serve --selftest`` serves a real
checkpoint end-to-end from the CLI."""

import json
import os
import subprocess
import sys

import jax

from theanompi_tpu.tools.check_obs_schema import validate_record

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, timeout=600):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["TMPI_FORCE_PLATFORM"] = "cpu"
    p = subprocess.run(
        cmd, cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    assert p.returncode == 0, f"{cmd} failed:\n{p.stderr[-3000:]}"
    return [l for l in p.stdout.strip().splitlines() if l.strip()]


def test_serve_bench_cpu_snapshot_schema():
    lines = _run([
        sys.executable, "bench.py", "--serve-bench",
        "--serve-duration", "0.6", "--serve-clients", "3",
        "--serve-buckets", "1,4",
    ])
    # driver contract: LAST line is the raw result object
    result = json.loads(lines[-1])
    assert result["metric"] == "serve_cifar10_requests_per_sec"
    assert result["unit"] == "requests/sec"
    assert result["value"] > 0
    assert result["p99_ms"] > 0 and result["p50_ms"] <= result["p99_ms"]
    assert 0 < result["batch_fill"] <= 1.0
    assert result["compiled_programs"] == 2  # one per bucket
    # satellite: the result ALSO rides the metrics-snapshot schema
    snapshot = json.loads(lines[-2])
    assert snapshot["kind"] == "metrics"
    assert validate_record(snapshot) == []
    assert snapshot["metrics"]["bench_p99_ms"] == result["p99_ms"]
    assert snapshot["metrics"]["bench_value"] == result["value"]


def test_cli_serve_selftest_roundtrip(tmp_path):
    """tmpi serve over a checkpoint this test saves: load -> AOT warm ->
    closed-loop selftest requests -> schema-valid serve stats line."""
    from theanompi_tpu.models.cifar10 import Cifar10_model
    from theanompi_tpu.train import init_train_state
    from theanompi_tpu.utils.checkpoint import save_checkpoint

    model = Cifar10_model()
    state = init_train_state(model, jax.random.PRNGKey(0))
    save_checkpoint(str(tmp_path), state, 2, rng=jax.random.PRNGKey(1))

    obs = tmp_path / "obs"
    lines = _run([
        sys.executable, "-m", "theanompi_tpu.cli", "serve",
        "--ckpt-dir", str(tmp_path), "--model", "cifar10",
        "--buckets", "1,4", "--selftest", "5", "--obs-dir", str(obs),
    ])
    stats = json.loads(lines[-1])
    assert stats["params_step"] == 2
    assert stats["metrics"]["tmpi_serve_served_total"] == 5.0
    assert validate_record(stats) == []
    # the obs sink landed and validates too
    from theanompi_tpu.tools.check_obs_schema import check_file

    assert check_file(str(obs / "serve.jsonl")) == []
