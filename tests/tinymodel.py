"""Minimal CNN for sync-rule/engine tests.

The exchanger algebra (EASGD elastic updates, GoSGD share-weight
merges, BSP allreduce) is model-independent, so these tests don't need
a realistic network — they need the cheapest model that still has a
multi-leaf param pytree and a real loss. A 1-conv net compiles several
times faster than the WRN CI variant on the single-CPU test host,
which is what keeps the fast tier inside its budget (round-4 re-tier).
"""

from theanompi_tpu import nn
from theanompi_tpu.models.cifar10 import Cifar10_model
from theanompi_tpu.nn import init as initializers


class TinyCNN(Cifar10_model):
    name = "tinycnn"

    def build(self):
        he = initializers.he_normal()
        return nn.Sequential(
            [
                nn.Conv(8, 3, padding="SAME", w_init=he, name="conv1"),
                nn.Activation("relu"),
                nn.Pool(2, stride=2, mode="max"),
                nn.Flatten(),
                nn.Dense(self.recipe.num_classes, name="softmax"),
            ],
            name="tiny_cnn",
        )
