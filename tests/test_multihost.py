"""Multi-controller integration tests: N cooperating processes over
sliced virtual CPU devices — the capability the reference got from
``mpirun`` + MPI_COMM_WORLD (reference: ``lib/base.py``
``get_internode_comm``; SURVEY.md §1 L1) and that a TPU pod gets from
one controller process per host.

These spawn REAL separate Python processes that form a
``jax.distributed`` world (gloo collectives over localhost), run the
same ``tmpi`` command on each, and verify lockstep training, rank-0
file output, and the cross-host checkpoint gather.
"""

import json

import pytest

from theanompi_tpu.launch.multihost import spawn_local

pytestmark = pytest.mark.slow

_TINY = [
    "--dataset", "synthetic",
    "--dataset-arg", "n_train=32",
    # n_val must cover EASGD's 8x4=32 global val batch: the driver now
    # REFUSES configs whose val loop would silently run zero batches
    "--dataset-arg", "n_val=32",
    "--epochs", "1",
    "--print-freq", "0",
]

_WRN = ["theanompi_tpu.models.model_zoo.wrn", "WRN_16_4"]


def _run(rule, tmp_path, extra=(), nproc=2, devices=8, batch=8):
    argv = [
        "-m", "theanompi_tpu.cli", rule, str(devices), *_WRN,
        "--batch-size", str(batch),
        "--save-dir", str(tmp_path), "--ckpt-dir", str(tmp_path / "ckpt"),
        *_TINY, *extra,
    ]
    return spawn_local(
        nproc, argv, devices_per_proc=devices // nproc, timeout=600
    )


def test_bsp_two_controllers(tmp_path):
    codes = _run("BSP", tmp_path)
    assert codes == [0, 0], f"controller exit codes {codes}"
    # rank 0 wrote recorder files; rank 1 must not have
    jsonl = tmp_path / "wrn_16_4_bsp.jsonl"
    assert jsonl.exists()
    events = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert any(e["kind"] == "train" for e in events)
    assert any(e["kind"] == "val" for e in events)
    # checkpoint written once (rank 0), loadable
    ckpts = list((tmp_path / "ckpt").glob("ckpt_*.npz"))
    assert len(ckpts) == 1


def test_easgd_two_controllers_sharded_checkpoint(tmp_path):
    """EASGD's per-worker state is SHARDED across processes — the
    checkpoint path must gather non-addressable shards cross-host."""
    # per-worker batch semantics: global batch = 8 workers x 4 = 32
    codes = _run("EASGD", tmp_path, extra=["--avg-freq", "1"], batch=4)
    assert codes == [0, 0], f"controller exit codes {codes}"
    ckpts = list((tmp_path / "ckpt").glob("ckpt_*.npz"))
    assert len(ckpts) == 1
    import numpy as np

    data = np.load(ckpts[0])
    worker_steps = [k for k in data.files if k.endswith("step") and "workers" in k]
    assert worker_steps, f"no per-worker step leaf in {data.files[:8]}"
    # the stacked worker axis must hold ALL 8 workers, not this host's 4
    assert data[worker_steps[0]].shape == (8,)


_LM = ["theanompi_tpu.models.lm", "TransformerLMModel"]
_LM_TINY = [
    "--recipe-arg", "d_model=32",
    "--recipe-arg", "n_heads=4",
    "--recipe-arg", "n_layers=2",
    "--recipe-arg", "d_ff=64",
    "--recipe-arg", "input_shape=(32,)",
    "--recipe-arg", "num_classes=32",
    "--batch-size", "16",
    "--dataset", "synthetic",
    "--dataset-arg", "n_train=64",
    "--dataset-arg", "n_val=16",
    "--print-freq", "0",
]


def _run_lm_nd(tmp_path, extra, nproc=2, devices=2):
    argv = [
        "-m", "theanompi_tpu.cli", "BSP", str(devices), *_LM,
        "--save-dir", str(tmp_path), "--ckpt-dir", str(tmp_path / "ckpt"),
        *_LM_TINY, *extra,
    ]
    return spawn_local(
        nproc, argv, devices_per_proc=devices // nproc, timeout=600
    )


def test_tp_two_controllers_with_resume(tmp_path):
    """Tensor parallelism SPANNING controller processes (round-4 verdict
    item 2: the reference ran every rule across nodes — SURVEY §3.1/§5.8
    mpirun process model): the tp=2 axis crosses the 2-process gloo
    world, host feed comes from NDEngine.host_batch_part (tokens are
    tp-replicated here, so both hosts feed the full batch and placement
    takes only addressable shards), the cross-host-sharded params are
    gathered into one checkpoint, and a second 2-process launch resumes
    from it in agreement."""
    codes = _run_lm_nd(tmp_path, ["--tp", "2", "--epochs", "1"])
    assert codes == [0, 0], f"controller exit codes {codes}"
    ckpts = list((tmp_path / "ckpt").glob("ckpt_*.npz"))
    assert len(ckpts) == 1  # rank-0 gathered save, written once
    codes = _run_lm_nd(tmp_path, ["--tp", "2", "--epochs", "2", "--resume"])
    assert codes == [0, 0], f"resume exit codes {codes}"
    jsonl = list(tmp_path.glob("*.jsonl"))
    assert len(jsonl) == 1
    events = [json.loads(l) for l in jsonl[0].read_text().splitlines()]
    steps = [e["step"] for e in events if e["kind"] == "train"]
    # 64 train tokens / batch 16 = 4 steps/epoch; resume continues 5..8
    # exactly (no replay, no gap) after the first launch's 1..4
    assert steps == list(range(1, 5)) + list(range(5, 9)), steps
    assert all(
        e["loss"] > 0 for e in events if e["kind"] == "train"
    )


def test_pp_two_controllers_sharded_checkpoint(tmp_path):
    """GPipe pipeline stages split ACROSS controller processes, with the
    per-host sharded checkpoint path (each host writes only its stage's
    addressable shards; the set is restorable under any process count)."""
    codes = _run_lm_nd(
        tmp_path, ["--pp", "2", "--epochs", "1", "--ckpt-sharded"]
    )
    assert codes == [0, 0], f"controller exit codes {codes}"
    shards = list((tmp_path / "ckpt").glob("ckpt_*.proc*of2.npz"))
    assert len(shards) == 2, [p.name for p in (tmp_path / "ckpt").iterdir()]
    # reassembly under a DIFFERENT process count: load single-process
    from theanompi_tpu.utils.checkpoint import latest_checkpoint

    assert latest_checkpoint(str(tmp_path / "ckpt")) is not None


def test_expert_two_controllers(tmp_path):
    """Switch-MoE expert parallelism across controller processes: the
    expert axis (also the batch axis) spans the 2-process world, so the
    all-to-all token dispatch crosses hosts and each host feeds its
    contiguous half of the batch (NDEngine.host_batch_part)."""
    argv = [
        "-m", "theanompi_tpu.cli", "BSP", "2",
        "theanompi_tpu.models.lm", "MoELMModel",
        "--expert", "2", "--epochs", "1",
        "--save-dir", str(tmp_path),
        "--recipe-arg", "n_experts=2",
        *_LM_TINY,
    ]
    codes = spawn_local(2, argv, devices_per_proc=1, timeout=600)
    assert codes == [0, 0], f"controller exit codes {codes}"
    jsonl = list(tmp_path.glob("*.jsonl"))
    assert len(jsonl) == 1  # rank-0 recorder only
    events = [json.loads(l) for l in jsonl[0].read_text().splitlines()]
    assert any(e["kind"] == "val" for e in events)
    assert all(e["loss"] > 0 for e in events if e["kind"] == "train")


def test_spawn_local_propagates_failure(tmp_path):
    codes = spawn_local(
        2,
        ["-c", "import sys, os; sys.exit(int(os.environ['TMPI_PROCESS_ID']))"],
        devices_per_proc=1,
        timeout=120,
    )
    assert codes == [0, 1]


def test_spawn_local_kills_hung_survivors():
    """A rank dying early must not hang the launcher while the other
    rank blocks forever (here: sleeps) — survivors are killed after the
    failure grace period."""
    import time

    t0 = time.monotonic()
    codes = spawn_local(
        2,
        [
            "-c",
            "import sys, os, time\n"
            "rank = int(os.environ['TMPI_PROCESS_ID'])\n"
            "sys.exit(1) if rank == 1 else time.sleep(600)",
        ],
        devices_per_proc=1,
        timeout=300,
        failure_grace=3.0,
    )
    assert time.monotonic() - t0 < 60, "launcher did not kill hung rank 0"
    assert codes[1] == 1
    assert codes[0] != 0  # killed, not a clean exit


def test_cli_refuses_nested_respawn(monkeypatch, capsys):
    """--nproc inside an already-spawned controller must not fork again
    (fork-bomb guard), and abbreviated --npro must be rejected."""
    import theanompi_tpu.cli as cli

    monkeypatch.setenv("TMPI_PROCESS_ID", "0")
    monkeypatch.setenv("TMPI_NUM_PROCESSES", "2")
    called = {}
    monkeypatch.setattr(
        "theanompi_tpu.launch.multihost.spawn_local",
        lambda *a, **k: called.setdefault("spawned", True) or [0],
    )
    # run_training / distributed init will be reached instead of a
    # respawn; stub them out (no real world to join in this test)
    import theanompi_tpu.launch.worker as worker
    import theanompi_tpu.parallel.distributed as dist

    monkeypatch.setattr(worker, "run_training", lambda **k: {"steps": 0, "epochs": []})
    monkeypatch.setattr(dist, "initialize_distributed", lambda *a, **k: False)
    rc = cli.main(
        ["BSP", "1", *_WRN, "--nproc", "2", "--max-steps", "1", "--synthetic"]
    )
    assert rc == 0
    assert "spawned" not in called

    with pytest.raises(SystemExit):
        cli.build_parser().parse_args(["BSP", "1", *_WRN, "--npro", "2"])
