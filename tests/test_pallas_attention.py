"""Pallas fused flash attention (ops/pallas_attention.py) vs the unfused
single-device oracle — forward, backward (custom VJP), padding/masking
edges, the transformer wiring, and the ulysses+flash composition. On CPU
the kernels run through the Pallas interpreter — same numerics as the
native TPU lowering. (BEYOND-PARITY: the 2016 reference has no attention
op; SURVEY.md §5.7.)"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.ops.pallas_attention import flash_attention
from theanompi_tpu.ops.ring_attention import (
    full_attention_reference,
    ulysses_attention,
)


def qkv(shape, seed, dtype=jnp.float32):
    r = np.random.RandomState(seed)
    return [jnp.asarray(r.randn(*shape), dtype) for _ in range(3)]


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "T,D,bq,bk",
    [
        (64, 32, 32, 32),   # exact multiples, several blocks
        (80, 24, 32, 16),   # ragged T (query+key padding), ragged D
        (16, 8, 128, 128),  # T smaller than one block
    ],
)
def test_forward_matches_reference(causal, T, D, bq, bk):
    q, k, v = qkv((2, T, 3, D), seed=T + D, )
    got = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk)
    want = full_attention_reference(q, k, v, causal=causal)
    assert got.shape == want.shape and got.dtype == want.dtype
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=3e-6, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_cross_attention_unequal_lengths(causal, monkeypatch):
    """Tq != Tk cross attention, both ragged vs blocks, causal included
    (position-aligned-at-start convention) — and the TMPI_PALLAS=0
    fallback must accept the same shapes (it used to build a [Tq, Tq]
    tril mask and crash on causal Tq != Tk)."""
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(2, 40, 2, 16), jnp.float32)
    k = jnp.asarray(r.randn(2, 72, 2, 16), jnp.float32)
    v = jnp.asarray(r.randn(2, 72, 2, 16), jnp.float32)
    got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    want = full_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=3e-6, rtol=1e-5)
    monkeypatch.setenv("TMPI_PALLAS", "0")
    fb = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(fb), np.asarray(want),
                               atol=3e-6, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    """Custom-VJP backward (dq/dk/dv kernels) vs jax AD of the oracle;
    ragged sizes so the padded tail's zero-gradient path is exercised."""
    q, k, v = qkv((2, 48, 2, 24), seed=7)

    def loss(f):
        return lambda q, k, v: jnp.sum(
            jnp.sin(f(q, k, v)) * (1.0 + jnp.arange(24))
        )

    gf = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=16, block_k=16)),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        loss(lambda q, k, v: full_attention_reference(q, k, v, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=1e-4,
            err_msg=f"d{name} mismatch (causal={causal})",
        )


def test_bf16_inputs():
    """bf16 in/out with fp32 softmax statistics: matches the fp32 oracle
    within bf16 matmul tolerance, and preserves the input dtype."""
    q, k, v = qkv((2, 64, 2, 32), seed=3, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    assert got.dtype == jnp.bfloat16
    want = full_attention_reference(
        *(t.astype(jnp.float32) for t in (q, k, v)), causal=True
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), atol=4e-2, rtol=4e-2
    )


def test_fallback_env_matches(monkeypatch):
    """TMPI_PALLAS=0 routes to the unfused reference (same signature)."""
    q, k, v = qkv((1, 32, 2, 16), seed=5)
    with_pallas = flash_attention(q, k, v, causal=True)
    monkeypatch.setenv("TMPI_PALLAS", "0")
    without = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(with_pallas), np.asarray(without), atol=3e-6, rtol=1e-5
    )


@pytest.mark.slow
def test_transformer_flash_matches_dense():
    """TransformerLM(attn='flash') loss AND grads == the default local
    full-attention path on identical params (no SP axis)."""
    from theanompi_tpu.models.transformer import TransformerLM

    r = np.random.RandomState(11)
    toks = jnp.asarray(r.randint(0, 64, (2, 40)), jnp.int32)
    lm_ref = TransformerLM(vocab=64, d_model=32, n_heads=2, n_layers=2,
                           d_ff=64, max_len=40)
    lm_flash = lm_ref._replace(attn="flash")
    params = lm_ref.init(jax.random.PRNGKey(0))

    lr, gr = jax.value_and_grad(
        lambda p: lm_ref.loss(p, toks, axis_name=None)
    )(params)
    lf, gf = jax.value_and_grad(
        lambda p: lm_flash.loss(p, toks, axis_name=None)
    )(params)
    np.testing.assert_allclose(float(lf), float(lr), atol=1e-5, rtol=1e-5)
    flat_r = jax.tree_util.tree_leaves(gr)
    flat_f = jax.tree_util.tree_leaves(gf)
    for a, b in zip(flat_f, flat_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=1e-3)


def test_precision_highest_bf16_matches_fp32_oracle():
    """precision=HIGHEST upcasts the tiles: bf16 inputs then match the
    fp32 oracle to fp32 tolerance (not bf16's ~5e-3) — the same knob the
    unfused reference exposes, so ulysses local_fn forwarding is sound."""
    q, k, v = qkv((2, 64, 2, 32), seed=17, dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True,
                          precision=jax.lax.Precision.HIGHEST,
                          block_q=32, block_k=32)
    assert got.dtype == jnp.bfloat16  # output dtype preserved
    want = full_attention_reference(
        *(t.astype(jnp.float32) for t in (q, k, v)), causal=True
    )
    # bf16 OUTPUT rounding is the only remaining error source
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), atol=1e-2, rtol=1e-2
    )
    # vs the non-upcast path the error should be strictly smaller
    loose = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    err_hi = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want)))
    err_lo = float(jnp.max(jnp.abs(loose.astype(jnp.float32) - want)))
    assert err_hi <= err_lo + 1e-6


def test_transformer_ulysses_flash_without_sp_uses_flash():
    """attn='ulysses_flash' with no SP axis degenerates to the fused
    local kernel (NOT the unfused O(T^2) reference) and matches the
    dense path numerically."""
    from theanompi_tpu.models.transformer import TransformerLM

    r = np.random.RandomState(19)
    toks = jnp.asarray(r.randint(0, 64, (2, 32)), jnp.int32)
    lm_uf = TransformerLM(vocab=64, d_model=32, n_heads=2, n_layers=1,
                          d_ff=64, max_len=32, attn="ulysses_flash")
    params = lm_uf.init(jax.random.PRNGKey(0))
    l_uf = float(lm_uf.loss(params, toks, axis_name=None))
    l_ref = float(lm_uf._replace(attn="ring").loss(params, toks, axis_name=None))
    np.testing.assert_allclose(l_uf, l_ref, atol=1e-5, rtol=1e-5)


def test_transformer_flash_under_sp_rejected():
    """attn='flash' is a local kernel: combining it with a seq axis must
    fail loudly at trace time, pointing at ring/ulysses."""
    from theanompi_tpu.models.transformer import SEQ_AXIS, TransformerLM, \
        make_sp_train_step
    from theanompi_tpu.parallel import make_mesh

    lm = TransformerLM(vocab=64, d_model=32, n_heads=2, n_layers=1,
                       d_ff=64, max_len=64, attn="flash")
    mesh = make_mesh(8, axis_names=(SEQ_AXIS,))
    step = make_sp_train_step(lm, mesh)
    toks = jnp.zeros((2, 64), jnp.int32)
    with pytest.raises(ValueError, match="ring"):
        step(lm.init(jax.random.PRNGKey(0)), toks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense_oracle(causal, mesh8):
    """ring_flash_attention on the 8-way mesh == the dense single-device
    oracle: per-hop flash folds + logsumexp merge reproduce the exact
    global softmax, with GLOBAL-position causal masking."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from theanompi_tpu.ops.pallas_attention import ring_flash_attention

    B, T, H, D = 2, 64, 2, 16
    qg, kg, vg = qkv((B, T, H, D), seed=23)

    f = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring_flash_attention(
                q, k, v, "data", causal=causal, block_q=8, block_k=8
            ),
            mesh=mesh8,
            in_specs=(P(None, "data"),) * 3, out_specs=P(None, "data"),
            check_vma=False,
        )
    )
    shard = NamedSharding(mesh8, P(None, "data"))
    got = f(*(jax.device_put(t, shard) for t in (qg, kg, vg)))
    want = full_attention_reference(qg, kg, vg, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-4)


def test_ring_flash_grads_match_dense_oracle(mesh8):
    """Whole-ring custom VJP (dq local-accumulated, dk/dv traveling with
    their shard) == jax AD of the dense oracle."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from theanompi_tpu.ops.pallas_attention import ring_flash_attention

    B, T, H, D = 1, 32, 2, 8
    qg, kg, vg = qkv((B, T, H, D), seed=29)
    weight = jnp.asarray(np.random.RandomState(31).randn(D), jnp.float32)

    def ring_loss(q, k, v):
        out = jax.shard_map(
            lambda q, k, v: ring_flash_attention(
                q, k, v, "data", causal=True, block_q=8, block_k=8
            ),
            mesh=mesh8,
            in_specs=(P(None, "data"),) * 3, out_specs=P(None, "data"),
            check_vma=False,
        )(q, k, v)
        return jnp.sum(jnp.sin(out) * weight)

    def dense_loss(q, k, v):
        return jnp.sum(jnp.sin(full_attention_reference(q, k, v, causal=True)) * weight)

    shard = NamedSharding(mesh8, P(None, "data"))
    qs, ks, vs = (jax.device_put(t, shard) for t in (qg, kg, vg))
    gr = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(qs, ks, vs)
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(qg, kg, vg)
    for a, b, name in zip(gr, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=1e-3,
            err_msg=f"ring_flash d{name} mismatch",
        )


@pytest.mark.slow
def test_transformer_ring_flash_matches_ring(mesh8):
    """TransformerLM(attn='ring_flash') == attn='ring' (unfused) on the
    same params over the 8-way seq mesh — loss and one SGD step."""
    from theanompi_tpu.models.transformer import (
        SEQ_AXIS,
        TransformerLM,
        make_sp_train_step,
    )
    from theanompi_tpu.parallel import make_mesh

    mesh = make_mesh(8, axis_names=(SEQ_AXIS,))
    r = np.random.RandomState(37)
    toks = jnp.asarray(r.randint(0, 64, (2, 64)), jnp.int32)
    losses = {}
    for attn in ("ring", "ring_flash"):
        lm = TransformerLM(vocab=64, d_model=32, n_heads=2, n_layers=1,
                           d_ff=64, max_len=64, attn=attn)
        step = make_sp_train_step(lm, mesh, lr=0.1)
        params = lm.init(jax.random.PRNGKey(0))
        params, loss = step(params, toks)
        losses[attn] = (float(loss), params)
    np.testing.assert_allclose(losses["ring_flash"][0], losses["ring"][0],
                               atol=1e-5, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(losses["ring_flash"][1]),
                    jax.tree_util.tree_leaves(losses["ring"][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3)


def test_ulysses_flash_composition(mesh8):
    """ulysses_attention(local_fn=flash_attention) on the 8-way mesh ==
    the dense oracle: the fused kernel runs inside shard_map, after the
    head<->sequence all-to-all."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    r = np.random.RandomState(13)
    B, T, H, D = 2, 64, 8, 16
    qg, kg, vg = qkv((B, T, H, D), seed=13)

    def sp(q, k, v):
        return ulysses_attention(
            q, k, v, "data", causal=True, local_fn=flash_attention
        )

    f = jax.jit(
        jax.shard_map(
            sp, mesh=mesh8,
            in_specs=(P(None, "data"),) * 3, out_specs=P(None, "data"),
            check_vma=False,
        )
    )
    shard = NamedSharding(mesh8, P(None, "data"))
    got = f(*(jax.device_put(t, shard) for t in (qg, kg, vg)))
    want = full_attention_reference(qg, kg, vg, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_2d_grid_backward_matches_reference(causal, monkeypatch):
    """The long-context 2-D-grid backward kernels (both sides streamed
    in blocks, outputs accumulated across grid revisits — the path that
    removes the full-sequence VMEM residency at T >= _BWD_2D_MIN_T)
    must produce the SAME gradients as AD of the dense oracle. Forced
    on at small T by lowering the threshold; ragged sizes exercise the
    padded-tail and causal-skip masking."""
    import theanompi_tpu.ops.pallas_attention as pa

    monkeypatch.setattr(pa, "_BWD_2D_MIN_T", 1)
    q, k, v = qkv((2, 48, 2, 24), seed=11)

    def loss(f):
        return lambda q, k, v: jnp.sum(
            jnp.sin(f(q, k, v)) * (1.0 + jnp.arange(24))
        )

    gf = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=16, block_k=16)),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        loss(lambda q, k, v: full_attention_reference(q, k, v, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=1e-4,
            err_msg=f"2d d{name} mismatch (causal={causal})",
        )
