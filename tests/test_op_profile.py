"""tools/op_profile.py — per-op TPU time tables from profiler traces
(≙ the SURVEY.md §5.1 "comm/compute split from the XLA profile" clause).
The parser is tested against a synthetic trace-viewer dump (device op
track, container while-op, numbered instances); the CPU path (no device
track) must degrade gracefully — real per-op tables need TPU captures."""

import gzip
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from theanompi_tpu.tools.op_profile import (
    format_table,
    generalize,
    op_table,
)


def _write_trace(tmp_path, events):
    d = tmp_path / "plugins" / "profile" / "2026_01_01"
    d.mkdir(parents=True)
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


def _meta(pid, pname, tid, tname):
    return [
        {"ph": "M", "pid": pid, "name": "process_name", "args": {"name": pname}},
        {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
         "args": {"name": tname}},
    ]


def _dev_op(name, ts, dur, pid=3, tid=9):
    return {"ph": "X", "pid": pid, "tid": tid, "name": name, "ts": ts, "dur": dur}


def test_generalize_collapses_instance_numbers():
    assert generalize("convert_reduce_fusion.307") == "convert_reduce_fusion.#"
    assert generalize("fusion.12.remat2") == "fusion.#.remat#"
    assert generalize("while") == "while"


def test_op_table_aggregates_and_drops_container(tmp_path):
    events = _meta(3, "/device:TPU:0", 9, "XLA Ops")
    events += _meta(7, "/host:CPU", 1, "python")
    # container while op spanning the whole window
    events.append(_dev_op("while.1", ts=0, dur=1000))
    # two instances of the same generalized op + one other
    events.append(_dev_op("conv_fusion.1", ts=0, dur=600))
    events.append(_dev_op("conv_fusion.2", ts=600, dur=200))
    events.append(_dev_op("reduce.9", ts=800, dur=200))
    # host events must be ignored even with big durations
    events.append(_dev_op("python_overhead", ts=0, dur=99999, pid=7, tid=1))
    trace = _write_trace(tmp_path, events)

    rows = op_table(trace, steps=2)
    ops = {r["op"]: r for r in rows}
    assert "while.#" not in ops, "container op must be dropped"
    assert "python_overhead" not in ops, "host track must be ignored"
    assert set(ops) == {"conv_fusion.#", "reduce.#"}
    # 800us conv over 2 steps = 0.4 ms/step, 2 instances over 2 steps = 1/step
    assert ops["conv_fusion.#"]["ms_per_step"] == pytest.approx(0.4)
    assert ops["conv_fusion.#"]["count_per_step"] == pytest.approx(1.0)
    assert ops["conv_fusion.#"]["share"] == pytest.approx(0.8)
    assert rows[0]["op"] == "conv_fusion.#", "rows sorted by time"
    txt = format_table(rows)
    assert "conv_fusion.#" in txt and "80.0%" in txt


def test_op_table_keeps_legit_dominant_op(tmp_path):
    """An op that is 70% of the step but NOT window-spanning per instance
    must survive the container filter."""
    events = _meta(3, "/device:TPU:0", 9, "XLA Ops")
    for i in range(10):
        events.append(_dev_op(f"big_fusion.{i}", ts=100 * i, dur=70))
        events.append(_dev_op(f"small.{i}", ts=100 * i + 70, dur=30))
    trace = _write_trace(tmp_path, events)
    rows = op_table(trace, steps=10)
    ops = {r["op"]: r for r in rows}
    assert ops["big_fusion.#"]["share"] == pytest.approx(0.7)


def test_op_table_keeps_window_spanning_megakernel(tmp_path):
    """A single instance spanning 90% of a one-step window is NOT a
    container when the remaining ops cannot account for the window
    (a wrapper's children fill it; a megakernel leaves it empty)."""
    events = _meta(3, "/device:TPU:0", 9, "XLA Ops")
    events.append(_dev_op("mega_fusion.1", ts=0, dur=900))
    events.append(_dev_op("small.1", ts=900, dur=100))
    trace = _write_trace(tmp_path, events)
    ops = {r["op"]: r for r in op_table(trace, steps=1)}
    assert ops["mega_fusion.#"]["share"] == pytest.approx(0.9)


def test_op_table_uses_one_device_on_multichip_traces(tmp_path):
    """A multi-chip trace carries the same SPMD ops once per
    '/device:TPU:n' process; summing across them would inflate
    ms_per_step by the device count — the table must use ONE device."""
    events = []
    for pid in (3, 4):  # two devices
        events += _meta(pid, f"/device:TPU:{pid - 3}", 9, "XLA Ops")
        events.append(_dev_op("conv_fusion.1", ts=0, dur=600, pid=pid))
        events.append(_dev_op("reduce.2", ts=600, dur=400, pid=pid))
    trace = _write_trace(tmp_path, events)
    rows = op_table(trace, steps=1)
    ops = {r["op"]: r for r in rows}
    assert ops["conv_fusion.#"]["ms_per_step"] == pytest.approx(0.6)
    assert ops["conv_fusion.#"]["count_per_step"] == pytest.approx(1.0)


def test_cpu_capture_degrades_gracefully(tmp_path):
    """A REAL CPU-backend capture has no device 'XLA Ops' track: the
    table is empty and format_table says why instead of crashing."""
    f = jax.jit(lambda x: jnp.sin(x) @ x.T)
    x = jnp.ones((64, 64))
    np.asarray(f(x))
    d = str(tmp_path / "trace")
    jax.profiler.start_trace(d)
    np.asarray(f(x))
    jax.profiler.stop_trace()
    rows = op_table(d)
    assert rows == []
    assert "CPU-only" in format_table(rows)


def test_missing_trace_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="trace.json.gz"):
        op_table(str(tmp_path))


def test_checked_in_fixture_parses():
    """The committed synthetic trace fixture (tests/fixtures/
    op_profile_trace/ — also the attribution join's input,
    tests/test_attribution.py) parses stably: container dropped, host
    track ignored, instance numbers collapsed, shares summing to 1."""
    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "op_profile_trace")
    rows = op_table(fixture, steps=4)
    ops = {r["op"]: r for r in rows}
    assert set(ops) == {"conv_fusion.#", "convert_reduce_fusion.#",
                        "all-reduce.#"}
    assert "while.#" not in ops and "python_overhead" not in ops
    assert sum(r["share"] for r in rows) == pytest.approx(1.0)
    assert generalize("all-reduce.3") == "all-reduce.#"
    assert "all-reduce.#" in format_table(rows)
