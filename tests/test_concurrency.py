"""Host-concurrency race analyzer (tools/analyze/concurrency.py,
ISSUE 14): thread-model discovery, the RACE001–005 rule family,
mutation self-tests (one seeded defect per rule, each caught by its
rule ID), and the clean-tree zero-findings gate.

The defects seeded here are the exact classes the analyzer exists
for — the classes every release so far shipped one of by hand: a
shared counter with no lock, a sink guarded at some write sites and
bare at others (the PR-13 metrics.jsonl lock, removed), two locks
taken in opposite orders, exists-then-unlink racing the prune/scrubber
threads, and a two-field publish a locked reader can see torn.
"""

import os
import textwrap

import pytest

from theanompi_tpu.tools.analyze import concurrency as C

FIXTURE = "/fixture/threaded.py"


def _findings(snippet: str):
    src = "import threading, os, queue\n" + textwrap.dedent(snippet)
    # check_golden=False: adding a fixture file IS a thread-model
    # change — the golden gate is exercised by its own tests below
    return C.concurrency_findings({FIXTURE: src}, check_golden=False)


def _rules(snippet: str):
    return [f.rule for f in _findings(snippet)]


# --------------------------------------------------------------------------
# clean tree + thread model
# --------------------------------------------------------------------------


def test_clean_tree_has_zero_findings():
    """The committed tree is race-lint clean — the ISSUE 14 satellite:
    every true positive the analyzer found (metrics-sink writes bare in
    set_traffic_model/note_reshard, unserialized scrubber passes, the
    layout-sidecar exists-then-remove) was FIXED, not exempted."""
    fs = C.concurrency_findings()
    assert fs == [], [(f.rule, f.path, f.line, f.message) for f in fs]


def test_thread_inventory_discovers_the_host_thread_model():
    """The discovered spawn inventory covers the real thread model —
    the same roles the watchdog's stacks.txt groups by."""
    inv = C.thread_inventory()
    roles = {s["role"] for s in inv}
    targets = {s["target"] for s in inv}
    assert "tmpi-serve-batcher" in roles
    assert "tmpi-serve-reload" in roles
    assert "tmpi-ckpt-scrub" in roles
    assert "tmpi-heartbeat-r" in roles       # f-string prefix
    assert "tmpi-stall-watchdog-r" in roles
    assert "http" in roles                   # ThreadingHTTPServer handlers
    assert "ServeEngine._loop" in targets
    assert "CheckpointReloader._loop" in targets
    assert "CheckpointScrubber._loop" in targets
    # the AsyncCheckpointer pool submit is a thread context too
    assert any(s["target"] == "save_checkpoint" for s in inv)


def test_contexts_propagate_through_callbacks_and_receivers():
    """The load-bearing propagation: the scrubber's on_result callback
    registration puts Observability.note_scrub on the scrubber thread,
    the reload poller's engine calls put ServeEngine.set_params on the
    reload thread, and obs_span puts SpanRecorder.finish on the
    prefetch producer and the checkpoint writer pool."""
    m = C.build_model()

    def ctx(cls, meth):
        return m.classes[cls].methods[meth].contexts

    assert "tmpi-ckpt-scrub" in ctx("Observability", "note_scrub")
    assert "tmpi-serve-reload" in ctx("ServeEngine", "set_params")
    assert "http" in ctx("ServeEngine", "submit")
    assert "tmpi-serve-batcher" in ctx("ServeEngine", "_serve_batch")
    assert "caller" not in ctx("ServeEngine", "_serve_batch")
    assert "tmpi-stall-watchdog-r" in ctx("FlightRecorder", "dump")
    spans = ctx("SpanRecorder", "finish")
    assert "tmpi-prefetch" in spans
    assert any("pool" in c for c in spans)


# --------------------------------------------------------------------------
# RACE001 — unguarded shared write
# --------------------------------------------------------------------------

RACY = """
class Racey:
    def __init__(self):
        self._n = 0
        self._thread = threading.Thread(
            target=self._run, name="tmpi-fix", daemon=True)

    def _run(self):
        self._n += 1

    def bump(self):
        self._n += 1
"""


def test_race001_unguarded_shared_write():
    fs = _findings(RACY)
    assert [f.rule for f in fs] == ["RACE001"]
    assert "_n" in fs[0].message and "tmpi-fix" in fs[0].message


def test_race001_single_context_writes_are_not_flagged():
    assert _rules("""
    class SingleWriter:
        def __init__(self):
            self._n = 0
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            while True:
                self._n += 1   # only the worker writes; readers are free

        def value(self):
            return self._n
    """) == []


def test_race001_init_writes_and_safe_types_exempt():
    assert _rules("""
    class Safe:
        def __init__(self):
            self._q = queue.Queue(4)
            self._stop = threading.Event()
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            self._stop.set()          # Event: internally synchronized
            self._q.put(1)

        def close(self):
            self._stop.set()
    """) == []


def test_race001_locked_both_sides_is_clean():
    assert _rules("""
    class Guarded:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            with self._lock:
                self._n += 1

        def bump(self):
            with self._lock:
                self._n += 1
    """) == []


# --------------------------------------------------------------------------
# RACE002 — inconsistent guarding
# --------------------------------------------------------------------------

INCONSISTENT = """
class HalfLocked:
    def __init__(self):
        self._lock = threading.Lock()
        self._sink = open(os.devnull, "w")
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self._lock:
            self._sink.write("a")

    def emit(self):
        self._sink.write("b")   # bare: the lock protects nothing
"""


def test_race002_locked_one_context_bare_in_another():
    fs = _findings(INCONSISTENT)
    assert [f.rule for f in fs] == ["RACE002"]
    assert "_sink" in fs[0].message and "BARE" in fs[0].message


def test_race002_nested_lock_holds_share_the_serializing_lock():
    """A write under `with a: with b:` and another under `with a:`
    shares lock a at every site — NOT 'different locks' (review
    regression: the union comparison fired on nested holds)."""
    assert _rules("""
    class Nested:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self.x = 0
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            with self._a:
                with self._b:
                    self.x = 1

        def poke(self):
            with self._a:
                self.x = 2
    """) == []


def test_race002_disjoint_locks_still_flagged():
    assert _rules("""
    class Disjoint:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self.x = 0
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            with self._a:
                self.x = 1

        def poke(self):
            with self._b:
                self.x = 2
    """) == ["RACE002"]


def test_race002_suppression_requires_reason(tmp_path):
    """spmd_exempt with a written reason suppresses a RACE finding
    through tmpi lint's shared mechanics (findings still listed under
    suppressed)."""
    from theanompi_tpu.tools.lint import LintReport, _add

    src = ("import threading, os\n"
           + textwrap.dedent(INCONSISTENT).replace(
               'self._sink.write("b")   # bare: the lock protects nothing',
               'self._sink.write("b")  # spmd_exempt: single-threaded '
               'in this deployment'))
    p = tmp_path / "half_locked.py"
    p.write_text(src)
    fs = C.concurrency_findings({str(p): src}, check_golden=False)
    assert [f.rule for f in fs] == ["RACE002"]
    report = LintReport()
    _add(report, fs[0].rule, str(p), fs[0].line, fs[0].message)
    assert report.findings == [] and len(report.suppressed) == 1


# --------------------------------------------------------------------------
# RACE003 — lock-order inversion
# --------------------------------------------------------------------------


def test_race003_lock_order_inversion():
    rules = _rules("""
    class TwoLocks:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self.x = 0
            self.y = 0
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            with self._a:
                with self._b:
                    self.x = 1

        def poke(self):
            with self._b:
                with self._a:
                    self.y = 1
    """)
    assert "RACE003" in rules


def test_race003_consistent_order_is_clean():
    assert "RACE003" not in _rules("""
    class OneOrder:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self.x = 0
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            with self._a:
                with self._b:
                    self.x = 1

        def poke(self):
            with self._a:
                with self._b:
                    self.x = 2
    """)


def test_race003_sees_one_call_deep():
    rules = _rules("""
    class NestedCall:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self.x = 0
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _grab_b(self):
            with self._b:
                self.x = 1

        def _run(self):
            with self._a:
                self._grab_b()      # a -> b through the call

        def poke(self):
            with self._b:
                with self._a:       # b -> a directly
                    self.x = 2
    """)
    assert "RACE003" in rules


# --------------------------------------------------------------------------
# RACE004 — filesystem TOCTOU
# --------------------------------------------------------------------------


def test_race004_exists_then_unlink_bare():
    fs = _findings("""
    def cleanup(d):
        p = os.path.join(d, "x.npz")
        if os.path.exists(p):
            os.unlink(p)
    """)
    assert [f.rule for f in fs] == ["RACE004"]
    assert "unlink" in fs[0].message


def test_race004_try_guard_is_the_fix():
    assert _rules("""
    def cleanup(d):
        p = os.path.join(d, "x.npz")
        if os.path.exists(p):
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass
    """) == []


def test_race004_else_branch_is_not_gated_by_the_check():
    """A sink in the else/elif of an exists-check runs when the check
    was FALSE — not a TOCTOU on it (review regression: orelse was
    scanned as if gated)."""
    assert _rules("""
    def f(p):
        if os.path.exists(p):
            return None
        else:
            open(p, "w")

    def g(p, q):
        if os.path.exists(p):
            return 1
        elif q:
            open(p)
    """) == []


def test_race004_cleanup_inside_except_handler_exempt():
    """The _atomic_savez pattern: exists-then-unlink of a private tmp
    inside an except handler is a cleanup of an already-failed write,
    not a cross-thread race."""
    assert _rules("""
    def save(d, tmp):
        try:
            os.replace(tmp, d)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
    """) == []


# --------------------------------------------------------------------------
# RACE005 — non-atomic multi-field publish
# --------------------------------------------------------------------------

TORN = """
class TornPublish:
    def __init__(self):
        self._lock = threading.Lock()
        self.params = None
        self.step = -1
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            with self._lock:
                pair = (self.params, self.step)

    def publish(self, p, s):
        self.params = p
        self.step = s
"""


def test_race005_bare_pair_publish_vs_locked_reader():
    fs = _findings(TORN)
    assert [f.rule for f in fs] == ["RACE005"]
    assert "params" in fs[0].message and "step" in fs[0].message


def test_race005_locked_publish_is_clean():
    assert _rules(TORN.replace(
        """    def publish(self, p, s):
        self.params = p
        self.step = s""",
        """    def publish(self, p, s):
        with self._lock:
            self.params = p
            self.step = s""")) == []


# --------------------------------------------------------------------------
# mutation self-tests on the REAL tree (ISSUE 14 acceptance, static
# half — the dynamic half is tests/test_stress.py)
# --------------------------------------------------------------------------

_OBS_PATH = [p for p in C.CONCURRENCY_FILES
             if p.endswith(os.path.join("obs", "__init__.py"))][0]

_LOCKED_SCRUB_BLOCK = '''        if self._metrics_f is not None and not self._closed:
            with self._metrics_lock:
                if not self._closed:
                    self._metrics_f.write(_json.dumps(line) + "\\n")
                    self._metrics_f.flush()'''

_BARE_SCRUB_BLOCK = '''        if self._metrics_f is not None and not self._closed:
            self._metrics_f.write(_json.dumps(line) + "\\n")
            self._metrics_f.flush()'''


def test_mutation_dropped_metrics_lock_caught_static():
    """Remove the PR-13 metrics.jsonl lock from note_scrub (the exact
    seeded defect of the ISSUE 14 acceptance): the analyzer must flag
    the now-bare sink writes as RACE002 — _metrics_f stays locked at
    every OTHER write site, so the inconsistency is the signal."""
    src = open(_OBS_PATH).read()
    assert _LOCKED_SCRUB_BLOCK in src, (
        "note_scrub's locked sink block moved — update the mutation")
    mutated = src.replace(_LOCKED_SCRUB_BLOCK, _BARE_SCRUB_BLOCK, 1)
    fs = C.concurrency_findings({_OBS_PATH: mutated})
    assert any(f.rule == "RACE002" and "_metrics_f" in f.message
               for f in fs), [(f.rule, f.message) for f in fs]


def test_mutation_dropped_scrubber_pass_lock_caught():
    """Remove the scrubber's pass lock (this PR's own fix): scrub_once
    is reachable from both the background loop and public callers, so
    its counter/memo writes go RACE001."""
    path = [p for p in C.CONCURRENCY_FILES
            if p.endswith(os.path.join("utils", "checkpoint.py"))][0]
    src = open(path).read()
    needle = "        with self._pass_lock:\n"
    assert needle in src
    # drop the with and dedent its body one level (stop at the first
    # line that falls back out of the block)
    lines = src.splitlines(keepends=True)
    i = lines.index(needle)
    out = lines[:i]
    j = i + 1
    while j < len(lines):
        ln = lines[j]
        if ln.strip() == "":
            out.append(ln)
        elif ln.startswith("            "):
            out.append(ln.replace("    ", "", 1))
        else:
            break
        j += 1
    out.extend(lines[j:])
    mutated = "".join(out)
    fs = C.concurrency_findings({path: mutated})
    assert any(f.rule == "RACE001" and "CheckpointScrubber" in f.message
               for f in fs), [(f.rule, f.message) for f in fs]


def test_mutation_unnamed_serve_drain_thread_caught_by_golden():
    """Dropping the tmpi-serve-drain name must not lose the spawn from
    the inventory (attribution degrades, discovery must not) — and the
    now-nameless spawn drifts the thread-model golden (RACE101), so it
    cannot land unreviewed."""
    path = [p for p in C.CONCURRENCY_FILES
            if p.endswith(os.path.join("serve", "cli.py"))][0]
    src = open(path).read()
    assert 'name="tmpi-serve-drain", ' in src
    mutated = src.replace('name="tmpi-serve-drain", ', "", 1)
    m = C.build_model({path: mutated})
    assert any("_drain_then_stop" in s.target for s in m.spawns)
    named = [s for s in C.build_model().spawns
             if "_drain_then_stop" in s.target]
    assert named and named[0].named and named[0].role == "tmpi-serve-drain"
    fs = C.concurrency_findings({path: mutated})
    assert any(f.rule == "RACE101" for f in fs), \
        [(f.rule, f.message) for f in fs]


def test_thread_model_golden_matches_and_regenerates(tmp_path,
                                                     monkeypatch):
    """The committed golden matches the discovered model; a divergent
    golden is RACE101; --update-golden rewrites it."""
    import json

    m = C.build_model()
    assert C.check_thread_model_golden(m) == []
    fake = tmp_path / "thread_model.json"
    monkeypatch.setattr(C, "GOLDEN_THREAD_MODEL", str(fake))
    fs = C.check_thread_model_golden(m)
    assert [f.rule for f in fs] == ["RACE101"]          # missing
    assert C.check_thread_model_golden(m, update=True) == []
    assert C.check_thread_model_golden(m) == []          # regenerated
    stored = json.loads(fake.read_text())
    stored[0]["role"] = "renamed"
    fake.write_text(json.dumps(stored))
    fs = C.check_thread_model_golden(m)
    assert [f.rule for f in fs] == ["RACE101"]
    assert "changed" in fs[0].message


# --------------------------------------------------------------------------
# lint integration
# --------------------------------------------------------------------------


def test_lint_rules_include_race_family():
    from theanompi_tpu.tools.lint import RULES

    for rule in ("RACE001", "RACE002", "RACE003", "RACE004", "RACE005",
                 "RACE101"):
        assert rule in RULES
