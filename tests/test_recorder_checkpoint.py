"""Recorder + checkpoint subsystem tests (SURVEY.md §5.1, §5.4)."""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tinymodel import TinyCNN
from theanompi_tpu.train import TrainState, init_train_state, make_train_step
from theanompi_tpu.utils import (
    Recorder,
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
)


def _state():
    model = TinyCNN(
        TinyCNN.default_recipe().replace(batch_size=8, input_shape=(16, 16, 3))
    )
    return model, init_train_state(model, jax.random.PRNGKey(0))


# -- recorder ---------------------------------------------------------------


def test_recorder_brackets_and_history(tmp_path):
    rec = Recorder(save_dir=str(tmp_path), run_name="t", print_freq=0)
    rec.start("step")
    time.sleep(0.01)
    dt = rec.end("step")
    assert dt >= 0.01
    rec.train_metrics(1, {"loss": 1.5, "error": 0.7}, n_images=32)
    rec.val_metrics(0, {"loss": 1.2, "error": 0.5, "top5_error": 0.1})
    rec.start_epoch()
    rec.end_epoch(0, n_images=320)
    rec.save()
    rec.close()

    jsonl = (tmp_path / "t.jsonl").read_text().strip().splitlines()
    kinds = [json.loads(l)["kind"] for l in jsonl]
    assert kinds == ["train", "val", "epoch"]
    assert json.loads(jsonl[0])["images_per_sec"] > 0

    hist = Recorder.load_history(str(tmp_path / "t_history.pkl"))
    assert hist["history"]["train"][0]["loss"] == 1.5


def test_recorder_sync_blocks_on_device_value():
    rec = Recorder(print_freq=0)
    rec.start("step")
    x = jnp.ones((256, 256)) @ jnp.ones((256, 256))
    rec.end("step", sync=x)
    assert rec.mean_time("step") > 0


# -- checkpoint -------------------------------------------------------------


def test_checkpoint_roundtrip_and_resume(tmp_path):
    model, state = _state()
    step_fn = jax.jit(make_train_step(model))
    x = jnp.zeros(model.input_shape)
    y = jnp.zeros((8,), jnp.int32)
    state, _ = step_fn(state, x, y, jax.random.PRNGKey(1))

    path = save_checkpoint(str(tmp_path), state, int(state.step), rng=jax.random.PRNGKey(7))
    assert path and os.path.exists(path)

    _, template = _state()
    restored, rng = load_checkpoint(path, template)
    assert int(restored.step) == 1
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(rng)), np.asarray(jax.random.PRNGKey(7))
    )
    for a, b in zip(jax.tree_util.tree_leaves(restored), jax.tree_util.tree_leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # training continues from the restored state
    restored = jax.tree_util.tree_map(jnp.asarray, restored)
    s2, _ = step_fn(TrainState(*restored), x, y, jax.random.PRNGKey(2))
    assert int(s2.step) == 2


def test_checkpoint_extra_meta_roundtrip(tmp_path):
    """extra_meta embeds in the file itself (not a sidecar): the
    pipeline stack layout must survive copying ckpt_*.npz alone."""
    from theanompi_tpu.utils.checkpoint import (
        read_checkpoint_meta,
        save_checkpoint_sharded,
    )

    _, state = _state()
    meta = {"pipeline_layout": {"interleave": 2, "n_stages": 4}}
    path = save_checkpoint(str(tmp_path), state, 3, extra_meta=meta)
    assert read_checkpoint_meta(path) == meta
    # plain save without meta: empty dict, not an error
    path2 = save_checkpoint(str(tmp_path), state, 4)
    assert read_checkpoint_meta(path2) == {}
    # the state itself still loads (the __usermeta__ key is not a leaf)
    _, template = _state()
    restored, _ = load_checkpoint(path, template)
    assert int(restored.step) == int(state.step)
    # sharded format carries it too
    spath = save_checkpoint_sharded(
        str(tmp_path / "sh"), state, 5, extra_meta=meta
    )
    assert read_checkpoint_meta(spath) == meta


def test_checkpoint_prune_and_latest(tmp_path):
    _, state = _state()
    for s in (1, 2, 3, 4):
        save_checkpoint(str(tmp_path), state, s, keep=2)
    files = sorted(os.listdir(tmp_path))
    assert files == ["ckpt_3.npz", "ckpt_4.npz"]
    assert latest_checkpoint(str(tmp_path)).endswith("ckpt_4.npz")
    assert latest_checkpoint(str(tmp_path / "nope")) is None


def test_checkpoint_structure_mismatch_raises(tmp_path):
    _, state = _state()
    path = save_checkpoint(str(tmp_path), {"a": state.params}, 1)
    with pytest.raises(KeyError):
        load_checkpoint(path, {"b": state.params})


def test_checkpoint_shape_mismatch_raises(tmp_path):
    path = save_checkpoint(str(tmp_path), {"w": jnp.zeros((3, 3))}, 1)
    with pytest.raises(ValueError):
        load_checkpoint(path, {"w": jnp.zeros((4, 3))})


def test_checkpoint_rng_cross_impl_resume(tmp_path):
    """A checkpoint's rng must resume under a DIFFERENT default PRNG impl
    than the one that wrote it: the package defaults to rbg (width-4 key
    data) but pre-rbg checkpoints carry threefry width-2 keys."""
    from theanompi_tpu.utils import wrap_saved_rng

    _, state = _state()
    # legacy checkpoint: raw threefry key data, as written before the
    # rbg default existed
    legacy_dir = str(tmp_path / "legacy")
    legacy = save_checkpoint(legacy_dir, state, 1, rng=np.array([7, 9], np.uint32))
    # raw width-2 data under the rbg default: save must NOT stamp 'rbg'
    # (the width contradicts it) — impl is inferred from width
    assert str(np.load(legacy)["__rng_impl__"]) == "threefry2x32"
    # simulate a pre-impl-tracking checkpoint: strip __rng_impl__
    data = dict(np.load(legacy))
    del data["__rng_impl__"]
    np.savez(legacy, **data)
    _, key = load_checkpoint(legacy, state)
    assert str(jax.random.key_impl(key)) == "threefry2x32"  # width-inferred
    a, b = jax.random.split(key)  # would raise under the rbg default pre-fix
    assert not np.array_equal(jax.random.key_data(a), jax.random.key_data(b))

    # current-impl round trip, including a TYPED key through save: the
    # stored impl name (not width) drives the wrap, so unsafe_rbg
    # (width 4, same as rbg) survives exactly
    cur = jax.random.key(3, impl="unsafe_rbg")
    path = save_checkpoint(str(tmp_path / "cur"), state, 2, rng=cur)
    _, key2 = load_checkpoint(path, state)
    assert str(jax.random.key_impl(key2)) == "unsafe_rbg"
    np.testing.assert_array_equal(
        jax.random.key_data(key2), jax.random.key_data(cur)
    )
    jax.random.split(key2)

    with pytest.raises(ValueError, match="key-data shape"):
        wrap_saved_rng(np.zeros((3,), np.uint32))


def test_async_checkpointer_matches_sync(tmp_path):
    """AsyncCheckpointer produces the identical artifact as the
    synchronous save (bit-equal leaves, same filename/prune behavior),
    with durability guaranteed after wait()/close()."""
    from theanompi_tpu.utils.checkpoint import AsyncCheckpointer

    model, state = _state()
    sync_path = save_checkpoint(str(tmp_path / "sync"), state, 5,
                                rng=jax.random.PRNGKey(3))
    w = AsyncCheckpointer()
    try:
        w.save(str(tmp_path / "async"), state, 5, rng=jax.random.PRNGKey(3))
        w.wait()
    finally:
        w.close()
    async_path = latest_checkpoint(str(tmp_path / "async"))
    assert os.path.basename(async_path) == os.path.basename(sync_path)
    a = np.load(async_path)
    b = np.load(sync_path)
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])


def test_async_checkpointer_orders_and_prunes(tmp_path):
    """Back-to-back saves land in step order and prune to keep."""
    from theanompi_tpu.utils.checkpoint import AsyncCheckpointer

    _, state = _state()
    w = AsyncCheckpointer()
    try:
        for s in (1, 2, 3, 4, 5):
            w.save(str(tmp_path), state, s, keep=2)
    finally:
        w.close()
    names = sorted(f for f in os.listdir(tmp_path) if f.startswith("ckpt_"))
    assert names == ["ckpt_4.npz", "ckpt_5.npz"]


def test_async_checkpointer_survives_buffer_donation(tmp_path):
    """REGRESSION: every multi-device engine donates its state buffers
    into the next step (donate_argnums=(0,)), which marks them deleted
    the moment the step is dispatched. save() must therefore snapshot
    (device-side copy) BEFORE returning — otherwise the background
    device_get races the next dispatch and dies with 'Array has been
    deleted'."""
    import time as _time

    from theanompi_tpu.utils.checkpoint import AsyncCheckpointer

    x = jnp.arange(512.0)
    donating = jax.jit(lambda a: a * 2.0, donate_argnums=(0,))
    w = AsyncCheckpointer()
    try:
        w.save(str(tmp_path), {"x": x}, 1)
        _ = donating(x)  # donates/deletes x's buffer immediately
        _time.sleep(0.05)  # give the worker thread time to hit the pull
        w.wait()  # must NOT raise
    finally:
        w.close()
    restored, _ = load_checkpoint(
        latest_checkpoint(str(tmp_path)), {"x": jnp.zeros((512,))}
    )
    np.testing.assert_array_equal(restored["x"], np.arange(512.0))


def test_async_checkpointer_surfaces_worker_errors(tmp_path):
    """A failed background write must NOT vanish: it re-raises on the
    next wait()/close() (the driver drains in its finally, so an epoch
    whose checkpoint failed cannot return a success summary)."""
    from theanompi_tpu.utils.checkpoint import AsyncCheckpointer

    _, state = _state()
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file where the ckpt dir should go")
    w = AsyncCheckpointer()
    try:
        w.save(str(blocker), state, 1)  # submit succeeds...
        with pytest.raises((NotADirectoryError, FileExistsError, OSError)):
            w.wait()  # ...the failure surfaces here
    finally:
        w.close()


def test_run_training_async_checkpoint_resume(tmp_path):
    """run_training's default async path writes a resumable checkpoint
    that the sync loader restores exactly (driver-level integration)."""
    from theanompi_tpu.launch.worker import run_training
    from tinymodel import TinyCNN

    kw = dict(
        rule="bsp",
        model_cls=TinyCNN,
        devices=1,
        dataset="synthetic",
        dataset_kwargs={"n_train": 32, "n_val": 16, "image_shape": [16, 16, 3]},
        recipe_overrides={"batch_size": 8, "input_shape": (16, 16, 3)},
        print_freq=0,
        ckpt_dir=str(tmp_path / "ck"),
    )
    out1 = run_training(n_epochs=1, **kw)
    p = latest_checkpoint(str(tmp_path / "ck"))
    assert p is not None and out1["steps"] == 4
    out2 = run_training(n_epochs=2, resume=True, **kw)
    assert out2["steps"] == 8  # continued, not restarted


def test_recorder_tensorboard_scalars(tmp_path):
    """tensorboard=True writes event files next to the JSONL (soft
    dependency)."""
    pytest.importorskip("tensorboardX")
    rec = Recorder(print_freq=0, save_dir=str(tmp_path), run_name="tbrun",
                   tensorboard=True)
    rec.start("step"); time.sleep(0.01); rec.end("step")
    rec.train_metrics(1, {"loss": 1.5, "error": 0.5}, n_images=8)
    rec.val_metrics(0, {"loss": 1.2, "error": 0.4})
    rec.close()
    tb_dir = tmp_path / "tb" / "tbrun_rank0"
    events = list(tb_dir.glob("events.out.tfevents.*"))
    assert events and events[0].stat().st_size > 0
    # JSONL remains the source of truth alongside
    assert (tmp_path / "tbrun.jsonl").exists()
