"""Sequence-parallel transformer LM: long-context training over a
('seq',) mesh with ring attention (beyond-parity extension; SURVEY.md
§5.7 design note made real)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from theanompi_tpu.models.transformer import (
    SEQ_AXIS,
    TransformerLM,
    make_sp_train_step,
)
from jax.sharding import NamedSharding

from theanompi_tpu.parallel import make_mesh


def _batches(n_batches, B, T, vocab, seed=0):
    """Bigram-learnable data: token[i+1] = (token[i] + 1) % vocab."""
    r = np.random.RandomState(seed)
    for _ in range(n_batches):
        start = r.randint(0, vocab, (B, 1))
        yield (start + np.arange(T)[None]) % vocab


def test_sp_loss_matches_single_device():
    """The sharded global-mean loss (boundary targets fetched via
    ppermute) must equal the plain single-device next-token loss."""
    model = TransformerLM(vocab=32, d_model=32, n_heads=2, n_layers=1, d_ff=64,
                          max_len=64)
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(next(_batches(1, 2, 32, 32)), jnp.int32)

    mesh4 = make_mesh(4, axis_names=(SEQ_AXIS,))
    loss4 = jax.jit(
        jax.shard_map(
            lambda p, t: model.loss(p, t), mesh=mesh4,
            in_specs=(P(), P(None, SEQ_AXIS)), out_specs=P(),
            check_vma=False,
        )
    )(params, toks)

    mesh1 = make_mesh(1, axis_names=(SEQ_AXIS,))
    loss1 = jax.jit(
        jax.shard_map(
            lambda p, t: model.loss(p, t), mesh=mesh1,
            in_specs=(P(), P(None, SEQ_AXIS)), out_specs=P(),
            check_vma=False,
        )
    )(params, toks)
    np.testing.assert_allclose(float(loss4), float(loss1), rtol=2e-5)


@pytest.mark.slow
def test_sp_training_learns():
    """120 Adam steps on the bigram task over an 8-way seq mesh must
    drive the loss well below chance (ln(32) ~ 3.47) — gradients flow
    through ring attention, the boundary ppermute, and the seq-axis
    psum. (Adam rather than plain SGD: with correctly mesh-invariant
    gradient scaling, SGD's plateau-escape on this task is too
    init-stream-sensitive for a deterministic assertion.)"""
    from theanompi_tpu.models.transformer import make_nd_train_step
    from theanompi_tpu.ops.optimizers import get_optimizer

    vocab = 32
    model = TransformerLM(vocab=vocab, d_model=64, n_heads=4, n_layers=2,
                          d_ff=128, max_len=128)
    mesh = make_mesh(8, axis_names=(SEQ_AXIS,))
    step = make_nd_train_step(model, mesh, lr=3e-3, sp_axis=SEQ_AXIS,
                              optimizer="adam")
    params = model.init(jax.random.PRNGKey(1))
    state = (params, get_optimizer("adam").init(params))

    first = last = None
    sharding = NamedSharding(mesh, P(None, SEQ_AXIS))  # dim 1 = sequence
    for i, tb in enumerate(_batches(120, 4, 64, vocab, seed=2)):
        toks = jax.device_put(jnp.asarray(tb, jnp.int32), sharding)
        state, loss = step(state, toks)
        if first is None:
            first = float(loss)
        last = float(loss)
    assert first > 2.0, f"initial loss {first} suspiciously low"
    assert last < 0.7, f"SP training failed to learn: {first} -> {last}"


@pytest.mark.slow
def test_remat_step_matches_plain():
    """remat=True (per-block jax.checkpoint) must be a pure memory/FLOPs
    trade: identical loss and updated params, through the full SP step
    (collectives replayed in the recomputation)."""
    from theanompi_tpu.models.transformer import make_nd_train_step

    base = dict(vocab=32, d_model=32, n_heads=2, n_layers=2, d_ff=64, max_len=64)
    mesh = make_mesh(4, axis_names=(SEQ_AXIS,))
    toks = jnp.asarray(next(_batches(1, 2, 32, 32, seed=7)), jnp.int32)

    results = []
    for remat in (False, True):
        model = TransformerLM(**base, remat=remat)
        params = model.init(jax.random.PRNGKey(3))
        step = make_nd_train_step(model, mesh, lr=0.05, sp_axis=SEQ_AXIS)
        results.append(step(params, toks))

    (p0, l0), (p1, l1) = results
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p0), jax.tree_util.tree_leaves(p1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


@pytest.mark.slow
def test_long_context_8k_tokens():
    """Long-context capability: one train step at T=8192 over the 8-way
    seq mesh (1024 tokens per device) with remat'd blocks — ring
    attention streams K/V, activations stay O(T/n) per device. Asserts
    the step runs, the loss is finite, and a second step changes it."""
    from theanompi_tpu.models.transformer import make_nd_train_step

    T = 8192
    model = TransformerLM(vocab=64, d_model=32, n_heads=2, n_layers=2,
                          d_ff=64, max_len=T, remat=True)
    mesh = make_mesh(8, axis_names=(SEQ_AXIS,))
    step = make_nd_train_step(model, mesh, lr=0.5, sp_axis=SEQ_AXIS)
    params = model.init(jax.random.PRNGKey(0))
    # learnable data (uniform-random tokens are ALREADY at the optimum)
    toks = jax.device_put(
        jnp.asarray(np.arange(T)[None] % 64, jnp.int32),
        NamedSharding(mesh, P(None, SEQ_AXIS)),
    )
    losses = []
    for _ in range(3):
        params, loss = step(params, toks)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_chunked_loss_matches_full():
    """chunked_nll (per-chunk head+CE, logits never fully materialized)
    == the whole-sequence loss, value AND gradients — including under
    sequence parallelism (boundary targets cross chunks AND shards)."""
    import numpy as np

    from theanompi_tpu.models.transformer import TransformerLM

    m = TransformerLM(vocab=32, d_model=32, n_heads=4, n_layers=2,
                      d_ff=64, max_len=64)
    mc = m._replace(loss_chunk=8)
    p = m.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, 32, (2, 32)), jnp.int32
    )
    l0, g0 = jax.value_and_grad(lambda p: m.loss(p, toks, None))(p)
    l1, g1 = jax.value_and_grad(lambda p: mc.loss(p, toks, None))(p)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # bad chunk size fails loudly, not silently wrong
    with pytest.raises(ValueError, match="must divide"):
        m._replace(loss_chunk=7).loss(p, toks, None)


def test_chunked_loss_under_sp():
    """Chunked loss composes with the sequence axis: the sp-sharded
    train step with loss_chunk reproduces the unchunked sp step."""
    import numpy as np

    from jax.sharding import NamedSharding, PartitionSpec as P

    from theanompi_tpu.models.transformer import (
        SEQ_AXIS,
        TransformerLM,
        make_sp_train_step,
    )
    from theanompi_tpu.parallel import make_mesh

    m = TransformerLM(vocab=32, d_model=32, n_heads=4, n_layers=1,
                      d_ff=64, max_len=64)
    p = m.init(jax.random.PRNGKey(1))
    toks = jnp.asarray(
        np.random.RandomState(1).randint(0, 32, (2, 64)), jnp.int32
    )
    mesh = make_mesh(8, axis_names=(SEQ_AXIS,))
    tin = jax.device_put(toks, NamedSharding(mesh, P(None, SEQ_AXIS)))
    _, l0 = make_sp_train_step(m, mesh, lr=0.05)(p, tin)
    _, l1 = make_sp_train_step(m._replace(loss_chunk=4), mesh, lr=0.05)(p, tin)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
